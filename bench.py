"""Benchmark: device-resident chunk+hash throughput vs single-thread CPU.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "MiB/s", "vs_baseline": N}

Method (BASELINE.json north star — chunk + fingerprint MiB/s at identical
dedup output):

* TPU path: corpus segments are synthesized **on device** with the JAX PRNG
  (the dev rig's host<->device relay tunnel is ~6 MiB/s, three orders below
  real PCIe/DMA, so streaming host bytes would measure the tunnel, not the
  kernels).  The timed loop is the production zero-round-trip driver
  (``DevicePipeline.manifest_segments_device``): Mosaic strip scan ->
  on-device parallel cut selection -> class-bucketed gather -> Pallas
  BLAKE3, with only async downloads of cuts+digests.
* CPU baseline: the native C implementation (``native/cdc_blake3.c``) of the
  identical pipeline on ONE host thread — the honest stand-in for the
  reference's fastcdc+blake3 crates; parity vs the spec oracle is asserted
  by tests/test_native.py and re-checked here before timing.  Best of 3
  runs (the shared dev host carries background load).
* Parity gate: an 8 MiB corpus is pushed through BOTH paths bit-for-bit;
  chunk boundaries and digests must match exactly or the benchmark reports
  failure — speed without identical dedup output is meaningless.

Scale: the headline corpus is BENCH_GIB GiB (default 10, BASELINE.md:37)
streamed as 256 MiB segments from a rotating pool of 8 device-resident
random segments; every config then keeps cycling until BENCH_MIN_WALL_S
(default 60 s) of sustained wall clock — sustained windows catch HBM
fragmentation, cache eviction, and pipeline-drain effects that
seconds-long bursts hide.  Environment knobs: BENCH_GIB,
BENCH_SEGMENT_MIB, BENCH_CPU_MIB, BENCH_MIN_WALL_S, BENCH_CONFIGS=0.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _metrics_snapshot() -> dict:
    """Registry state at report time, embedded in every BENCH record so a
    run's counters/histograms (pack stages, retries, faults) ride along
    with the headline number."""
    from backuwup_tpu.obs import metrics as obs_metrics
    return obs_metrics.registry().snapshot()


def _pipeline_report() -> dict:
    """Whole-run pipeline report (obs/profile.py): dispatch counts per
    stage, bytes, padding efficiency.  bench runs in a fresh process, so
    process totals ARE this run — the before/after the round-5
    digest-dispatch merge diffs (PERF.md)."""
    from backuwup_tpu.obs import profile as obs_profile
    return obs_profile.report()


def main() -> None:
    from backuwup_tpu.utils.jaxcache import enable_compilation_cache
    enable_compilation_cache()

    from backuwup_tpu.utils.platform import pin_platform_from_env
    pin_platform_from_env()

    import jax

    # Device-init watchdog: a dead accelerator tunnel makes jax.devices()
    # hang indefinitely; report a JSON failure instead so the caller's
    # run records an honest error.  Covers backend INIT only — compiles
    # can legitimately take minutes and are not under this timeout.
    init_done = threading.Event()
    init_err: list = []

    def _probe():
        try:
            jax.devices()
        except BaseException as e:  # noqa: BLE001 - reported below
            init_err.append(e)
        finally:
            init_done.set()

    threading.Thread(target=_probe, daemon=True).start()
    if not init_done.wait(float(os.environ.get("BENCH_INIT_TIMEOUT_S",
                                               "240"))):
        _cpu_fallback_report()
        return
    if init_err:
        raise init_err[0]  # fast init failure: propagate the real error
    import jax.numpy as jnp
    import numpy as np

    from backuwup_tpu.ops import cdc_cpu
    from backuwup_tpu.ops.blake3_cpu import Blake3Numpy
    from backuwup_tpu.ops.cdc_tpu import _HALO
    from backuwup_tpu.ops.gear import CDCParams
    from backuwup_tpu.ops.pipeline import DevicePipeline

    import bench_configs

    total_gib = float(os.environ.get("BENCH_GIB", "10"))
    seg_mib = bench_configs.segment_mib()
    cpu_mib = int(os.environ.get("BENCH_CPU_MIB", "64"))
    params = CDCParams()  # production 256KiB/1MiB/3MiB
    pipeline = DevicePipeline(params)
    seg_bytes = seg_mib * (1 << 20)
    segments = max(2, int(total_gib * 1024) // seg_mib)

    log(f"devices: {jax.devices()}  fused={pipeline.fused} "
        f"pallas_digest={pipeline.pallas_digest}")

    # --- parity gate -------------------------------------------------------
    rng = np.random.default_rng(1234)
    parity = rng.integers(0, 256, 8 << 20, dtype=np.uint8)
    # tile a block so dedup has real duplicates to find
    parity[4 << 20:6 << 20] = parity[0:2 << 20]
    parity_bytes = parity.tobytes()
    cpu_chunks = cdc_cpu.chunk_stream(parity_bytes, params)
    cpu_digests = Blake3Numpy().digest_batch(
        [parity_bytes[o:o + l] for o, l in cpu_chunks])
    ext = np.concatenate([np.zeros(_HALO, dtype=np.uint8), parity])
    # strict_overflow: an overflow/unresolved row silently re-chunks on the
    # CPU oracle, which would make this gate compare oracle to oracle and
    # pass vacuously exactly when the device path misbehaves.
    (tpu_chunks, tpu_digests), = next(iter(pipeline.manifest_segments_device(
        [(jnp.asarray(ext.reshape(1, -1)),
          np.full(1, len(parity_bytes), dtype=np.int32))],
        strict_overflow=True)))
    tpu_digest_bytes = [bytes(d) for d in tpu_digests]
    if tpu_chunks != cpu_chunks or tpu_digest_bytes != cpu_digests:
        print(json.dumps({"metric": "chunk+hash parity FAILED", "value": 0.0,
                          "unit": "MiB/s", "vs_baseline": 0.0,
                          "metrics": _metrics_snapshot()}))
        return
    dedup = len(set(cpu_digests)) / len(cpu_digests)
    log(f"parity OK: {len(cpu_chunks)} chunks, unique-ratio {dedup:.3f}")

    # --- TPU timing: sustained streaming over the 10 GiB corpus ------------
    key = jax.random.PRNGKey(0)
    row = _HALO + seg_bytes
    nv = np.full(1, seg_bytes, dtype=np.int32)

    @jax.jit
    def synth(key):
        seg = jax.random.randint(key, (seg_bytes,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), seg]
                               ).reshape(1, row)

    # pool of 8 distinct resident segments cycled through the stream (the
    # whole corpus cannot live in HBM at once; per-segment state is nil)
    pool = []
    for _ in range(min(8, segments)):
        key, sub = jax.random.split(key)
        pool.append((synth(sub), nv))
    jax.block_until_ready([b for b, _ in pool])

    # warm every compiled shape out of the timed loop
    list(pipeline.manifest_segments_device(pool[:2], strict_overflow=True))

    # staged-ahead feeder (PERF.md round-5 item 3): keep two upcoming
    # segments committed to device ahead of the consuming driver so any
    # synth/staging DMA rides under manifest compute instead of
    # serializing with it — the upload-side twin of the window's
    # overlapped downloads, same ring discipline as
    # ops/pipeline.manifest_segments_stream.  Resident pool items make
    # device_put a no-op, so the headline device-resident semantics are
    # unchanged; host-built segments (cpu fallback, future host-streamed
    # corpora) get real overlap.
    def _staged_ahead(items, depth=2):
        from collections import deque
        it = iter(items)
        ring = deque()

        def stage_one():
            for buf, nv in it:
                ring.append((jax.device_put(buf), nv))
                return True
            return False

        while True:
            while len(ring) < depth and stage_one():
                pass
            if not ring:
                return
            yield ring.popleft()

    # sustained window: the stated corpus, then keep cycling until the
    # minimum wall clock elapses (sustained numbers catch HBM
    # fragmentation / cache-eviction / pipeline-drain effects that
    # seconds-long bursts hide)
    window = bench_configs.SustainedWindow(segments)
    total_chunks = 0
    for results in pipeline.manifest_segments_device(
            _staged_ahead(window.items(pool)), strict_overflow=True):
        for chunks, _dig in results:
            total_chunks += len(chunks)
    tpu_s = window.wall
    done_segments = window.count
    tpu_mibs = done_segments * seg_mib / tpu_s
    log(f"tpu: {done_segments}x{seg_mib} MiB "
        f"({done_segments*seg_mib/1024:.1f} GiB) "
        f"in {tpu_s:.2f}s = {tpu_mibs:.1f} MiB/s ({total_chunks} chunks)")

    # --- CPU baseline: native C pipeline, single thread, best of 3 ---------
    from backuwup_tpu import native

    host = rng.integers(0, 256, cpu_mib << 20, dtype=np.uint8).tobytes()
    baseline_kind = "native C fastcdc-class+blake3 pipeline, 1 host thread"
    try:
        nat_chunks, nat_digests = native.manifest_native(parity_bytes, params)
        if nat_chunks != cpu_chunks or nat_digests != cpu_digests:
            print(json.dumps({"metric": "native baseline parity FAILED",
                              "value": 0.0, "unit": "MiB/s",
                              "vs_baseline": 0.0,
                              "metrics": _metrics_snapshot()}))
            return
        cpu_s = min(_timed(native.manifest_native, host, params)
                    for _ in range(3))
        cpu_mibs = cpu_mib / cpu_s
        log(f"cpu-native: {cpu_mib} MiB in {cpu_s:.2f}s = {cpu_mibs:.1f}"
            " MiB/s (single thread, best of 3)")
    except native.NativeUnavailable as e:
        # no C compiler on this host: fall back to the numpy oracle as the
        # (much slower) baseline rather than crashing the JSON contract
        log(f"native baseline unavailable ({e}); using numpy oracle")
        baseline_kind = "numpy oracle pipeline, 1 host thread (no C compiler)"
        t0 = time.time()
        chunks = cdc_cpu.chunk_stream(host, params)
        Blake3Numpy().digest_batch([host[o:o + l] for o, l in chunks])
        cpu_s = time.time() - t0
        cpu_mibs = cpu_mib / cpu_s

    # --- BASELINE configs #2-#6 -------------------------------------------
    configs = {}
    if os.environ.get("BENCH_CONFIGS", "1") != "0":
        configs = bench_configs.run_all(pipeline, params, cpu_mibs, log)

    record = {
        "metric": "dedup pipeline chunk+hash throughput (device-resident)",
        "value": round(tpu_mibs, 2),
        "unit": "MiB/s",
        "vs_baseline": round(tpu_mibs / cpu_mibs, 2),
        "baseline": f"{baseline_kind} ({cpu_mibs:.1f} MiB/s)",
        "corpus_gib": round(done_segments * seg_mib / 1024, 2),
        "wall_s": round(tpu_s, 2),
        "configs": configs,
    }
    # config #8 measures serial-vs-concurrent in one run; surface the
    # ratio at top level so BENCH_r*.json diffs track it directly
    transfer = configs.get("8_transfer", {})
    if "speedup" in transfer:
        record["transfer_speedup"] = transfer["speedup"]
    # config #9 is pass/fail: surface the scorecard verdict at top level
    # so a durability regression is one grep away in BENCH_r*.json
    scenario = configs.get("9_scenario", {})
    if "passed" in scenario:
        record["scenario_passed"] = scenario["passed"]
        record["scenario_violation_seconds"] = \
            scenario.get("violation_seconds", 0)
    # config #12 is the coordination-plane scale-out gate: surface the
    # sharded tier's matchmaking throughput and request p99 at top level
    swarm = configs.get("12_swarm", {})
    if "matchmakings_per_s" in swarm:
        record["matchmakings_per_s"] = swarm["matchmakings_per_s"]
        record["server_p99_ms"] = swarm.get("server_p99_ms")
    # config #13 measures serial-vs-multi-source restore in one run;
    # surface both acceptance numbers (wall speedup, bytes-on-wire
    # ratio) at top level so BENCH_r*.json diffs track them directly
    restore = configs.get("13_restore", {})
    if "speedup" in restore:
        record["restore_speedup"] = restore["speedup"]
        record["restore_bytes_ratio"] = restore.get("bytes_ratio")
    # config #14 is the mesh manifest plane: surface the matched-work
    # multichip speedup at top level (parity/even-split/handoff gates run
    # everywhere; the wall-clock gate arms on hardware only)
    multichip = configs.get("14_multichip", {})
    if "speedup" in multichip:
        record["multichip_speedup"] = multichip["speedup"]
    # config #15 is the snapshot lifecycle plane: surface how much of the
    # shipped data GC reclaimed (and the zero-violation verdict) at top
    # level so BENCH_r*.json diffs track the collector directly
    gc = configs.get("15_gc", {})
    if "gc_reclaim_ratio" in gc:
        record["gc_reclaim_ratio"] = gc["gc_reclaim_ratio"]
        record["gc_passed"] = gc.get("passed")
    # config #16 is the federated coordination plane: surface the
    # multi-node matchmaking speedups at top level (scaling gates arm on
    # >=4-CPU hosts; the churn scorecard's zero-lost gate runs
    # everywhere) so BENCH_r*.json diffs track federation directly
    federation = configs.get("16_federation", {})
    if "federation_speedup_2node" in federation:
        record["federation_speedup_2node"] = \
            federation["federation_speedup_2node"]
        record["federation_speedup_4node"] = \
            federation["federation_speedup_4node"]
    # config #17 is the tiered dedup index: surface the skewed-corpus
    # device-path hit rate at top level (parity/budget/hit-rate gates
    # run everywhere; the wall gate arms on hardware only) so
    # BENCH_r*.json diffs track the tier split directly
    tiered = configs.get("17_tiered", {})
    if "tiered_hit_rate" in tiered:
        record["tiered_hit_rate"] = tiered["tiered_hit_rate"]
        record["tiered_overflow_ratio"] = tiered.get("overflow_ratio")
    # config #18 is replicated coordination metadata: surface the
    # permakill durability count (must stay 0) and the promote-to-
    # serving time at top level so BENCH_r*.json diffs track the
    # replication plane directly
    repl = configs.get("18_replication", {})
    if "replication_lost_rows" in repl:
        record["replication_lost_rows"] = repl["replication_lost_rows"]
        record["repl_promote_s"] = repl.get("repl_promote_s")
    # config #19 is the virtual-clock simulation plane: surface the
    # driver throughput and the time-compression ratio at top level so
    # BENCH_r*.json diffs track whether a simulated week still fits a
    # tier-1 minute
    sim = configs.get("19_sim", {})
    if "sim_time_compression" in sim:
        record["sim_events_per_s"] = sim["sim_events_per_s"]
        record["sim_time_compression"] = sim["sim_time_compression"]
    # config #20 is the streaming dataflow engine: surface the overlap
    # efficiency (max stage busy / wall) and the phased->stream speedup
    # at top level so BENCH_r*.json diffs track whether the backup wall
    # still converges to max(stage) rather than sum(stage)
    dataflow = configs.get("20_dataflow", {})
    if "dataflow_overlap_efficiency" in dataflow:
        record["dataflow_overlap_efficiency"] = \
            dataflow["dataflow_overlap_efficiency"]
        record["dataflow_speedup"] = dataflow["dataflow_speedup"]
    # config #21 is the live SLO plane: surface breach-detection latency
    # and explainer precision at top level so BENCH_r*.json diffs (and
    # scripts/bench_trend.py) track whether a durability incident still
    # pages within the budget and the root-cause ranking stays exact
    slo = configs.get("21_slo", {})
    if "slo_detection_s" in slo:
        record["slo_detection_s"] = slo["slo_detection_s"]
        record["slo_precision"] = slo["slo_precision"]
    print(json.dumps({
        **record,
        "note": "corpus synthesized on-device (host<->device relay tunnel "
                "~6 MiB/s would measure the tunnel, not the kernels); "
                "parity vs CPU oracle gated per config",
        "pipeline_report": _pipeline_report(),
        "metrics": _metrics_snapshot(),
    }))


def _cpu_fallback_report() -> None:
    """Device init timed out: measure the HOST pipeline (native C if it
    compiles, numpy oracle otherwise) instead of printing value 0.0 — the
    run still records a real throughput number, tagged ``backend:
    cpu-fallback`` so recap tooling never mistakes it for a device
    measurement.  Touches no jax device APIs (they are what hung)."""
    import numpy as np

    from backuwup_tpu import native
    from backuwup_tpu.ops import cdc_cpu
    from backuwup_tpu.ops.blake3_cpu import Blake3Numpy
    from backuwup_tpu.ops.gear import CDCParams

    params = CDCParams()
    cpu_mib = int(os.environ.get("BENCH_CPU_MIB", "64"))
    host = np.random.default_rng(1234).integers(
        0, 256, cpu_mib << 20, dtype=np.uint8).tobytes()
    try:
        kind = "native C fastcdc-class+blake3 pipeline, 1 host thread"
        cpu_s = min(_timed(native.manifest_native, host, params)
                    for _ in range(3))
    except native.NativeUnavailable as e:
        log(f"native baseline unavailable ({e}); using numpy oracle")
        kind = "numpy oracle pipeline, 1 host thread (no C compiler)"

        def run(data, p):
            chunks = cdc_cpu.chunk_stream(data, p)
            Blake3Numpy().digest_batch([data[o:o + l] for o, l in chunks])

        cpu_s = min(_timed(run, host, params) for _ in range(3))
    mibs = cpu_mib / cpu_s
    log(f"cpu-fallback: {cpu_mib} MiB in {cpu_s:.2f}s = {mibs:.1f} MiB/s")
    print(json.dumps({
        "metric": "dedup pipeline chunk+hash throughput (device-resident)",
        "value": round(mibs, 2),
        "unit": "MiB/s",
        "vs_baseline": 1.0,
        "backend": "cpu-fallback",
        "baseline": f"{kind} ({mibs:.1f} MiB/s)",
        "error": "device init timed out (accelerator tunnel down?); "
                 "see BENCH_INIT_TIMEOUT_S",
        "note": "HOST-pipeline measurement — the device never initialized;"
                " PERF.md and the last BENCH_r*.json hold the most recent"
                " device numbers",
        "pipeline_report": _pipeline_report(),
        "metrics": _metrics_snapshot()}))


def _timed(fn, *args):
    t0 = time.time()
    fn(*args)
    return time.time() - t0


if __name__ == "__main__":
    main()
