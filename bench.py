"""Benchmark: device-resident chunk+hash throughput vs single-thread CPU.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "MiB/s", "vs_baseline": N}

Method (BASELINE.json north star — chunk + fingerprint MiB/s at identical
dedup output):

* TPU path: corpus segments are synthesized **on device** with the JAX PRNG
  (the dev rig's host<->device relay tunnel is ~6 MiB/s, three orders below
  real PCIe/DMA, so streaming host bytes would measure the tunnel, not the
  kernels).  Each segment runs the full resident pipeline: gear scan ->
  sparse candidates -> host cut selection -> on-device chunk gather ->
  batched BLAKE3.
* CPU baseline: the native C implementation (``native/cdc_blake3.c``) of the
  identical pipeline on ONE host thread — the honest stand-in for the
  reference's fastcdc+blake3 crates; parity vs the spec oracle is asserted
  by tests/test_native.py and re-checked here before timing.  The numpy
  oracle's throughput is logged as a secondary line only.
* Parity gate: an 8 MiB corpus is pushed through BOTH paths bit-for-bit;
  chunk boundaries and digests must match exactly or the benchmark reports
  failure — speed without identical dedup output is meaningless.

Environment knobs: BENCH_SEGMENTS (default 4), BENCH_SEGMENT_MIB (default
128), BENCH_CPU_MIB (default 64).
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    from backuwup_tpu.utils.jaxcache import enable_compilation_cache
    enable_compilation_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from backuwup_tpu.ops import cdc_cpu
    from backuwup_tpu.ops.blake3_cpu import Blake3Numpy
    from backuwup_tpu.ops.cdc_tpu import _HALO
    from backuwup_tpu.ops.gear import CDCParams
    from backuwup_tpu.ops.pipeline import DevicePipeline

    segments = int(os.environ.get("BENCH_SEGMENTS", "3"))
    seg_mib = int(os.environ.get("BENCH_SEGMENT_MIB", "256"))
    cpu_mib = int(os.environ.get("BENCH_CPU_MIB", "64"))
    params = CDCParams()  # production 256KiB/1MiB/3MiB
    pipeline = DevicePipeline(params)
    seg_bytes = seg_mib * (1 << 20)

    log(f"devices: {jax.devices()}")

    # --- parity gate -------------------------------------------------------
    rng = np.random.default_rng(1234)
    parity = rng.integers(0, 256, 8 << 20, dtype=np.uint8)
    # tile a block so dedup has real duplicates to find
    parity[4 << 20:6 << 20] = parity[0:2 << 20]
    parity_bytes = parity.tobytes()
    cpu_chunks = cdc_cpu.chunk_stream(parity_bytes, params)
    cpu_digests = Blake3Numpy().digest_batch(
        [parity_bytes[o:o + l] for o, l in cpu_chunks])
    ext = np.concatenate([np.zeros(_HALO, dtype=np.uint8), parity])
    (tpu_chunks, tpu_digests), = pipeline.manifest_resident_batch(
        jnp.asarray(ext.reshape(1, -1)),
        np.full(1, len(parity_bytes), dtype=np.int32))
    tpu_digest_bytes = [bytes(d) for d in tpu_digests]
    if tpu_chunks != cpu_chunks or tpu_digest_bytes != cpu_digests:
        print(json.dumps({"metric": "chunk+hash parity FAILED", "value": 0.0,
                          "unit": "MiB/s", "vs_baseline": 0.0}))
        return
    dedup = len(set(cpu_digests)) / len(cpu_digests)
    log(f"parity OK: {len(cpu_chunks)} chunks, unique-ratio {dedup:.3f}")

    # --- TPU timing: pre-synthesized resident corpus, pipelined ------------
    # Times pipeline.manifest_segments — the pipelined driver over the exact
    # device core the engine's backup path runs per batch.  The corpus is
    # synthesized into HBM up front (it would arrive by DMA in a real rig;
    # here the relay tunnel would otherwise be the measurement), then the
    # timed loop overlaps scan+select, cut download, and digest across
    # segments.
    key = jax.random.PRNGKey(0)
    row = _HALO + seg_bytes
    nv = np.full(1, seg_bytes, dtype=np.int32)

    @jax.jit
    def synth(key):
        seg = jax.random.randint(key, (seg_bytes,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), seg]
                               ).reshape(1, row)

    # warm: compile the closed digest tile universe (B in {8,32,128} x the
    # production L buckets) plus the scan program, so the timed loop can
    # never hit a 20-40s XLA compile regardless of chunk-count jitter;
    # everything lands in the persistent cache for future runs
    from backuwup_tpu.ops.pipeline import _gather_digest

    span_max = pipeline.l_bucket * 1024
    # the flat buffer's shape is part of the compiled signature: warm with
    # the exact length the timed segments produce (1 row + gather slack)
    flat_w = jnp.zeros(row + span_max, dtype=jnp.uint8)
    meta_w = jnp.zeros((3, 256), dtype=jnp.int32)
    for L in (256, 512, 1024, 2048, 3072):
        for B in (8, 32, 128):
            acc_w = jnp.zeros((256, 8), dtype=jnp.uint32)
            _gather_digest(flat_w, meta_w, meta_w[2, 0], acc_w, B=B, L=L)
    for _ in range(2):
        key, sub = jax.random.split(key)
        pipeline.manifest_resident_batch(synth(sub), nv, strict_overflow=True)

    corpus = []
    for _ in range(segments):
        key, sub = jax.random.split(key)
        corpus.append((synth(sub), nv))
    jax.block_until_ready([b for b, _ in corpus])

    t0 = time.time()
    results = list(pipeline.manifest_segments(corpus, strict_overflow=True))
    tpu_s = time.time() - t0
    total_chunks = sum(len(chunks) for (chunks, _), in results)
    tpu_mibs = segments * seg_mib / tpu_s
    log(f"tpu: {segments}x{seg_mib} MiB in {tpu_s:.2f}s = {tpu_mibs:.1f} MiB/s"
        f" ({total_chunks} chunks)")

    # --- CPU baseline: native C pipeline, single thread --------------------
    from backuwup_tpu import native

    host = rng.integers(0, 256, cpu_mib << 20, dtype=np.uint8).tobytes()
    baseline_kind = "native C fastcdc+blake3 pipeline, 1 host thread"
    try:
        nat_chunks, nat_digests = native.manifest_native(parity_bytes, params)
        if nat_chunks != cpu_chunks or nat_digests != cpu_digests:
            print(json.dumps({"metric": "native baseline parity FAILED",
                              "value": 0.0, "unit": "MiB/s",
                              "vs_baseline": 0.0}))
            return
        t0 = time.time()
        native.manifest_native(host, params)
        cpu_s = time.time() - t0
        cpu_mibs = cpu_mib / cpu_s
        log(f"cpu-native: {cpu_mib} MiB in {cpu_s:.2f}s = {cpu_mibs:.1f}"
            " MiB/s (single thread)")
    except native.NativeUnavailable as e:
        # no C compiler on this host: fall back to the numpy oracle as the
        # (much slower) baseline rather than crashing the JSON contract
        log(f"native baseline unavailable ({e}); using numpy oracle")
        baseline_kind = "numpy oracle pipeline, 1 host thread (no C compiler)"
        t0 = time.time()
        chunks = cdc_cpu.chunk_stream(host, params)
        Blake3Numpy().digest_batch([host[o:o + l] for o, l in chunks])
        cpu_s = time.time() - t0
        cpu_mibs = cpu_mib / cpu_s

    # --- BASELINE configs #2-#5 -------------------------------------------
    configs = {}
    if os.environ.get("BENCH_CONFIGS", "1") != "0":
        import bench_configs

        configs = bench_configs.run_all(pipeline, params, cpu_mibs, log)

    print(json.dumps({
        "metric": "dedup pipeline chunk+hash throughput (device-resident)",
        "value": round(tpu_mibs, 2),
        "unit": "MiB/s",
        "vs_baseline": round(tpu_mibs / cpu_mibs, 2),
        "baseline": f"{baseline_kind} ({cpu_mibs:.1f} MiB/s)",
        "configs": configs,
        "note": "corpus synthesized on-device (host<->device relay tunnel "
                "~6 MiB/s would measure the tunnel, not the kernels); "
                "parity vs CPU oracle gated per config",
    }))


if __name__ == "__main__":
    main()
