"""BASELINE.md benchmark configs #2-#6 (config #1 is bench.py's main loop).

Each config times the production device pipeline on a device-synthesized
corpus shaped like the BASELINE workload and gates the numbers on
bit-parity with the CPU oracle over a downloaded subset (speed without
identical dedup output is meaningless):

  #2  many small files     — ~80k kernel-tree-shaped files; files below
      the 256 KiB CDC minimum are single chunks, so the production path
      (engine.manifest_batch's tiny-file branch) is digest-bound: staged
      device tiles + batched Pallas BLAKE3, no scan
  #3  two-snapshot overlap — incremental re-chunk over 2x1 GiB, high dedup
  #4  large stream         — 4 GiB at 64 KiB average chunks (VM-image
      profile), streamed through the zero-round-trip driver
  #5  cross-peer global dedup — sharded HBM index, device-resident
      queries, chained sync-free inserts
  #6  end-to-end backup    — DirPacker over a real on-disk tree on the
      host-side engine (packer/packfile/index overheads made visible)

  #7  erasure coding      — RS shard encode/decode throughput
  #8  transfer plane      — serial-vs-concurrent end-to-end backup over
      loopback p2p with N latency-injected peers (ratio, not sustained)
  #9  chaos scenario      — the composed scorecard gate embedded in the
      bench record (durability regression tripwire)
  #10 wan resume          — resume-enabled vs restart-from-zero
      bytes-on-wire across two injected mid-transfer cuts (ratio)
  #11 crash matrix        — armed commit-seam crashes + recovery sweep
      cost, scorecard embedded
  #12 swarm               — sharded vs single-lock coordination plane:
      direct matchmaking-layer speedup legs plus the HTTP swarm
      scenario's p99/stall/off-loop-commit evidence (gate: ≥ 2x)
  #14 multichip           — matched-work 1-device vs N-device mesh
      manifest (shard_map scan→digest + device-resident dedup handoff);
      parity/even-split/handoff gates always on, wall-clock speedup
      gate armed on hardware only

Environment knobs: BENCH_C2_FILES, BENCH_C3_MIB, BENCH_C4_GIB,
BENCH_C5_HASHES, BENCH_C6_MIB, BENCH_C7_SHARD_KIB, BENCH_C7_STRIPES,
BENCH_C8_MIB, BENCH_C8_PEERS, BENCH_C8_LATENCY_S, BENCH_C10_KIB,
BENCH_C10_CHUNK_KIB, BENCH_C12_CLIENTS, BENCH_C12_S, BENCH_C14_DEVICES,
BENCH_C14_ROWS_PER_DEV, BENCH_C14_ROW_KIB, BENCH_C14_SPEEDUP_GATE,
BENCH_C17_DEVICES, BENCH_C17_POPULATION, BENCH_C17_BATCH,
BENCH_C17_HOT_FRACTION, BENCH_C17_HIT_GATE, BENCH_C17_WALL_GATE.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.blake3_cpu import Blake3Numpy, blake3_hash
from backuwup_tpu.ops.blake3_tpu import digest_padded
from backuwup_tpu.ops.cdc_tpu import _HALO
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.pipeline import DevicePipeline


def segment_mib() -> int:
    """Shared segment-size knob: bench.py's main loop and configs #3/#4
    must agree or the suite silently benchmarks mixed segment sizes."""
    return int(os.environ.get("BENCH_SEGMENT_MIB", "256"))


def min_wall_s() -> float:
    """Minimum sustained wall clock per config (BASELINE discipline:
    sustained minutes-long runs, not seconds-long bursts).  0 disables
    (CPU smoke runs)."""
    return float(os.environ.get("BENCH_MIN_WALL_S", "60"))


class SustainedWindow:
    """One shared implementation of the sustained-window discipline.

    Every timed path cycles its work pool for at least the stated scale
    AND at least :func:`min_wall_s` of wall clock; the window records how
    much work actually ran so throughput = work / wall stays honest.
    """

    def __init__(self, n_min: int = 1):
        self.n_min = n_min
        self.count = 0
        self.t0 = time.time()

    def items(self, pool):
        """Yield pool items cyclically for the window (fine-grained
        paths: one item per segment)."""
        while (self.count < self.n_min
               or time.time() - self.t0 < min_wall_s()):
            yield pool[self.count % len(pool)]
            self.count += 1

    def passes(self):
        """Yield pass indices for the window (coarse paths: one pass =
        the whole stated workload)."""
        while (self.count < max(1, self.n_min)
               or time.time() - self.t0 < min_wall_s()):
            yield self.count
            self.count += 1

    @property
    def wall(self) -> float:
        return time.time() - self.t0


def _oracle(data: bytes, params: CDCParams):
    chunks = cdc_cpu.chunk_stream(data, params)
    digests = Blake3Numpy().digest_batch(
        [data[o:o + l] for o, l in chunks])
    return chunks, digests


def _check(device_result, data: bytes, params: CDCParams, tag: str):
    chunks, digests = device_result
    ref_chunks, ref_digests = _oracle(data, params)
    if chunks != ref_chunks or [bytes(d) for d in digests] != ref_digests:
        raise RuntimeError(f"config {tag}: device/oracle parity FAILED")


@functools.partial(jax.jit, static_argnames=("B", "L", "pallas"))
def _gather_digest_tiles(pool: jnp.ndarray, offs: jnp.ndarray,
                         lens: jnp.ndarray, *, B: int, L: int,
                         pallas: bool) -> jnp.ndarray:
    """Carve (B,) file spans out of a resident pool and digest them in
    ONE program — one dispatch submission per tile instead of two, and
    XLA fuses the zero-mask/word-prep into the gather output."""
    span = L * 1024

    def one(off):
        # no zero-mask here: digest_padded masks past-length bytes itself
        return jax.lax.dynamic_slice(pool, (off,), (span,))

    tiles = jax.vmap(one)(offs.astype(jnp.int32))
    return digest_padded(tiles, lens.astype(jnp.int32), L=L, pallas=pallas)


def config2_small_files(pipeline: DevicePipeline, params: CDCParams,
                        log: Callable) -> Dict:
    """~80k small files, batched digests — BASELINE config #2.

    Kernel-tree shape (BASELINE.md:38): tens of thousands of files, nearly
    all below CDC min chunk size, so each is exactly one chunk and one
    BLAKE3 root.  The production path for these is the tiny-file branch of
    ``DevicePipeline.manifest_batch`` / the engine packer: batched
    digests, no scan.  This config stages the files into (B, L*1024)
    digest tiles on device and times gather+digest+manifest assembly.
    """
    n_files = int(os.environ.get("BENCH_C2_FILES", "80000"))
    rng = np.random.default_rng(21)
    # kernel-tree-ish size mix: mostly 1-32 KiB, tail up to 192 KiB
    sizes = np.minimum(
        (rng.lognormal(mean=9.2, sigma=1.1, size=n_files)).astype(np.int64),
        192 * 1024)
    sizes = np.maximum(sizes, 64)
    total = int(sizes.sum())
    pool_len = 256 << 20
    pool = jax.random.randint(jax.random.PRNGKey(5), (pool_len,), 0, 256,
                              dtype=jnp.uint8)
    offs = rng.integers(0, pool_len - 200 * 1024, size=n_files)
    assert (sizes <= params.min_size).all(), "config2 files must be tiny"

    # bucket by leaf count into a closed tile universe
    leaf_buckets = (4, 8, 16, 32, 64, 128, 192)
    leaves = -(-sizes // 1024)
    bucket_of = np.searchsorted(np.array(leaf_buckets), leaves, side="left")
    B = 512
    plan = []  # (bucket L, file index array padded to B)
    for bi, L in enumerate(leaf_buckets):
        idxs = np.nonzero(bucket_of == bi)[0]
        for s0 in range(0, len(idxs), B):
            plan.append((L, idxs[s0:s0 + B]))

    def run():
        digests = np.zeros((n_files, 32), dtype=np.uint8)
        pend = []
        for L, idxs in plan:
            o = np.zeros(B, dtype=np.int64)
            ln = np.zeros(B, dtype=np.int32)
            o[:len(idxs)] = offs[idxs]
            ln[:len(idxs)] = sizes[idxs]
            cv = _gather_digest_tiles(pool, jnp.asarray(o), jnp.asarray(ln),
                                      B=B, L=L,
                                      pallas=pipeline.pallas_digest)
            try:
                cv.copy_to_host_async()
            except AttributeError:
                pass
            pend.append((idxs, cv))
        for idxs, cv in pend:
            dig = np.ascontiguousarray(
                np.asarray(cv).astype("<u4")).view(np.uint8).reshape(-1, 32)
            digests[idxs] = dig[:len(idxs)]
        return digests

    run()  # warm
    window = SustainedWindow()
    for _ in window.passes():
        digests = run()
    loops = window.count
    dt = window.wall
    mibs = loops * total / (1 << 20) / dt

    # parity: oracle-hash a sample of files (download only their spans —
    # the relay link makes bulk downloads the slowest op on this rig)
    for i in rng.integers(0, n_files, size=8):
        off, ln = int(offs[i]), int(sizes[i])
        data = np.asarray(pool[off:off + ln]).tobytes()
        if blake3_hash(data) != bytes(digests[i]):
            raise RuntimeError("config #2: digest parity FAILED")
        if cdc_cpu.chunk_stream(data, params) != [(0, ln)]:
            raise RuntimeError("config #2: tiny file not single-chunk")
    log(f"config#2 small-files: {loops}x{n_files} files, "
        f"{loops * total / (1 << 20):.0f} MiB in {dt:.2f}s = "
        f"{mibs:.1f} MiB/s")
    return {"files": n_files, "mib_s": round(mibs, 2),
            "wall_s": round(dt, 2)}


def _synth_segments(key, n_seg: int, seg: int):
    row = _HALO + seg

    @jax.jit
    def synth(key):
        s = jax.random.randint(key, (seg,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), s]
                               ).reshape(1, row)

    out = []
    for _ in range(n_seg):
        key, sub = jax.random.split(key)
        out.append(synth(sub))
    jax.block_until_ready(out)
    return out


def config3_incremental(pipeline: DevicePipeline, params: CDCParams,
                        log: Callable) -> Dict:
    """Two consecutive snapshots with small edits — BASELINE config #3."""
    snap_mib = int(os.environ.get("BENCH_C3_MIB", "1024"))
    seg_mib = segment_mib()
    seg = seg_mib << 20
    n_seg = max(1, (snap_mib << 20) // seg)
    key = jax.random.PRNGKey(31)

    @jax.jit
    def edit(buf, key):
        """Overwrite 20 x 4 KiB windows — the incremental delta."""
        flat = buf.reshape(-1)
        ks = jax.random.split(key, 20)
        offs = jax.random.randint(key, (20,), _HALO, buf.shape[1] - 4096)
        for i in range(20):
            patch = jax.random.randint(ks[i], (4096,), 0, 256,
                                       dtype=jnp.uint8)
            flat = jax.lax.dynamic_update_slice(flat, patch, (offs[i],))
        return flat.reshape(1, buf.shape[1])

    snap_a = _synth_segments(key, n_seg, seg)
    key2 = jax.random.PRNGKey(32)
    snap_b = []
    for s in snap_a:
        key2, sub = jax.random.split(key2)
        snap_b.append(edit(s, sub))
    jax.block_until_ready(snap_b)
    nv = np.full(1, seg, dtype=np.int32)
    batches = [(s, nv) for s in snap_a + snap_b]

    list(pipeline.manifest_segments_device(batches[:2],
                                           strict_overflow=True))  # warm
    window = SustainedWindow()
    for n in window.passes():
        out = list(pipeline.manifest_segments_device(
            batches, strict_overflow=True))
        if n == 0:
            results = out
    passes = window.count
    dt = window.wall
    dig_a = set()
    for (chunks, digs), in results[:n_seg]:
        dig_a.update(bytes(d) for d in digs)
    dup = tot = 0
    for (chunks, digs), in results[n_seg:]:
        for d in digs:
            tot += 1
            dup += bytes(d) in dig_a
    ratio = dup / max(tot, 1)
    mibs = passes * 2 * n_seg * seg_mib / dt

    # parity + identical dedup ratio on an 8 MiB sub-pair (clipped to the
    # segment size so tiny smoke runs don't declare bytes past the buffer)
    sub = min(8 << 20, seg)
    a8 = bytes(np.asarray(snap_a[0][0, _HALO:_HALO + sub]))
    b8 = bytes(np.asarray(snap_b[0][0, _HALO:_HALO + sub]))
    ca, da = _oracle(a8, params)
    cb, db = _oracle(b8, params)
    sa = set(da)
    oracle_dup = sum(1 for d in db if d in sa)
    dev_sub = []
    for blob in (a8, b8):
        ext = np.concatenate([np.zeros(_HALO, dtype=np.uint8),
                              np.frombuffer(blob, dtype=np.uint8)])
        (res,), = pipeline.manifest_segments_device(
            [(jnp.asarray(ext.reshape(1, -1)),
              np.full(1, sub, dtype=np.int32))], strict_overflow=True)
        _check(res, blob, params, "#3")
        dev_sub.append(res)
    dev_sa = {bytes(d) for d in dev_sub[0][1]}
    dev_dup = sum(1 for d in dev_sub[1][1] if bytes(d) in dev_sa)
    if dev_dup != oracle_dup:
        raise RuntimeError("config #3: dedup-ratio divergence on sub-pair")
    log(f"config#3 incremental: {passes}x2x{n_seg * seg_mib} MiB in "
        f"{dt:.2f}s = {mibs:.1f} MiB/s, dedup ratio {ratio:.3f} "
        f"(oracle sub-pair dup {oracle_dup}/{len(cb)})")
    return {"mib_s": round(mibs, 2), "dedup_ratio": round(ratio, 4),
            "wall_s": round(dt, 2)}


def config4_large_stream(log: Callable) -> Dict:
    """4 GiB contiguous stream at 64 KiB average chunks — config #4."""
    total_gib = float(os.environ.get("BENCH_C4_GIB", "4"))
    params = CDCParams.from_desired(64 << 10)
    pipeline = DevicePipeline(params, l_bucket=256, b_bucket=512)
    seg_mib = segment_mib()
    seg = seg_mib << 20
    n_seg = max(2, int(total_gib * 1024) // seg_mib)
    pool = _synth_segments(jax.random.PRNGKey(41), min(8, n_seg), seg)
    nv = np.full(1, seg, dtype=np.int32)
    list(pipeline.manifest_segments_device([(pool[0], nv), (pool[1], nv)],
                                           strict_overflow=True))  # warm

    window = SustainedWindow(n_seg)
    n_chunks = 0
    for results in pipeline.manifest_segments_device(
            window.items([(s, nv) for s in pool]), strict_overflow=True):
        for chunks, _d in results:
            n_chunks += len(chunks)
    done = window.count
    dt = window.wall
    mibs = done * seg_mib / dt

    sub = min(8 << 20, seg)
    data = bytes(np.asarray(pool[0][0, _HALO:_HALO + sub]))
    ext = np.concatenate([np.zeros(_HALO, dtype=np.uint8),
                          np.frombuffer(data, dtype=np.uint8)])
    (dev_sub,), = pipeline.manifest_segments_device(
        [(jnp.asarray(ext.reshape(1, -1)), np.full(1, sub, dtype=np.int32))],
        strict_overflow=True)
    _check(dev_sub, data, params, "#4")
    log(f"config#4 large-stream(64KiB): {done * seg_mib / 1024:.1f} GiB in "
        f"{dt:.2f}s = {mibs:.1f} MiB/s ({n_chunks} chunks)")
    return {"mib_s": round(mibs, 2), "chunks": n_chunks,
            "wall_s": round(dt, 2)}


def config5_cross_peer(log: Callable) -> Dict:
    """Cross-peer global dedup on the sharded HBM index — config #5.

    Queries are device-resident (in production the digests land in HBM
    straight from the digest stage) and inserts chain without host syncs;
    a smaller host-checked sub-run gates classification parity first.
    """
    from jax.sharding import Mesh

    from backuwup_tpu.ops.dedup_index import (ShardedDedupIndex,
                                              hashes_to_queries)

    n_hashes = int(os.environ.get("BENCH_C5_HASHES", "4000000"))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    rng = np.random.default_rng(51)

    # --- parity sub-run (200k hashes, host-simulated) ----------------------
    shared = [rng.bytes(32) for _ in range(25000)]
    peers = []
    for p in range(4):
        own = [rng.bytes(32) for _ in range(25000)]
        picks = rng.choice(len(shared), 25000, replace=False)
        peers.append(own + [shared[i] for i in picks])
    cap = 1 << 20
    index = ShardedDedupIndex.create(mesh, capacity=cap)
    host_seen = set()
    dev_flags = []
    host_flags = []
    for corpus in peers:
        q = hashes_to_queries(corpus)
        found = index.insert(q, np.ones(len(corpus), dtype=np.uint32))
        dev_flags.extend(bool(f) for f in found)
        for h in corpus:
            host_flags.append(h in host_seen)
            host_seen.add(h)
    if dev_flags != host_flags:
        raise RuntimeError("config #5: device/host global dedup mismatch")

    # --- timed run: device-resident queries, sync-free inserts -------------
    batch = 500_000
    n_batches = max(1, n_hashes // batch)
    cap = 1 << max(20, (4 * n_hashes).bit_length() - 1)
    index = ShardedDedupIndex.create(mesh, capacity=cap)
    key = jax.random.PRNGKey(55)
    d = mesh.shape["data"]

    @jax.jit
    def synth_q(key, dup_from):
        """Half fresh random keys, half repeats of an earlier batch."""
        fresh = jax.random.bits(key, (batch, 4), dtype=jnp.uint32)
        mix = jnp.where((jnp.arange(batch) % 2 == 0)[:, None],
                        fresh, dup_from)
        return mix.reshape(d, batch // d, 4)

    k0, key = jax.random.split(key)
    first = jax.random.bits(k0, (batch, 4), dtype=jnp.uint32)
    qs = []
    prev = first
    for _ in range(n_batches):
        key, sub = jax.random.split(key)
        q = synth_q(sub, prev)
        prev = q.reshape(batch, 4)
        qs.append(q)
    jax.block_until_ready(qs)
    vals = jnp.ones((d, batch // d), dtype=jnp.uint32)

    # warm insert AND probe programs on a throwaway table (same shapes
    # as the timed table, so both compiles land out of the timed window)
    warm = ShardedDedupIndex.create(mesh, capacity=cap)
    warm.insert_device(qs[0], vals)
    jax.block_until_ready(warm.probe_device(qs[0]))

    t0 = time.time()
    founds = []
    for q in qs:
        found, lost = index.insert_device(q, vals)
        founds.append((found, lost))
    # one sync at the end: download the found/lost flags
    lost_total = 0
    dup_total = 0
    for found, lost in founds:
        lost_total += int(np.asarray(lost).sum())
        dup_total += int((np.asarray(found) != 0).sum())
    insert_dt = time.time() - t0
    if lost_total:
        raise RuntimeError("config #5: unresolved inserts (table too full)")
    total = n_batches * batch
    rate = total / insert_dt

    # sustained window: keep issuing device-resident probe batches (the
    # dominant steady-state operation — inserts are capped by the table's
    # load-factor budget, probes are not)
    # own window, independent of how long the inserts took: the
    # sustained-read metric must exist even when the insert phase alone
    # exceeds the budget
    probes = 0
    probe_chain = []
    t1 = time.time()
    while time.time() - t1 < min_wall_s():
        probe_chain.append(index.probe_device(qs[probes % len(qs)]))
        probes += 1
        if len(probe_chain) >= 8:
            # bound in-flight work with a one-scalar download: device
            # executions run in order, so syncing result i proves all
            # earlier probes completed, without the bulk found-vector
            # transfer (block_until_ready returns early on this rig —
            # the scripts/devtime.py discovery — and np.asarray of the
            # full vector would measure the relay link instead)
            np.asarray(probe_chain.pop(0).ravel()[0])
    if probe_chain:
        np.asarray(probe_chain[-1].ravel()[0])
    probe_dt = time.time() - t1
    probe_rate = probes * batch / probe_dt if probes else 0.0
    dt = time.time() - t0
    log(f"config#5 cross-peer: {total} hashes over {d} device(s) in "
        f"{insert_dt:.2f}s = {rate:,.0f} inserts/s, dup ratio "
        f"{dup_total/total:.3f}; sustained {probes * batch} probes "
        f"at {probe_rate:,.0f}/s (wall {dt:.1f}s)")
    out = {"hashes_s": round(rate), "dup_ratio": round(dup_total / total, 4),
           "wall_s": round(dt, 2)}
    if probes:
        out["probe_hashes_s"] = round(probe_rate)
    return out


def config6_end_to_end(log: Callable) -> Dict:
    """End-to-end DirPacker over a real on-disk tree — engine overheads.

    Runs the actual backup packer (walk -> chunk -> dedup -> compress ->
    encrypt -> packfile write) on the host CPU backend over a temp corpus,
    so packer/packfile/index costs are visible next to the kernel numbers
    (reference hot path: dir_packer.rs:246-311 + pack.rs:116-204).  The
    device backend on this rig would measure the ~6 MiB/s relay tunnel.
    """
    import shutil
    import tempfile
    from pathlib import Path

    from backuwup_tpu.crypto import KeyManager
    from backuwup_tpu.ops.backend import CpuBackend, NativeBackend
    from backuwup_tpu.snapshot.blob_index import BlobIndex
    from backuwup_tpu.snapshot.packer import DirPacker
    from backuwup_tpu.snapshot.packfile import PackfileWriter

    total_mib = int(os.environ.get("BENCH_C6_MIB", "256"))
    rng = np.random.default_rng(61)
    tmp = Path(tempfile.mkdtemp(prefix="bkw_bench_"))
    try:
        src = tmp / "src"
        src.mkdir()
        written = 0
        i = 0
        while written < (total_mib << 20):
            sub = src / f"d{i % 16}"
            sub.mkdir(exist_ok=True)
            n = int(rng.integers(16 << 10, 4 << 20))
            (sub / f"f{i}").write_bytes(rng.bytes(n))
            written += n
            i += 1
        keys = KeyManager.generate()
        try:
            backend = NativeBackend()
        except Exception:
            backend = CpuBackend()

        def one_pass(n: int) -> None:
            out = tmp / f"packs{n}"
            out.mkdir()
            packer = DirPacker(backend, PackfileWriter(keys, out),
                               BlobIndex(keys, tmp / f"index{n}"))
            packer.pack(src)
            packer.writer.close()
            shutil.rmtree(out, ignore_errors=True)
            shutil.rmtree(tmp / f"index{n}", ignore_errors=True)

        window = SustainedWindow()
        for n in window.passes():
            one_pass(n)  # fresh index/writer: full work every pass
        passes = window.count
        dt = window.wall
        mibs = passes * written / (1 << 20) / dt
        log(f"config#6 end-to-end: {passes}x{written / (1 << 20):.0f} MiB, "
            f"{i} files packed in {dt:.2f}s = {mibs:.1f} MiB/s "
            f"(host {backend.name} backend)")
        return {"mib_s": round(mibs, 2), "files": i, "wall_s": round(dt, 2)}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def config7_erasure(log: Callable) -> Dict:
    """Reed-Solomon shard encode/decode throughput — BASELINE config #7.

    Times the erasure subsystem's hot path (``backend.encode_shards`` /
    ``decode_shards``: table-lookup GF(2^8) matmul + XOR-reduce under
    jit(vmap) on device, numpy oracle on CPU) over batches of RS_K+RS_M
    stripes.  Decode reconstructs from the WORST-case survivor set (all
    parity shards in play) so the recovery-matrix solve is real work, and
    the gate demands bit-identical output: encode must match the gf_cpu
    oracle, decode must reproduce the original data shards exactly.
    """
    from backuwup_tpu import defaults
    from backuwup_tpu.erasure import gf_cpu
    from backuwup_tpu.ops.backend import select_backend

    k, m = int(defaults.RS_K), int(defaults.RS_M)
    shard_kib = int(os.environ.get("BENCH_C7_SHARD_KIB", "512"))
    batch = int(os.environ.get("BENCH_C7_STRIPES", "64"))
    backend = select_backend()
    ln = shard_kib << 10
    rng = np.random.default_rng(71)
    stripes = rng.integers(0, 256, (batch, k, ln), dtype=np.uint8)

    # parity + round-trip gate on one stripe before anything is timed
    parity = np.asarray(backend.encode_shards(stripes, m), dtype=np.uint8)
    ref = gf_cpu.gf_matmul(gf_cpu.generator_matrix(k, m)[k:], stripes[0])
    if not np.array_equal(parity[0], ref):
        raise RuntimeError("config #7: encode parity FAILED vs gf_cpu")
    present = list(range(m, k + m))  # first m data shards "lost"
    full = np.concatenate([stripes, parity], axis=1)
    surv = full[:, present, :]
    decoded = np.asarray(backend.decode_shards(surv, k, m, present),
                         dtype=np.uint8)
    if not np.array_equal(decoded, stripes):
        raise RuntimeError("config #7: decode round-trip FAILED")

    data_mib = batch * k * ln / (1 << 20)
    window = SustainedWindow()
    for _ in window.passes():
        p = np.asarray(backend.encode_shards(stripes, m))
        np.asarray(backend.decode_shards(surv, k, m, present))
        del p
    passes = window.count
    dt = window.wall
    # each pass encodes AND decodes the full batch of stripes
    enc_dec_mibs = passes * 2 * data_mib / dt
    log(f"config#7 erasure rs({k},{m}): {passes}x{data_mib:.0f} MiB "
        f"enc+dec in {dt:.2f}s = {enc_dec_mibs:.1f} MiB/s "
        f"({backend.name} backend)")
    return {"mib_s": round(enc_dec_mibs, 2), "rs_k": k, "rs_m": m,
            "shard_kib": shard_kib, "backend": backend.name,
            "wall_s": round(dt, 2)}


def config8_transfer(log: Callable) -> Dict:
    """Serial-vs-concurrent transfer plane over loopback p2p — config #8.

    Spins up a CoordinationServer, one source client, and N holder
    clients in-process, then runs the SAME end-to-end backup twice with
    per-send latency injected through the fault plane (a loopback socket
    is too fast for transfer order to matter otherwise):

      serial     — TRANSFER_MAX_INFLIGHT=1, TRANSFER_MAX_PEERS=1,
                   PACK_SEAL_WORKERS=0: one transfer in flight at a
                   time and a synchronous seal, the pre-transfer-plane
                   shape
      concurrent — the shipped defaults: all shards of a stripe in
                   flight to distinct peers, pipelined seal

    Both numbers land in one record so BENCH_r*.json tracks the ratio.
    This is a ratio measurement (one pass each), not a sustained-window
    throughput config.
    """
    import asyncio
    import shutil
    import tempfile
    from pathlib import Path

    from backuwup_tpu import defaults
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.server import CoordinationServer
    from backuwup_tpu.ops.backend import CpuBackend, NativeBackend
    from backuwup_tpu.utils import faults

    total_mib = int(os.environ.get("BENCH_C8_MIB", "4"))
    n_peers = int(os.environ.get("BENCH_C8_PEERS", "6"))
    latency_s = float(os.environ.get("BENCH_C8_LATENCY_S", "0.04"))

    saved = {k: getattr(defaults, k) for k in (
        "PACKFILE_TARGET_SIZE", "TRANSFER_MAX_INFLIGHT",
        "TRANSFER_MAX_PEERS", "PACK_SEAL_WORKERS")}
    tmp = Path(tempfile.mkdtemp(prefix="bkw_bench_c8_"))
    rng = np.random.default_rng(81)
    src = tmp / "src"
    src.mkdir()
    written = 0
    i = 0
    while written < (total_mib << 20):
        sub = src / f"d{i % 8}"
        sub.mkdir(exist_ok=True)
        n = int(rng.integers(64 << 10, 512 << 10))
        (sub / f"f{i}").write_bytes(rng.bytes(n))
        written += n
        i += 1

    async def one_backup(tag: str) -> float:
        server = CoordinationServer(db_path=str(tmp / f"server_{tag}.db"))
        port = await server.start()

        def make_app(name):
            # native chunk+hash where available: the measurement is the
            # transfer plane, not the python oracle chunker
            params = CDCParams.from_desired(16 << 10)
            try:
                backend = NativeBackend(params)
            except Exception:
                backend = CpuBackend(params)
            app = ClientApp(config_dir=tmp / tag / name / "cfg",
                            data_dir=tmp / tag / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            backend=backend)
            app.store.set_backup_path(str(src))
            return app

        a = make_app("a")
        holders = [make_app(f"p{j}") for j in range(n_peers)]
        apps = [a] + holders
        try:
            for app in apps:
                await app.start()
                app._audit_task.cancel()
            a.engine.auto_repair = False
            amt = 8 * (written + (64 << 20)) // max(1, n_peers)
            for peer in holders:
                a.store.add_peer_negotiated(peer.client_id, amt)
                peer.store.add_peer_negotiated(a.client_id, amt)
                server.db.save_storage_negotiated(
                    bytes(a.client_id), bytes(peer.client_id), amt)
            t0 = time.time()
            snapshot = await asyncio.wait_for(a.backup(), 600)
            if not snapshot:
                raise RuntimeError(f"config #8 {tag}: backup returned none")
            return time.time() - t0
        finally:
            for app in apps:
                try:
                    await app.stop()
                except Exception:
                    pass
            await server.stop()

    async def both() -> Dict:
        # always-fire latency on every FILE send: makes the run
        # transfer-bound so overlap (or its absence) dominates the wall
        faults.install(faults.FaultPlane(seed=8, latency=1.0,
                                         latency_s=latency_s))
        try:
            defaults.PACKFILE_TARGET_SIZE = 128 * 1024
            defaults.TRANSFER_MAX_INFLIGHT = 1
            defaults.TRANSFER_MAX_PEERS = 1
            defaults.PACK_SEAL_WORKERS = 0
            serial_wall = await one_backup("serial")
            defaults.TRANSFER_MAX_INFLIGHT = saved["TRANSFER_MAX_INFLIGHT"]
            defaults.TRANSFER_MAX_PEERS = saved["TRANSFER_MAX_PEERS"]
            defaults.PACK_SEAL_WORKERS = saved["PACK_SEAL_WORKERS"]
            concurrent_wall = await one_backup("concurrent")
            return {"serial": serial_wall, "concurrent": concurrent_wall}
        finally:
            faults.uninstall()

    try:
        walls = asyncio.run(both())
        data_mib = written / (1 << 20)
        serial = data_mib / walls["serial"]
        concurrent = data_mib / walls["concurrent"]
        speedup = walls["serial"] / walls["concurrent"]
        log(f"config#8 transfer: {data_mib:.0f} MiB to {n_peers} peers "
            f"(+{latency_s * 1000:.0f}ms/send): serial {serial:.2f} MiB/s, "
            f"concurrent {concurrent:.2f} MiB/s = {speedup:.2f}x")
        return {"mib_s": round(concurrent, 2),
                "serial_mib_s": round(serial, 2),
                "speedup": round(speedup, 2), "peers": n_peers,
                "latency_ms": round(latency_s * 1000, 1),
                "wall_s": round(walls["serial"] + walls["concurrent"], 2)}
    finally:
        for k, v in saved.items():
            setattr(defaults, k, v)
        shutil.rmtree(tmp, ignore_errors=True)


def config10_wan(log: Callable) -> Dict:
    """Resume-enabled vs restart-from-zero over a cut WAN link — #10.

    One source and one holder over loopback p2p, a 512 KiB payload
    chunked into 16 KiB FILE_PART frames, and the SAME two armed
    exact-offset cuts (at 256 KiB and 384 KiB) severing the connection
    mid-transfer in both legs:

      resume  — TRANSFER_RESUME_ENABLED semantics: each reconnect runs
                the RESUME_QUERY/RESUME_OFFER handshake and continues
                from the receiver's verified partial
      restart — resume negotiation disabled, so every reconnect starts
                the file over from byte zero (the pre-resume shape)

    Both legs report sender-side bytes-on-wire (the
    bkw_p2p_bytes_sent_total delta — every outbound frame crosses the
    one transport chokepoint) and wall clock in one record; the ratio
    is the acceptance number (expected ~0.44, gate <= 0.6).
    """
    import asyncio
    import contextlib
    import shutil
    import tempfile
    from pathlib import Path

    from backuwup_tpu import defaults, wire
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.p2p import P2PError
    from backuwup_tpu.net.server import CoordinationServer
    from backuwup_tpu.obs import metrics as obs_metrics
    from backuwup_tpu.utils import faults

    payload_kib = int(os.environ.get("BENCH_C10_KIB", "512"))
    chunk_kib = int(os.environ.get("BENCH_C10_CHUNK_KIB", "16"))
    cuts = (payload_kib << 10) // 2, 3 * (payload_kib << 10) // 4

    saved = defaults.TRANSFER_CHUNK_BYTES
    tmp = Path(tempfile.mkdtemp(prefix="bkw_bench_c10_"))
    rng = np.random.default_rng(101)
    data = rng.bytes(payload_kib << 10)

    def wire_bytes() -> float:
        fam = obs_metrics.registry().snapshot().get(
            "bkw_p2p_bytes_sent_total") or {}
        return sum(s["value"] for s in fam.get("series", []))

    async def one_leg(a: ClientApp, holder_id: bytes, plane,
                      file_id: bytes, resume: bool) -> Dict:
        plane.arm_cut(holder_id, *cuts)
        before, t0 = wire_bytes(), time.time()
        t = await a.node.connect(holder_id, wire.RequestType.TRANSPORT,
                                 timeout=10.0)
        try:
            for _ in range(len(cuts) + 2):
                try:
                    await t.send_file(data, wire.FileInfoKind.PACKFILE,
                                      file_id, resume=resume)
                    break
                except P2PError:
                    t = await a.node.connect(
                        holder_id, wire.RequestType.TRANSPORT, timeout=10.0)
            else:
                raise RuntimeError("config #10: transfer never completed")
        finally:
            with contextlib.suppress(Exception):
                await t.close()
        return {"bytes_wire": round(wire_bytes() - before),
                "wall_s": round(time.time() - t0, 3)}

    async def both() -> Dict:
        plane = faults.install(faults.FaultPlane(seed=101))
        server = CoordinationServer(db_path=str(tmp / "server.db"))
        port = await server.start()

        def make_app(name):
            app = ClientApp(config_dir=tmp / name / "cfg",
                            data_dir=tmp / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            tls=False)  # plaintext loopback deployment
            return app

        a, h = make_app("a"), make_app("h")
        try:
            for app in (a, h):
                await app.start()
                app._audit_task.cancel()
            amt = 64 << 20
            a.store.add_peer_negotiated(h.client_id, amt)
            h.store.add_peer_negotiated(a.client_id, amt)
            server.db.save_storage_negotiated(
                bytes(a.client_id), bytes(h.client_id), amt)
            legs = {}
            legs["resume"] = await one_leg(
                a, h.client_id, plane, bytes(range(32)), resume=True)
            legs["restart"] = await one_leg(
                a, h.client_id, plane, bytes(range(32, 64)), resume=False)
            return legs
        finally:
            for app in (a, h):
                with contextlib.suppress(Exception):
                    await app.stop()
            await server.stop()
            faults.uninstall()

    try:
        defaults.TRANSFER_CHUNK_BYTES = chunk_kib << 10
        legs = asyncio.run(both())
        ratio = legs["resume"]["bytes_wire"] / max(
            legs["restart"]["bytes_wire"], 1)
        log(f"config#10 wan resume: {payload_kib} KiB across 2 cuts: "
            f"resume {legs['resume']['bytes_wire']} B on wire in "
            f"{legs['resume']['wall_s']}s, restart "
            f"{legs['restart']['bytes_wire']} B in "
            f"{legs['restart']['wall_s']}s = {ratio:.2f}x")
        return {"payload_kib": payload_kib, "chunk_kib": chunk_kib,
                "cut_offsets": list(cuts), "resume": legs["resume"],
                "restart": legs["restart"], "ratio": round(ratio, 3),
                "wall_s": round(legs["resume"]["wall_s"]
                                + legs["restart"]["wall_s"], 2)}
    finally:
        defaults.TRANSFER_CHUNK_BYTES = saved
        shutil.rmtree(tmp, ignore_errors=True)


def config9_scenario(log: Callable) -> Dict:
    """Composed chaos scenario + scorecard gate — config #9.

    Runs the seeded ``composed`` scenario (scenario/harness.py: backup,
    sustained churn, byzantine corrupt-shard audit demotion, sourceless
    repair, backup + restore + repair racing the exclusivity lock) and
    embeds the full scorecard in the BENCH record, so every bench run
    doubles as a durability regression gate: ``passed`` flips false if
    any hard assertion (zero invariant-violation-seconds, verified
    restore, shards rebuilt, final status ok) regresses.
    """
    import asyncio
    import tempfile
    from pathlib import Path

    from backuwup_tpu.scenario import builtin_scenarios, run_scenario

    spec = builtin_scenarios()["composed"]
    with tempfile.TemporaryDirectory(prefix="bkw_bench_scenario_") as td:
        card = asyncio.run(run_scenario(spec, Path(td)))
    counters = card.counters
    rebuilt = counters.get("bkw_repair_shards_rebuilt_total", 0)
    log(f"config#9 scenario '{card.scenario}' (seed {card.seed}): "
        f"{'PASS' if card.passed else 'FAIL'} in {card.elapsed_s:.1f}s, "
        f"violation_s={card.invariants['violation_seconds']}, "
        f"shards_rebuilt={rebuilt:g}, "
        f"final={card.invariants['final'].get('status', '?')}")
    return {"passed": card.passed,
            "violation_seconds": card.invariants["violation_seconds"],
            "worst_status": card.invariants["worst_status"],
            "shards_rebuilt": int(rebuilt),
            "wall_s": round(card.elapsed_s, 2),
            "scorecard": card.to_dict()}


def config11_crash(log: Callable) -> Dict:
    """Crash matrix + recovery sweep cost — config #11.

    Runs the representative ``crash`` scenario (three armed commit-seam
    crashes mid-backup, each followed by a client restart, the startup
    recovery sweep, a drain re-backup, and an idempotence probe) and
    reports what crash recovery COSTS: sweeps run, items reconciled by
    category, and the sweep wall-time quantiles — with the full
    scorecard embedded so the ``recovery_clean`` hard gate regresses
    loudly in the BENCH record.
    """
    import asyncio
    import tempfile
    from pathlib import Path

    from backuwup_tpu.scenario import builtin_scenarios, run_scenario

    spec = builtin_scenarios()["crash"]
    with tempfile.TemporaryDirectory(prefix="bkw_bench_crash_") as td:
        card = asyncio.run(run_scenario(spec, Path(td)))
    counters = card.counters
    sweeps = sum(v for k, v in counters.items()
                 if k.startswith("bkw_recovery_runs_total"))
    items = {k.split("category=", 1)[1].rstrip("}"): v
             for k, v in counters.items()
             if k.startswith("bkw_recovery_items_total")}
    sweep_q = next((v for k, v in card.quantiles.items()
                    if k.startswith("bkw_recovery_seconds")), {})
    log(f"config#11 crash '{card.scenario}' (seed {card.seed}): "
        f"{'PASS' if card.passed else 'FAIL'} in {card.elapsed_s:.1f}s, "
        f"sweeps={sweeps:g} reconciled={sum(items.values()):g} "
        f"sweep_p99={sweep_q.get('p99')}s")
    return {"passed": card.passed,
            "recovery_sweeps": int(sweeps),
            "items_reconciled": items,
            "sweep_seconds": sweep_q,
            "wall_s": round(card.elapsed_s, 2),
            "scorecard": card.to_dict()}


def config12_swarm(log: Callable) -> Dict:
    """Sharded vs single-lock coordination plane — config #12.

    Two measurements land in ONE record:

    * **speedup legs** — the matchmaker + store pair driven directly by
      time-boxed client coroutines (same file-backed sqlite, same fsync
      discipline, same per-candidate audit-history scan weight in both
      legs): ``baseline`` is the legacy single-lock StorageQueue over
      the direct-commit store, ``sharded`` the pubkey-sharded matchmaker
      over the write-behind store.  The gate is sharded ≥ 2x baseline
      matchmakings/s.  The legs bypass HTTP deliberately: on a
      single-core box the identical per-request HTTP/auth cost dominates
      both tiers and hides the coordination-layer difference.
    * **swarm evidence** — the full HTTP swarm scenario (register, WS
      push, seeded request mix, churn) on the sharded tier, embedding
      the scorecard whose hard gates assert the p99 is measured, the
      event loop never stalled past budget, and no sqlite commit ran on
      the loop thread.
    """
    import asyncio
    import dataclasses
    import tempfile
    from pathlib import Path

    from backuwup_tpu.scenario import (MatchLoadSpec, builtin_swarms,
                                       run_match_load, run_swarm)

    clients = int(os.environ.get("BENCH_C12_CLIENTS", "128"))
    duration_s = float(os.environ.get("BENCH_C12_S", "2.5"))

    spec = MatchLoadSpec(clients=clients, duration_s=duration_s)
    with tempfile.TemporaryDirectory(prefix="bkw_bench_swarm_") as td:
        baseline = run_match_load(
            dataclasses.replace(spec, legacy=True), td)
        sharded = run_match_load(spec, td)
        swarm_spec = builtin_swarms()["swarm"]
        card, swarm = asyncio.run(run_swarm(swarm_spec, Path(td)))
    speedup = (sharded["matchmakings_per_s"]
               / max(baseline["matchmakings_per_s"], 1e-9))
    passed = speedup >= 2.0 and card.passed
    log(f"config#12 swarm: {clients} clients x {duration_s:.1f}s: "
        f"baseline {baseline['matchmakings_per_s']:.0f} mm/s, "
        f"sharded {sharded['matchmakings_per_s']:.0f} mm/s = "
        f"{speedup:.2f}x; http swarm p99={swarm['server_p99_ms']}ms "
        f"stall={swarm['max_stall_ms']}ms "
        f"commits_on_loop={swarm['commits_on_loop']} "
        f"[{'PASS' if passed else 'FAIL'}]")
    return {"passed": passed,
            "matchmakings_per_s": sharded["matchmakings_per_s"],
            "baseline_matchmakings_per_s": baseline["matchmakings_per_s"],
            "speedup": round(speedup, 2),
            "server_p99_ms": swarm["server_p99_ms"],
            "max_stall_ms": swarm["max_stall_ms"],
            "commits_on_loop": swarm["commits_on_loop"],
            "legs": {"baseline": baseline, "sharded": sharded},
            "swarm": swarm,
            "scorecard": card.to_dict()}


def config13_restore(log: Callable) -> Dict:
    """Serial all-holder RESTORE_ALL vs multi-source k-of-n restore — #13.

    One loopback deployment (CoordinationServer, one source, N holders),
    one striped backup, then the SAME restore twice into different
    destinations, both legs in one record:

      serial — the pre-pull-plane shape: the placement map is ignored
               (``_restore_plan`` forced to None) so every holder pushes
               its entire stream and the wall clock waits out the
               slowest; one holder's frames are armed with a per-send
               stall through the fault plane, the WAN shape where one
               seeder crawls
      multi  — the shard-granular pull planner: each stripe from its k
               fastest holders by the peer-stats estimators (the crawler
               is measured-slow, so it is a spare, not a primary), with
               a second holder killed dark between the legs so its
               re-queued pulls must land on healthier peers

    ``speedup`` is serial/multi wall (gate >= 2x), ``bytes_ratio`` is
    multi/serial sender-side bytes-on-wire (the bkw_p2p_bytes_sent_total
    delta; k/n = 4/6 floor ~= 0.67, gate <= 0.8).  Ratio measurement,
    one pass each — not a sustained-window config.
    """
    import asyncio
    import contextlib
    import shutil
    import tempfile
    from pathlib import Path

    from backuwup_tpu import defaults
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.peer_stats import PeerEstimate
    from backuwup_tpu.net.server import CoordinationServer
    from backuwup_tpu.obs import metrics as obs_metrics
    from backuwup_tpu.ops.backend import CpuBackend, NativeBackend
    from backuwup_tpu.utils import faults

    total_mib = int(os.environ.get("BENCH_C13_MIB", "2"))
    n_peers = int(os.environ.get("BENCH_C13_PEERS", "6"))
    latency_s = float(os.environ.get("BENCH_C13_LATENCY_S", "0.4"))

    saved = {k: getattr(defaults, k) for k in (
        "PACKFILE_TARGET_SIZE", "RESTORE_REQUEST_THROTTLE_S")}
    tmp = Path(tempfile.mkdtemp(prefix="bkw_bench_c13_"))
    rng = np.random.default_rng(131)
    src = tmp / "src"
    src.mkdir()
    written = 0
    i = 0
    while written < (total_mib << 20):
        sub = src / f"d{i % 8}"
        sub.mkdir(exist_ok=True)
        n = int(rng.integers(64 << 10, 256 << 10))
        (sub / f"f{i}").write_bytes(rng.bytes(n))
        written += n
        i += 1

    def wire_bytes() -> float:
        fam = obs_metrics.registry().snapshot().get(
            "bkw_p2p_bytes_sent_total") or {}
        return sum(s["value"] for s in fam.get("series", []))

    def tree_bytes(root: Path) -> int:
        return sum(p.stat().st_size for p in root.rglob("*") if p.is_file())

    async def both() -> Dict:
        plane = faults.install(faults.FaultPlane(seed=131))
        server = CoordinationServer(db_path=str(tmp / "server.db"))
        port = await server.start()

        def make_app(name):
            # native chunk+hash where available: the measurement is the
            # restore data plane, not the python oracle chunker
            params = CDCParams.from_desired(16 << 10)
            try:
                backend = NativeBackend(params)
            except Exception:
                backend = CpuBackend(params)
            app = ClientApp(config_dir=tmp / name / "cfg",
                            data_dir=tmp / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            backend=backend,
                            tls=False)  # plaintext loopback deployment
            return app

        a = make_app("a")
        a.store.set_backup_path(str(src))
        holders = [make_app(f"p{j}") for j in range(n_peers)]
        apps = [a] + holders
        try:
            for app in apps:
                await app.start()
                app._audit_task.cancel()
            a.engine.auto_repair = False
            amt = 8 * (written + (64 << 20)) // max(1, n_peers)
            for peer in holders:
                a.store.add_peer_negotiated(peer.client_id, amt)
                peer.store.add_peer_negotiated(a.client_id, amt)
                server.db.save_storage_negotiated(
                    bytes(a.client_id), bytes(peer.client_id), amt)
            snapshot = await asyncio.wait_for(a.backup(), 600)
            if not snapshot:
                raise RuntimeError("config #13: backup returned none")
            placed = sorted({bytes(peer) for _, peer, _s, idx, _ in
                             a.store.all_placements() if idx >= 0})
            if len(placed) < 3:
                raise RuntimeError(
                    f"config #13: only {len(placed)} striped holders")
            slow, dark = placed[0], placed[1]
            # seed the live estimator bank (ranking reads memory, not the
            # store): the crawling holder is measured-slow so the planner
            # leaves it as a spare; the soon-to-be-dark holder ranks
            # fastest so its failed pulls must re-queue onto the rest
            ps = a.engine.peer_stats
            with ps._lock:
                for j, peer in enumerate(placed):
                    bps = {slow: 1e3, dark: 100e6}.get(peer, (50 + j) * 1e6)
                    ps._est[peer] = PeerEstimate(
                        peer=peer, throughput_bps=bps, latency_s=0.01,
                        success=1.0, samples=10, updated=time.time())
            # slow-seeder injection: pace every file the slow holder
            # serves (both protocols — the holder is slow, period; the
            # multi leg wins by ROUTING around it, not by a kinder fault)
            slow_app = next(h for h in holders
                            if bytes(h.client_id) == slow)

            def paced(serve):
                async def run(peer_id, transport):
                    real = transport.send_file

                    async def crawl(*args, **kw):
                        await asyncio.sleep(latency_s)
                        return await real(*args, **kw)
                    transport.send_file = crawl
                    return await serve(peer_id, transport)
                return run

            slow_app.node.serve_restore = paced(
                slow_app.node.serve_restore)
            slow_app.node.serve_restore_fetch = paced(
                slow_app.node.serve_restore_fetch)

            async def one_restore(tag: str) -> Dict:
                before, t0 = wire_bytes(), time.time()
                out = await asyncio.wait_for(
                    a.restore(dest=tmp / f"out_{tag}"), 600)
                wall = time.time() - t0
                if tree_bytes(Path(out)) != written:
                    raise RuntimeError(
                        f"config #13 {tag}: restored size mismatch")
                return {"bytes_wire": round(wire_bytes() - before),
                        "wall_s": round(wall, 3)}

            legs = {}
            a.engine._restore_plan = lambda: None  # force legacy streams
            try:
                legs["serial"] = await one_restore("serial")
            finally:
                del a.engine._restore_plan
            plane.kill(dark)  # holder goes dark between the legs
            legs["multi"] = await one_restore("multi")
            legs["slow"], legs["dark"] = slow.hex()[:16], dark.hex()[:16]
            return legs
        finally:
            for app in apps:
                with contextlib.suppress(Exception):
                    await app.stop()
            await server.stop()
            faults.uninstall()

    try:
        defaults.PACKFILE_TARGET_SIZE = 128 * 1024
        defaults.RESTORE_REQUEST_THROTTLE_S = 0.0
        legs = asyncio.run(both())
        data_mib = written / (1 << 20)
        speedup = legs["serial"]["wall_s"] / legs["multi"]["wall_s"]
        ratio = legs["multi"]["bytes_wire"] / max(
            legs["serial"]["bytes_wire"], 1)
        passed = speedup >= 2.0 and ratio <= 0.8
        log(f"config#13 restore: {data_mib:.0f} MiB from {n_peers} holders "
            f"(+{latency_s * 1000:.0f}ms/frame to one): serial "
            f"{legs['serial']['wall_s']}s / multi {legs['multi']['wall_s']}s"
            f" = {speedup:.2f}x, bytes {ratio:.2f}x "
            f"[{'PASS' if passed else 'FAIL'}]")
        return {"mib_s": round(data_mib / legs["multi"]["wall_s"], 2),
                "serial_mib_s": round(data_mib / legs["serial"]["wall_s"],
                                      2),
                "speedup": round(speedup, 2),
                "bytes_ratio": round(ratio, 3),
                "passed": passed,
                "serial": legs["serial"], "multi": legs["multi"],
                "slow_holder": legs["slow"], "dark_holder": legs["dark"],
                "peers": n_peers,
                "latency_ms": round(latency_s * 1000, 1),
                "data_mib": round(data_mib, 2),
                "wall_s": round(legs["serial"]["wall_s"]
                                + legs["multi"]["wall_s"], 2)}
    finally:
        for k, v in saved.items():
            setattr(defaults, k, v)
        shutil.rmtree(tmp, ignore_errors=True)


def config15_gc(log: Callable) -> Dict:
    """Snapshot lifecycle plane: retention + GC under a crash — #15.

    Runs a dedicated GC scenario (scenario/harness.py): populate via
    backup, then a ``gc`` phase with ONE armed commit seam
    (``gc.swap.post`` — the make-before-break commit point): retention
    prunes to keep-last:1, the GC run crashes at the seam, the client
    restarts, the startup recovery sweep rolls the interrupted swap
    forward, and a clean re-run finishes reclaiming; a final ``restore``
    phase proves the post-GC world restores byte-identically.

    Hard gates (the scorecard's, restated in the record): bytes actually
    reclaimed on the holders (> 0 at both ends of the RECLAIM protocol),
    zero durability-violation seconds at every sample while packfiles
    were dropped and compacted, and the byte-identical final restore.
    ``gc_reclaim_ratio`` is reclaimed-bytes / bytes-on-wire for the whole
    run — how much of what the run shipped GC later proved dead.
    """
    import asyncio
    import tempfile
    from pathlib import Path

    from backuwup_tpu.scenario import (Phase, ScenarioSpec, builtin_scenarios,
                                       run_scenario)

    site = os.environ.get("BENCH_C15_SITE", "gc.swap.post")
    spec = ScenarioSpec(
        name="gc_bench", seed=151,
        corpus_files=builtin_scenarios()["gc"].corpus_files,
        phases=(Phase("backup"),
                Phase("gc", sites=(site,)),
                Phase("restore")))
    with tempfile.TemporaryDirectory(prefix="bkw_bench_gc_") as td:
        card = asyncio.run(run_scenario(spec, Path(td)))
    counters = card.counters
    reclaimed = sum(v for k, v in counters.items()
                    if k.startswith("bkw_gc_bytes_reclaimed_total"))
    freed = sum(v for k, v in counters.items()
                if k.startswith("bkw_reclaim_bytes_freed_total"))
    dropped = sum(v for k, v in counters.items()
                  if k.startswith("bkw_gc_packfiles_dropped_total"))
    compacted = sum(v for k, v in counters.items()
                    if k.startswith("bkw_gc_packfiles_compacted_total"))
    wire = sum(v for k, v in counters.items()
               if k.startswith("bkw_transfer_bytes_total"))
    ratio = reclaimed / max(wire, 1.0)
    violation_s = card.invariants["violation_seconds"]
    passed = card.passed and reclaimed > 0 and freed > 0 \
        and violation_s == 0
    log(f"config#15 gc '{card.scenario}' (seed {card.seed}, crash {site}):"
        f" {'PASS' if passed else 'FAIL'} in {card.elapsed_s:.1f}s, "
        f"reclaimed={reclaimed / 1024:.0f}KiB freed={freed / 1024:.0f}KiB "
        f"dropped={dropped:g} compacted={compacted:g} "
        f"ratio={ratio:.3f} violation_s={violation_s}")
    return {"passed": passed,
            "gc_reclaim_ratio": round(ratio, 4),
            "bytes_reclaimed": int(reclaimed),
            "holder_bytes_freed": int(freed),
            "packfiles_dropped": int(dropped),
            "packfiles_compacted": int(compacted),
            "violation_seconds": violation_s,
            "crash_site": site,
            "wall_s": round(card.elapsed_s, 2),
            "scorecard": card.to_dict()}


def config14_multichip(log: Callable, n_devices: int = 0) -> Dict:
    """Matched-work single-device vs mesh manifest plane — config #14.

    The SAME staged batch (``BENCH_C14_ROWS_PER_DEV`` rows per device x
    ``BENCH_C14_ROW_KIB`` KiB of random bytes) runs through the
    zero-round-trip single-device driver and through the shard-mapped
    mesh driver (:meth:`DevicePipeline.manifest_segments_mesh`) with the
    manifest->dedup handoff attached (``MeshDedupIndex``), so the record
    captures the whole production multi-chip path: per-shard leaf pools,
    per-device dispatch accounting, and device-resident classify.

    Gates enforced on EVERY platform (forced-8 CPU mesh included):

      * parity — mesh rows bit-identical to the single-device rows, and
        to the CPU oracle on a downloaded row
      * even split — per-device digest dispatch counts within +-1
      * handoff — index-stage dispatches == device batches (classify
        rides ``insert_device``; zero per-batch host round trips), and
        the device found-vector classifies the warmed corpus duplicate

    The wall-clock gate (``speedup >= BENCH_C14_SPEEDUP_GATE``, default
    1.5) arms only on real hardware: a forced-8-device CPU "mesh"
    timeshares one host core pool, so its speedup measures shard_map
    overhead, not scale.
    """
    import pathlib
    import shutil
    import tempfile

    from jax.sharding import Mesh

    from backuwup_tpu.crypto import KeyManager
    from backuwup_tpu.obs import profile as obs_profile
    from backuwup_tpu.snapshot.blob_index import BlobIndex
    from backuwup_tpu.snapshot.device_dedup import MeshDedupIndex

    n_dev = n_devices or int(os.environ.get("BENCH_C14_DEVICES", "8"))
    n_dev = max(1, min(n_dev, jax.device_count()))
    rows_per_dev = int(os.environ.get("BENCH_C14_ROWS_PER_DEV", "2"))
    P = int(os.environ.get("BENCH_C14_ROW_KIB", "1024")) << 10
    B = n_dev * rows_per_dev
    params = CDCParams.from_desired(16 << 10)
    pass_mib = B * P / (1 << 20)

    pipe1 = DevicePipeline(params)
    if not pipe1.pool_digest:
        log("config#14: leaf-pool digest unavailable; mesh plane skipped")
        return {"skipped": "pool_digest unavailable"}

    rng = np.random.default_rng(141)
    buf = np.zeros((B, _HALO + P), dtype=np.uint8)
    buf[:, _HALO:] = rng.integers(0, 256, (B, P), dtype=np.uint8)
    nv = np.full(B, P, dtype=np.int32)
    buf1 = jnp.asarray(buf)

    # --- leg 1: single device, zero-round-trip driver ---------------------
    (single,) = list(pipe1.manifest_segments_device(
        [(buf1, nv)], strict_overflow=True))  # warm + parity reference
    w1 = SustainedWindow(2)
    for _ in w1.passes():
        for _rows in pipe1.manifest_segments_device([(buf1, nv)],
                                                    strict_overflow=True):
            pass
    mibs1 = w1.count * pass_mib / w1.wall

    # --- leg 2: mesh driver + device-resident dedup handoff ---------------
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="bkw_bench_c14_"))
    try:
        dedup = MeshDedupIndex(
            mesh, BlobIndex(KeyManager.from_secret(b"\x0e" * 32),
                            tmp / "index"))
        pipe_n = DevicePipeline(params, mesh=mesh)
        ((mesh_rows, _fl),) = list(pipe_n.manifest_segments_mesh(
            [(buf, nv)], strict_overflow=True, dedup=dedup))  # warm
        for r in range(B):
            if mesh_rows[r][0] != single[r][0] or not np.array_equal(
                    mesh_rows[r][1], single[r][1]):
                raise RuntimeError("config #14: mesh/single parity FAILED")
        _check(mesh_rows[0], bytes(buf[0, _HALO:]), params, "#14")

        base = obs_profile.baseline()
        batches = 0
        dup_flags_ok = True
        w2 = SustainedWindow(2)
        for _ in w2.passes():
            for _rows, flags in pipe_n.manifest_segments_mesh(
                    [(buf, nv)], strict_overflow=True, dedup=dedup):
                batches += 1
                for fl in flags:
                    # the warm pass made every key resident: the device
                    # found-vector must classify all-duplicate
                    if fl is None or not all(bool(x) for x in fl):
                        dup_flags_ok = False
        mibs_n = w2.count * pass_mib / w2.wall
        rep = obs_profile.report(base)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    dev_disp = rep.get("device_dispatches", {})
    digest_counts = [dev_disp.get(str(d), {}).get("digest", 0)
                     for d in range(n_dev)]
    delta = max(digest_counts) - min(digest_counts)
    if delta > 1:
        raise RuntimeError(f"config #14: uneven shard split {digest_counts}")
    if rep["dispatches"]["index"] != batches:
        raise RuntimeError(
            f"config #14: handoff made host round trips "
            f"({rep['dispatches']['index']} index dispatches for "
            f"{batches} batches)")
    for d in range(n_dev):
        if dev_disp.get(str(d), {}).get("index", 0) != batches:
            raise RuntimeError(
                f"config #14: device {d} index dispatches "
                f"{dev_disp.get(str(d), {}).get('index', 0)} != {batches}")
    if not dup_flags_ok:
        raise RuntimeError("config #14: device classify missed residency")

    speedup = mibs_n / mibs1 if mibs1 > 0 else 0.0
    gate = float(os.environ.get("BENCH_C14_SPEEDUP_GATE", "1.5"))
    armed = jax.devices()[0].platform != "cpu"
    if armed and speedup < gate:
        raise RuntimeError(
            f"config #14: multichip speedup {speedup:.2f}x < {gate}x")
    log(f"config#14 multichip: 1dev {mibs1:.1f} MiB/s vs {n_dev}dev "
        f"{mibs_n:.1f} MiB/s = {speedup:.2f}x "
        f"({'gate armed' if armed else 'gate recorded only, CPU mesh'}; "
        f"digest split {digest_counts})")
    return {"n_devices": n_dev, "mib_s_1dev": round(mibs1, 2),
            "mib_s_mesh": round(mibs_n, 2), "speedup": round(speedup, 3),
            "speedup_gate_armed": armed,
            "device_dispatches": dev_disp,
            "device_pad_efficiency": rep.get("device_pad_efficiency", {}),
            "even_split_max_delta": delta,
            "index_dispatches": rep["dispatches"]["index"],
            "batches": batches,
            "hbm_high_water_bytes": max(
                pipe_n.mesh_hbm_high_water.values(), default=0),
            "wall_s": round(w1.wall + w2.wall, 2)}


def config16_federation(log: Callable) -> Dict:
    """Federated coordination plane — config #16.

    Two measurements land in ONE record:

    * **scaling legs** — the SAME seeded client universe driven at
      1, 2, and 4 nodes, each node a real OS process with its own
      ServerStore partition file and real ``/fed/steal`` HTTP between
      processes (scenario/federation.py).  ``federation_speedup_2node``
      / ``_4node`` are always recorded; the throughput gates
      (≥ ``BENCH_C16_SPEEDUP_GATE_2`` = 1.6x at 2 nodes,
      ≥ ``BENCH_C16_SPEEDUP_GATE_4`` = 2.8x at 4 nodes) arm only when
      the host has ≥ 4 CPUs (or ``BENCH_C16_FORCE_GATE=1``): node
      processes timesharing one core measure scheduler overhead, not
      scale — the config-14 precedent.
    * **churn evidence** — the full HTTP federation swarm (3 nodes over
      one partitioned store, client failover, a node kill + same-port
      revive mid-run), embedding the scorecard whose hard gates assert
      zero lost matchmakings (durable negotiation rows ≥ 2x total
      matchmakings across every partition), post-revive matchmaking
      flow, at least one client failover, and bounded per-route p99.
    """
    import asyncio
    import dataclasses
    import tempfile
    from pathlib import Path

    from backuwup_tpu.scenario import builtin_swarms, run_swarm
    from backuwup_tpu.scenario.federation import (FederationLoadSpec,
                                                  run_federation_load)

    clients = int(os.environ.get("BENCH_C16_CLIENTS", "64"))
    duration_s = float(os.environ.get("BENCH_C16_S", "2.0"))
    spec = FederationLoadSpec(nodes=1, clients=clients,
                              duration_s=duration_s)
    legs = {}
    with tempfile.TemporaryDirectory(prefix="bkw_bench_fed_") as td:
        for n in (1, 2, 4):
            legs[n] = run_federation_load(
                dataclasses.replace(spec, nodes=n), Path(td) / f"n{n}")
        card, swarm = asyncio.run(run_swarm(
            builtin_swarms()["federation"], Path(td) / "churn"))
    base = max(legs[1]["matchmakings_per_s"], 1e-9)
    speedup2 = legs[2]["matchmakings_per_s"] / base
    speedup4 = legs[4]["matchmakings_per_s"] / base
    gate2 = float(os.environ.get("BENCH_C16_SPEEDUP_GATE_2", "1.6"))
    gate4 = float(os.environ.get("BENCH_C16_SPEEDUP_GATE_4", "2.8"))
    armed = ((os.cpu_count() or 1) >= 4
             or os.environ.get("BENCH_C16_FORCE_GATE") == "1")
    scaling_ok = (not armed) or (speedup2 >= gate2 and speedup4 >= gate4)
    passed = scaling_ok and card.passed
    mode = "gates armed" if armed else "gates recorded only, few-core host"
    log(f"config#16 federation: {clients} clients x {duration_s:.1f}s: "
        f"1n {legs[1]['matchmakings_per_s']:.0f} mm/s, "
        f"2n {legs[2]['matchmakings_per_s']:.0f} ({speedup2:.2f}x), "
        f"4n {legs[4]['matchmakings_per_s']:.0f} ({speedup4:.2f}x) "
        f"({mode}); churn swarm: "
        f"failovers={swarm['failovers']} rows={swarm['negotiated_rows']} "
        f"mm={swarm['total_matchmakings']} p99={swarm['server_p99_ms']}ms "
        f"[{'PASS' if passed else 'FAIL'}]")
    return {"passed": passed,
            "federation_speedup_2node": round(speedup2, 2),
            "federation_speedup_4node": round(speedup4, 2),
            "speedup_gate_armed": armed,
            "matchmakings_per_s_1node": legs[1]["matchmakings_per_s"],
            "matchmakings_per_s_2node": legs[2]["matchmakings_per_s"],
            "matchmakings_per_s_4node": legs[4]["matchmakings_per_s"],
            "steals_2node": legs[2]["steals"],
            "steals_4node": legs[4]["steals"],
            "churn_failovers": swarm["failovers"],
            "churn_negotiated_rows": swarm["negotiated_rows"],
            "churn_total_matchmakings": swarm["total_matchmakings"],
            "server_p99_ms": swarm["server_p99_ms"],
            "legs": {f"{n}node": legs[n] for n in (1, 2, 4)},
            "swarm": swarm,
            "scorecard": card.to_dict()}


def config17_tiered(log: Callable) -> Dict:
    """Tiered dedup index — config #17 (docs/dedup_tiering.md).

    One ``TieredDedupIndex`` is populated to ~12x its HBM budget (the
    hot table is HARD-capped; the overflow demotes into the cold LSM
    store), then probed through two legs:

    * **skewed** — ``BENCH_C17_HOT_FRACTION`` (default 0.97) of every
      batch drawn from a working set sized to fit the hot table, the
      rest uniform over the whole population (the real-corpus shape:
      incremental backups re-probe recent fingerprints)
    * **uniform** — batches drawn uniformly over the population, the
      adversarial shape that must fall through to the cold tier

    Gates enforced on EVERY platform (CPU mesh included — all three
    are deterministic counting/parity claims, not wall clock):

      * parity — every classification during population bit-identical
        to the BlobIndex oracle, and a post-population sample must
        classify all-duplicate while fresh keys classify all-new
      * budget — ``bkw_tier_hbm_highwater_bytes`` never exceeds the
        budget while the population is >= 10x the hot slot count
      * hit rate — the skewed leg answers > ``BENCH_C17_HIT_GATE``
        (default 0.95) of its device probes on device
        (``bkw_tier_hits/probes_total{path=device}`` deltas — the
        ROADMAP's >95% device-path claim, surfaced as
        ``tiered_hit_rate``)

    The wall gate (skewed leg >= ``BENCH_C17_WALL_GATE`` x the uniform
    leg's probe throughput, default 1.2) arms only on real hardware:
    a forced CPU mesh timeshares the host with the cold tier's numpy
    path, so the ratio measures dispatch overhead, not HBM locality.
    """
    import pathlib
    import shutil
    import tempfile

    from jax.sharding import Mesh

    from backuwup_tpu.crypto import KeyManager
    from backuwup_tpu.dedupstore import TieredDedupIndex
    from backuwup_tpu.obs import metrics as obs_metrics
    from backuwup_tpu.snapshot.blob_index import BlobIndex

    def _tier(name, **labels):
        m = obs_metrics.registry().get(name)
        return 0.0 if m is None else m.value(**labels)

    n_dev = max(1, min(int(os.environ.get("BENCH_C17_DEVICES", "8")),
                       jax.device_count()))
    population = int(os.environ.get("BENCH_C17_POPULATION", "200000"))
    batch = int(os.environ.get("BENCH_C17_BATCH", "4096"))
    hot_frac = float(os.environ.get("BENCH_C17_HOT_FRACTION", "0.97"))
    # budget sized so the population overflows the hot table ~12x
    budget = max(population // 12, n_dev * 64) * 20
    rng = np.random.default_rng(171)
    hashes = [t.tobytes()
              for t in rng.integers(0, 256, (population, 32),
                                    dtype=np.uint8)]
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="bkw_bench_c17_"))
    try:
        host = BlobIndex(KeyManager.from_secret(b"\x11" * 32),
                         tmp / "index")
        ti = TieredDedupIndex(mesh, host, cold_dir=tmp / "cold",
                              hbm_budget_bytes=budget,
                              promote_min_hits=1)
        total_slots = mesh.shape["data"] * ti.capacity
        if population < 10 * total_slots:
            raise RuntimeError(
                f"config #17: population {population} < 10x hot slots "
                f"{total_slots} — overflow claim would not be tested")
        # --- populate to ~12x budget, parity-gated against the oracle
        mismatches = 0
        for s in range(0, population, 8192):
            seg = hashes[s:s + 8192]
            for h, f in zip(seg, ti.classify_insert(seg)):
                if f != host.is_duplicate(h):
                    mismatches += 1
                host.mark_queued(h)
        if mismatches:
            raise RuntimeError(
                f"config #17: {mismatches} oracle parity mismatches")
        if _tier("bkw_tier_hbm_highwater_bytes") > budget:
            raise RuntimeError("config #17: HBM budget exceeded")
        # --- skewed leg: working set sized to the demotion keep-set
        # (a quarter of the table) so churn from the uniform tail
        # cannot push it out of HBM.  The hot lanes scan the set in
        # rotation (an incremental re-backup re-probes every recent
        # fingerprint, not a with-replacement sample — replacement
        # would collapse hot lanes under per-batch dedup and inflate
        # the tail's unique-lane share ~2x past ``1 - hot_frac``).
        hot_n = max(total_slots // 4, batch)
        hot_set = [hashes[i] for i in rng.integers(0, population, hot_n)]
        for s in range(0, hot_n, batch):  # warm: promote the hot set
            ti.classify_insert(hot_set[s:s + batch])
        d0, h0 = (_tier("bkw_tier_probes_total", path="device"),
                  _tier("bkw_tier_hits_total", path="device"))
        w1 = SustainedWindow(4)
        cursor = 0
        for _ in w1.passes():
            n_hot = int(batch * hot_frac)
            leg = [hot_set[(cursor + i) % hot_n] for i in range(n_hot)]
            cursor = (cursor + n_hot) % hot_n
            leg += [hashes[int(i)] for i in
                    rng.integers(0, population, batch - n_hot)]
            if not all(ti.classify_insert(leg)):
                raise RuntimeError("config #17: skewed leg parity FAILED")
        d1, h1 = (_tier("bkw_tier_probes_total", path="device"),
                  _tier("bkw_tier_hits_total", path="device"))
        hit_rate = (h1 - h0) / max(d1 - d0, 1.0)
        skew_pps = w1.count * batch / w1.wall
        # --- uniform leg: the cold tier carries the tail
        w2 = SustainedWindow(4)
        for _ in w2.passes():
            leg = [hashes[int(i)] for i in
                   rng.integers(0, population, batch)]
            if not all(ti.classify_insert(leg)):
                raise RuntimeError("config #17: uniform leg parity FAILED")
        uni_pps = w2.count * batch / w2.wall
        # --- fresh keys still classify new after all the churn
        fresh = [t.tobytes() for t in
                 rng.integers(0, 256, (batch, 32), dtype=np.uint8)]
        if any(ti.classify_insert(fresh)):
            raise RuntimeError("config #17: fresh keys misclassified")
        if _tier("bkw_tier_hbm_highwater_bytes") > budget:
            raise RuntimeError("config #17: HBM budget exceeded post-legs")
        hit_gate = float(os.environ.get("BENCH_C17_HIT_GATE", "0.95"))
        if hit_rate < hit_gate:
            raise RuntimeError(
                f"config #17: device hit rate {hit_rate:.3f} < {hit_gate}")
        speedup = skew_pps / max(uni_pps, 1e-9)
        wall_gate = float(os.environ.get("BENCH_C17_WALL_GATE", "1.2"))
        armed = jax.devices()[0].platform != "cpu"
        if armed and speedup < wall_gate:
            raise RuntimeError(
                f"config #17: skewed/uniform {speedup:.2f}x < {wall_gate}x")
        mode = ("wall gate armed" if armed
                else "wall gate recorded only, CPU mesh")
        log(f"config#17 tiered: {population} keys @ {total_slots} hot "
            f"slots ({population / total_slots:.0f}x): skewed "
            f"{skew_pps / 1e3:.0f}k probes/s hit {hit_rate:.3f}, uniform "
            f"{uni_pps / 1e3:.0f}k probes/s = {speedup:.2f}x ({mode}; "
            f"demotions {int(_tier('bkw_tier_demotions_total'))}, "
            f"promotions {int(_tier('bkw_tier_promotions_total'))}, "
            f"cold runs {int(_tier('bkw_tier_cold_runs'))})")
        return {"population": population,
                "hot_slots": total_slots,
                "overflow_ratio": round(population / total_slots, 1),
                "hbm_budget_bytes": budget,
                "hbm_highwater_bytes":
                    int(_tier("bkw_tier_hbm_highwater_bytes")),
                "tiered_hit_rate": round(hit_rate, 4),
                "hit_gate": hit_gate,
                "parity_mismatches": mismatches,
                "probes_per_s_skewed": round(skew_pps, 1),
                "probes_per_s_uniform": round(uni_pps, 1),
                "skew_speedup": round(speedup, 3),
                "wall_gate_armed": armed,
                "demotions": int(_tier("bkw_tier_demotions_total")),
                "promotions": int(_tier("bkw_tier_promotions_total")),
                "cold_runs": int(_tier("bkw_tier_cold_runs")),
                "cold_records": int(_tier("bkw_tier_cold_records")),
                "wall_s": round(w1.wall + w2.wall, 2)}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def config18_replication(log: Callable) -> Dict:
    """Replicated coordination metadata — config #18 (docs/server.md
    §Replication).

    Two swarm runs land in ONE record:

    * **permakill leg** — the builtin ``replication`` swarm: 3 nodes
      with PER-NODE ``ReplicatedServerStore``s (nothing shared), a
      partition-owning node killed for good mid-run.  Hard gates ride
      the scorecard: a ring successor promoted within the probe
      deadline, matchmaking flowed post-promotion, and zero durable
      negotiation rows lost (``replication_lost_rows`` is recorded
      top-level and must be 0 — the rows' only applier is gone, so
      every surviving row crossed the ship-before-ack barrier).
    * **shared-store baseline** — the SAME spec (clients, think time,
      total load-window duration) with one shared partitioned store
      behind the nodes and no kill: the only differences from the
      permakill leg are the ship barrier and the death, so the rate
      ratio prices the synchronous log ship.  Recorded, not gated —
      one-core hosts measure scheduler noise, the config-16 precedent.
    """
    import asyncio
    import dataclasses
    import tempfile
    from pathlib import Path

    from backuwup_tpu.scenario import Phase, builtin_swarms, run_swarm

    spec = builtin_swarms()["replication"]
    load_s = sum(p.duration_s or 0.0 for p in spec.phases)
    baseline = dataclasses.replace(
        spec, name="replication_shared_baseline", shared_store=True,
        phases=(Phase("register"), Phase("swarm", duration_s=load_s),
                Phase("drain")))
    with tempfile.TemporaryDirectory(prefix="bkw_bench_repl_") as td:
        repl_card, repl = asyncio.run(run_swarm(spec, Path(td) / "repl"))
        base_card, base = asyncio.run(run_swarm(
            baseline, Path(td) / "shared"))
    lost = max(0, 2 * repl["total_matchmakings"]
               - repl["negotiated_rows"])
    repl_rate = repl["total_matchmakings"] / max(repl_card.elapsed_s,
                                                 1e-9)
    base_rate = base["total_matchmakings"] / max(base_card.elapsed_s,
                                                 1e-9)
    passed = (repl_card.passed and base_card.passed and lost == 0
              and repl["promotions"] >= 1)
    log(f"config#18 replication: permakill leg "
        f"mm={repl['total_matchmakings']} rows={repl['negotiated_rows']}"
        f" lost={lost} promote={repl['repl_promote_s']}s"
        f" ({repl_rate:.0f} mm/s); shared baseline "
        f"mm={base['total_matchmakings']} ({base_rate:.0f} mm/s, "
        f"ship cost {repl_rate / max(base_rate, 1e-9):.2f}x) "
        f"[{'PASS' if passed else 'FAIL'}]")
    return {"passed": passed,
            "replication_lost_rows": lost,
            "repl_promote_s": repl["repl_promote_s"],
            "promotions": repl["promotions"],
            "post_promote_matchmakings":
                repl["post_promote_matchmakings"],
            "matchmakings_per_s_replicated": round(repl_rate, 2),
            "matchmakings_per_s_shared": round(base_rate, 2),
            "ship_cost_ratio": round(repl_rate / max(base_rate, 1e-9),
                                     3),
            "server_p99_ms": repl["server_p99_ms"],
            "swarm": repl,
            "baseline_swarm": base,
            "scorecard": repl_card.to_dict()}


def config19_sim(log: Callable) -> Dict:
    """Virtual-clock simulation plane — config #19 (docs/simulation.md).

    Two legs land in one record:

    * **throughput leg** — the tier-1 acceptance builtin
      (``regionfail``: 10⁵ clients, a simulated week, a quarter of the
      regions lost on day 2) at full scale, real matchmaking and
      serverstore on the virtual clock.  Records driver events/s and
      the time-compression ratio (virtual seconds per wall second).
      Hard gates: the scenario's own scorecard all green AND
      compression ≥ ``BENCH_C19_COMPRESSION_GATE`` (default 10⁴× — a
      simulated week inside about a wall minute).
    * **determinism leg** — ``flashcrowd`` at 2 000 clients twice with
      the same seed: the scorecards must be byte-identical
      (``card_json``), the replay contract triage leans on.
    """
    from backuwup_tpu.sim import card_json, run_sim

    clients = int(os.environ.get("BENCH_C19_CLIENTS", "100000"))
    gate = float(os.environ.get("BENCH_C19_COMPRESSION_GATE", "10000"))
    card, stats = run_sim("regionfail", clients=clients)
    d1, _ = run_sim("flashcrowd", clients=2000)
    d2, _ = run_sim("flashcrowd", clients=2000)
    deterministic = card_json(d1) == card_json(d2)
    passed = (card["passed"] and deterministic
              and stats["time_compression"] >= gate)
    log(f"config#19 sim: regionfail@{clients} simulated "
        f"{card['sim_seconds'] / 86400:.0f}d in {stats['wall_s']}s "
        f"({stats['events_per_s']:.0f} ev/s, "
        f"{stats['time_compression']:.0f}x compression vs gate "
        f"{gate:.0f}x) gates={'green' if card['passed'] else 'RED'} "
        f"determinism={'ok' if deterministic else 'BROKEN'} "
        f"[{'PASS' if passed else 'FAIL'}]")
    return {"passed": passed,
            "sim_events_per_s": stats["events_per_s"],
            "sim_time_compression": stats["time_compression"],
            "sim_wall_s": stats["wall_s"],
            "sim_events": card["events"],
            "deterministic": deterministic,
            "match_rate": card["match_rate"],
            "repair_drain_s": card["repair_drain_s"],
            "violation_client_seconds": card["violation_client_seconds"],
            "scorecard": card}


def config20_dataflow(log: Callable) -> Dict:
    """Streaming dataflow vs phased backup — config #20 (docs/dataflow.md).

    The SAME end-to-end backup (one source, N holders over loopback,
    fault-plane latency on every send so the wire leg is comparable to
    the pack leg on a one-core host) runs twice over identical corpora:

      phased — ``BKW_BACKUP_PHASED=1``: the send loop starts only after
               the packer finishes, wall = sum(stage), the pre-dataflow
               shape
      stream — shipped default: sealed packfiles enter transfer
               admission the moment they commit, wall -> max(stage)

    Gates (both hard):
      * stream overlap efficiency ≥ ``BENCH_C20_EFFICIENCY_GATE``
        (default 0.8, i.e. wall ≤ 1.25 x max per-stage busy seconds)
      * phased_wall / stream_wall ≥ ``BENCH_C20_SPEEDUP_GATE`` (1.5)

    Plus a correctness gate: both legs must produce the SAME snapshot
    id — the root hash is content-addressed, so streaming emission
    (lag-bounded partial packfiles, docs/dataflow.md) must be
    byte-invisible in the snapshot.
    """
    import asyncio
    import shutil
    import tempfile
    from pathlib import Path

    from backuwup_tpu import defaults
    from backuwup_tpu.app import ClientApp
    from backuwup_tpu.net.server import CoordinationServer
    from backuwup_tpu.ops.backend import CpuBackend, NativeBackend
    from backuwup_tpu.utils import faults

    # 32 MiB / 8 ms tuned so pack wall and send wall are the same order
    # on a 1-core CPU runner (~6s each): smaller corpora make pack
    # trivially cheap (overlap can't show) and higher latency makes
    # send dominate both legs (speedup ceiling falls toward 1.0)
    total_mib = int(os.environ.get("BENCH_C20_MIB", "32"))
    n_peers = int(os.environ.get("BENCH_C20_PEERS", "6"))
    latency_s = float(os.environ.get("BENCH_C20_LATENCY_S", "0.008"))
    eff_gate = float(os.environ.get("BENCH_C20_EFFICIENCY_GATE", "0.8"))
    speedup_gate = float(os.environ.get("BENCH_C20_SPEEDUP_GATE", "1.5"))

    # ACK_TIMEOUT_S: the injected per-send latency queues behind per-peer
    # ordering, so a late ack is latency backlog, not a dead link — with
    # the 5 s production floor the stall detector aborts ~1% of sends
    # into resume retries and the measured walls pick up seconds of noise
    saved = {k: getattr(defaults, k) for k in ("PACKFILE_TARGET_SIZE",
                                               "ACK_TIMEOUT_S")}
    tmp = Path(tempfile.mkdtemp(prefix="bkw_bench_c20_"))
    rng = np.random.default_rng(20)
    src = tmp / "src"
    src.mkdir()
    written = 0
    i = 0
    # Small-file-heavy corpus with a sprinkle of multi-chunk large files:
    # per-file pack cost (chunk boundaries, manifest rows, dedup probes)
    # is what gives the chunk/seal/write stages real wall time to overlap
    # against the latency-bound send stage — a few big files would make
    # pack trivially cheap and the overlap gate meaningless on CPU.
    while written < (total_mib << 20):
        sub = src / f"d{i % 6}"
        sub.mkdir(exist_ok=True)
        n = int(rng.integers(256 << 10, 768 << 10)) if i % 16 == 0 \
            else int(rng.integers(4 << 10, 32 << 10))
        (sub / f"f{i}").write_bytes(rng.bytes(n))
        written += n
        i += 1

    async def one_backup(tag: str):
        server = CoordinationServer(db_path=str(tmp / f"server_{tag}.db"))
        port = await server.start()

        def make_app(name):
            params = CDCParams.from_desired(16 << 10)
            try:
                backend = NativeBackend(params)
            except Exception:
                backend = CpuBackend(params)
            app = ClientApp(config_dir=tmp / tag / name / "cfg",
                            data_dir=tmp / tag / name / "data",
                            server_addr=f"127.0.0.1:{port}",
                            backend=backend,
                            tls=False)  # plaintext loopback deployment
            app.store.set_backup_path(str(src))
            return app

        a = make_app("a")
        holders = [make_app(f"p{j}") for j in range(n_peers)]
        apps = [a] + holders
        try:
            for app in apps:
                await app.start()
                app._audit_task.cancel()
            a.engine.auto_repair = False
            amt = 8 * (written + (64 << 20)) // max(1, n_peers)
            for peer in holders:
                a.store.add_peer_negotiated(peer.client_id, amt)
                peer.store.add_peer_negotiated(a.client_id, amt)
                server.db.save_storage_negotiated(
                    bytes(a.client_id), bytes(peer.client_id), amt)
            snapshot = await asyncio.wait_for(a.backup(), 600)
            if not snapshot:
                raise RuntimeError(f"config #20 {tag}: backup returned none")
            overlap = dict(a.engine.last_overlap or {})
            return bytes(snapshot), overlap
        finally:
            for app in apps:
                try:
                    await app.stop()
                except Exception:
                    pass
            await server.stop()

    async def both() -> Dict:
        defaults.PACKFILE_TARGET_SIZE = 128 * 1024
        defaults.ACK_TIMEOUT_S = 60.0
        # unmeasured warmup leg: eat the jit-compile walls once so the
        # phased leg (which runs first) is not charged for them
        await one_backup("warm")
        faults.install(faults.FaultPlane(seed=20, latency=1.0,
                                         latency_s=latency_s))
        try:
            # best-of-2 per leg: a 1-core runner's scheduler hiccups land
            # on one leg at a time, so min-wall per mode compares the
            # modes rather than the runner's worst moment.  Snapshot
            # parity must hold across EVERY leg, best or not.
            snaps_p, snaps_s = [], []
            phased = stream = None
            for rep in range(2):
                os.environ["BKW_BACKUP_PHASED"] = "1"
                try:
                    snap_p, leg_p = await one_backup(f"phased{rep}")
                finally:
                    os.environ.pop("BKW_BACKUP_PHASED", None)
                snaps_p.append(snap_p)
                if phased is None or leg_p["wall_s"] < phased["wall_s"]:
                    phased = leg_p
                snap_s, leg_s = await one_backup(f"stream{rep}")
                snaps_s.append(snap_s)
                if stream is None or leg_s["wall_s"] < stream["wall_s"]:
                    stream = leg_s
            return {"snaps_phased": snaps_p, "snaps_stream": snaps_s,
                    "phased": phased, "stream": stream}
        finally:
            faults.uninstall()

    try:
        r = asyncio.run(both())
        data_mib = written / (1 << 20)
        phased, stream = r["phased"], r["stream"]
        speedup = phased["wall_s"] / max(stream["wall_s"], 1e-9)
        efficiency = stream["overlap_efficiency"]
        identical = len(set(r["snaps_phased"] + r["snaps_stream"])) == 1
        passed = (identical and efficiency >= eff_gate
                  and speedup >= speedup_gate)
        log(f"config#20 dataflow: {data_mib:.0f} MiB to {n_peers} peers "
            f"(+{latency_s * 1000:.0f}ms/send): phased "
            f"{phased['wall_s']:.2f}s -> stream {stream['wall_s']:.2f}s "
            f"= {speedup:.2f}x (gate {speedup_gate}x), overlap "
            f"{efficiency:.2f} (gate {eff_gate}), snapshot "
            f"{'identical' if identical else 'DIVERGED'} "
            f"[{'PASS' if passed else 'FAIL'}]")
        return {"passed": passed,
                "mib_s": round(data_mib / stream["wall_s"], 2),
                "dataflow_overlap_efficiency": round(efficiency, 4),
                "dataflow_speedup": round(speedup, 2),
                "snapshot_identical": identical,
                "phased_wall_s": round(phased["wall_s"], 3),
                "stream_wall_s": round(stream["wall_s"], 3),
                "stream_stage_busy_s": stream["stage_busy_s"],
                "phased_stage_busy_s": phased["stage_busy_s"],
                "peers": n_peers,
                "latency_ms": round(latency_s * 1000, 1),
                "wall_s": round(phased["wall_s"] + stream["wall_s"], 2)}
    finally:
        for k, v in saved.items():
            setattr(defaults, k, v)
        shutil.rmtree(tmp, ignore_errors=True)


def config21_slo(log: Callable) -> Dict:
    """Live SLO plane: detection latency + explainer precision — #21.

    Two legs land in one record (docs/observability.md §SLOs):

    * **detection leg** — the ``diagnosis`` scenario (scenario/
      harness.py): a quiet pre-fault baseline, then three of six
      holders permanently dark — below RS k, so durability flips
      violated and the shrunken fast burn windows must fire.  Hard
      gates: the scenario's own scorecard all green, breach detection
      within ``BENCH_C21_DETECTION_GATE`` seconds of the first violated
      sample (default 1.0 — two patched sweep intervals), and explainer
      precision 1.0 (zero pre-fault breaches, the armed fault site in
      the top-3 causes).
    * **determinism leg** — the ``regionfail`` sim at 2 000 clients /
      3 virtual days twice with the same seed: the cards — burn ticks,
      breach times, the ranked diagnosis — must be byte-identical
      (``card_json``), so a paged operator can replay the exact
      incident.
    """
    import asyncio
    import tempfile
    from pathlib import Path

    from backuwup_tpu.scenario import builtin_scenarios
    from backuwup_tpu.scenario.harness import ScenarioHarness
    from backuwup_tpu.sim import card_json, run_sim

    detect_gate = float(os.environ.get("BENCH_C21_DETECTION_GATE", "1.0"))
    spec = builtin_scenarios()["diagnosis"]

    async def one_run(td: str):
        harness = ScenarioHarness(spec, Path(td))
        await harness.setup()
        try:
            card = await harness.run()
        finally:
            await harness.teardown()
        return dict(harness.facts.get("slo") or {}), card

    with tempfile.TemporaryDirectory(prefix="bkw_bench_slo_") as td:
        slo, card = asyncio.run(one_run(td))

    days3 = 3 * 86400.0
    d1, _ = run_sim("regionfail", clients=2000, sim_seconds=days3)
    d2, _ = run_sim("regionfail", clients=2000, sim_seconds=days3)
    deterministic = card_json(d1) == card_json(d2)

    detect_s = slo.get("detection_s")
    precision = slo.get("precision")
    passed = (card.passed and deterministic
              and detect_s is not None and detect_s <= detect_gate
              and precision == 1.0)
    log(f"config#21 slo: diagnosis scenario "
        f"{'green' if card.passed else 'RED'} detection={detect_s}s "
        f"(gate {detect_gate}s) precision={precision} "
        f"breaches={slo.get('breaches')} sim_determinism="
        f"{'ok' if deterministic else 'BROKEN'} "
        f"[{'PASS' if passed else 'FAIL'}]")
    return {"passed": passed,
            "slo_detection_s": detect_s,
            "slo_precision": precision,
            "slo_breaches": slo.get("breaches", 0),
            "top_causes": slo.get("top_causes", []),
            "deterministic": deterministic,
            "sim_slo_status": (d1.get("slo") or {}).get("status"),
            "wall_s": round(card.elapsed_s, 2),
            "scorecard": card.to_dict()}


def run_all(pipeline: DevicePipeline, params: CDCParams, cpu_mibs: float,
            log: Callable) -> Dict:
    out = {}
    for name, fn in (
            ("2_small_files", lambda: config2_small_files(pipeline, params,
                                                          log)),
            ("3_incremental", lambda: config3_incremental(pipeline, params,
                                                          log)),
            ("4_large_stream_64k", lambda: config4_large_stream(log)),
            ("5_cross_peer_dedup", lambda: config5_cross_peer(log)),
            ("6_end_to_end", lambda: config6_end_to_end(log)),
            ("7_erasure", lambda: config7_erasure(log)),
            ("8_transfer", lambda: config8_transfer(log)),
            ("9_scenario", lambda: config9_scenario(log)),
            ("10_wan", lambda: config10_wan(log)),
            ("11_crash", lambda: config11_crash(log)),
            ("12_swarm", lambda: config12_swarm(log)),
            ("13_restore", lambda: config13_restore(log)),
            ("14_multichip", lambda: config14_multichip(log)),
            ("15_gc", lambda: config15_gc(log)),
            ("16_federation", lambda: config16_federation(log)),
            ("17_tiered", lambda: config17_tiered(log)),
            ("18_replication", lambda: config18_replication(log)),
            ("19_sim", lambda: config19_sim(log)),
            ("20_dataflow", lambda: config20_dataflow(log)),
            ("21_slo", lambda: config21_slo(log))):
        # BENCH_ONLY_CONFIG=<substring> re-runs a single config (the
        # tpu_watch.sh recapture path re-measures just "7_erasure")
        only = os.environ.get("BENCH_ONLY_CONFIG", "")
        if only and only not in name:
            continue
        try:
            out[name] = fn()
            if "mib_s" in out[name]:
                out[name]["vs_baseline"] = round(
                    out[name]["mib_s"] / cpu_mibs, 2)
        except Exception as e:  # a config failure must not kill the JSON
            log(f"config {name} FAILED: {e}")
            out[name] = {"error": str(e)[:200]}
    return out
