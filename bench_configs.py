"""BASELINE.md benchmark configs #2-#5 (config #1 is bench.py's main loop).

Each config times the production device pipeline on a device-synthesized
corpus shaped like the BASELINE workload and gates the numbers on
bit-parity with the CPU oracle over a small downloaded subset (speed
without identical dedup output is meaningless):

  #2  many small files    — the vmapped per-directory batch path
  #3  two-snapshot overlap — incremental re-chunk, high dedup
  #4  large stream         — 64 KiB average chunks (VM-image profile)
  #5  cross-peer global dedup — sharded HBM index over the device mesh

Environment knobs: BENCH_C2_MIB, BENCH_C3_MIB, BENCH_C4_MIB, BENCH_C5_HASHES.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from backuwup_tpu.ops import cdc_cpu
from backuwup_tpu.ops.blake3_cpu import Blake3Numpy
from backuwup_tpu.ops.cdc_tpu import _HALO, _segment_bucket
from backuwup_tpu.ops.gear import CDCParams
from backuwup_tpu.ops.pipeline import DevicePipeline


def _oracle(data: bytes, params: CDCParams):
    chunks = cdc_cpu.chunk_stream(data, params)
    digests = Blake3Numpy().digest_batch(
        [data[o:o + l] for o, l in chunks])
    return chunks, digests


def _check(device_result, data: bytes, params: CDCParams, tag: str):
    chunks, digests = device_result
    ref_chunks, ref_digests = _oracle(data, params)
    if chunks != ref_chunks or [bytes(d) for d in digests] != ref_digests:
        raise RuntimeError(f"config {tag}: device/oracle parity FAILED")


@functools.partial(jax.jit, static_argnames=("P",))
def _stage_rows(big: jnp.ndarray, offs: jnp.ndarray, lens: jnp.ndarray,
                *, P: int) -> jnp.ndarray:
    """Carve (B,) spans of a resident random pool into halo-padded rows."""

    def one(off, ln):
        sl = jax.lax.dynamic_slice(big, (off,), (P,))
        sl = jnp.where(jnp.arange(P, dtype=jnp.int32) < ln, sl, jnp.uint8(0))
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), sl])

    return jax.vmap(one)(offs.astype(jnp.int32), lens.astype(jnp.int32))


def config2_small_files(pipeline: DevicePipeline, params: CDCParams,
                        log: Callable) -> Dict:
    """Many small files, batched — BASELINE config #2."""
    total_mib = int(os.environ.get("BENCH_C2_MIB", "128"))
    rng = np.random.default_rng(21)
    sizes = []
    left = total_mib << 20
    while left > 0:
        n = int(rng.integers(4 << 10, 192 << 10))
        sizes.append(min(n, left))
        left -= n
    pool_len = (total_mib << 20) + (256 << 10)
    pool = jax.random.randint(jax.random.PRNGKey(5), (pool_len,), 0, 256,
                              dtype=jnp.uint8)
    offs = np.zeros(len(sizes), dtype=np.int64)
    pos = 0
    for i, s in enumerate(sizes):
        offs[i] = pos
        pos += s

    # bucket by padded length like manifest_batch, stage on device
    groups: Dict[int, list] = {}
    for i, s in enumerate(sizes):
        groups.setdefault(_segment_bucket(s), []).append(i)
    batches = []
    parts = []
    for P, idxs in sorted(groups.items()):
        row = _HALO + P
        b_cap = max(1, (128 << 20) // row)
        b_cap = 1 << (b_cap.bit_length() - 1)
        for s0 in range(0, len(idxs), b_cap):
            part = idxs[s0:s0 + b_cap]
            B = min(8, b_cap)
            while B < len(part):
                B *= 2
            o = np.zeros(B, dtype=np.int64)
            ln = np.zeros(B, dtype=np.int32)
            for r, i in enumerate(part):
                o[r], ln[r] = offs[i], sizes[i]
            buf = _stage_rows(pool, jnp.asarray(o), jnp.asarray(ln), P=P)
            batches.append((buf, ln))
            parts.append(part)
    jax.block_until_ready([b for b, _ in batches])

    # warm every batch shape (compiles must stay out of the timed loop)
    list(pipeline.manifest_segments(batches))
    t0 = time.time()
    results = list(pipeline.manifest_segments(batches))
    dt = time.time() - t0
    mibs = total_mib / dt

    # parity on the first batch's first rows (~1 MiB download)
    buf0, ln0 = batches[0]
    taken = 0
    for r in range(len(parts[0])):
        if taken > (1 << 20):
            break
        data = bytes(np.asarray(buf0[r, _HALO:_HALO + int(ln0[r])]))
        _check(results[0][r], data, params, "#2")
        taken += len(data)
    n_files = len(sizes)
    log(f"config#2 small-files: {n_files} files, {total_mib} MiB in "
        f"{dt:.2f}s = {mibs:.1f} MiB/s")
    return {"files": n_files, "mib_s": round(mibs, 2)}


def config3_incremental(pipeline: DevicePipeline, params: CDCParams,
                        log: Callable) -> Dict:
    """Two consecutive snapshots with small edits — BASELINE config #3."""
    seg_mib = int(os.environ.get("BENCH_C3_MIB", "128"))
    seg = seg_mib << 20
    row = _HALO + seg
    key = jax.random.PRNGKey(31)

    @jax.jit
    def synth(key):
        s = jax.random.randint(key, (seg,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), s]
                               ).reshape(1, row)

    @jax.jit
    def edit(buf, key):
        """Overwrite 20 x 4 KiB windows — the incremental delta."""
        flat = buf.reshape(-1)
        ks = jax.random.split(key, 20)
        offs = jax.random.randint(key, (20,), _HALO, row - 4096)
        for i in range(20):
            patch = jax.random.randint(ks[i], (4096,), 0, 256,
                                       dtype=jnp.uint8)
            flat = jax.lax.dynamic_update_slice(flat, patch, (offs[i],))
        return flat.reshape(1, row)

    key, k1, k2, kw1, kw2 = jax.random.split(key, 5)
    a = synth(k1)
    b = edit(a, k2)
    nv = np.full(1, seg, dtype=np.int32)
    jax.block_until_ready([a, b])
    # warm this segment shape (two distinct segments cover the tile combos)
    list(pipeline.manifest_segments(
        [(synth(kw1), nv), (edit(synth(kw2), kw1), nv)]))

    t0 = time.time()
    (ra,), (rb,) = pipeline.manifest_segments([(a, nv), (b, nv)],
                                              strict_overflow=True)
    dt = time.time() - t0
    dig_a = {bytes(d) for d in ra[1]}
    dup = sum(1 for d in rb[1] if bytes(d) in dig_a)
    ratio = dup / max(len(rb[0]), 1)
    mibs = 2 * seg_mib / dt

    # parity + identical dedup ratio on an 8 MiB sub-pair
    sub = 8 << 20
    a8 = bytes(np.asarray(a[0, _HALO:_HALO + sub]))
    b8 = bytes(np.asarray(b[0, _HALO:_HALO + sub]))
    ca, da = _oracle(a8, params)
    cb, db = _oracle(b8, params)
    sa = set(da)
    oracle_dup = sum(1 for d in db if d in sa)
    dev_sub = []
    for blob in (a8, b8):
        ext = np.concatenate([np.zeros(_HALO, dtype=np.uint8),
                              np.frombuffer(blob, dtype=np.uint8)])
        res, = pipeline.manifest_resident_batch(
            jnp.asarray(ext.reshape(1, -1)),
            np.full(1, sub, dtype=np.int32))
        _check(res, blob, params, "#3")
        dev_sub.append(res)
    dev_sa = {bytes(d) for d in dev_sub[0][1]}
    dev_dup = sum(1 for d in dev_sub[1][1] if bytes(d) in dev_sa)
    if dev_dup != oracle_dup:
        raise RuntimeError("config #3: dedup-ratio divergence on sub-pair")
    log(f"config#3 incremental: 2x{seg_mib} MiB in {dt:.2f}s = "
        f"{mibs:.1f} MiB/s, dedup ratio {ratio:.3f} "
        f"(oracle sub-pair dup {oracle_dup}/{len(cb)})")
    return {"mib_s": round(mibs, 2), "dedup_ratio": round(ratio, 4)}


def config4_large_stream(log: Callable) -> Dict:
    """Large contiguous stream at 64 KiB average chunks — config #4."""
    seg_mib = int(os.environ.get("BENCH_C4_MIB", "256"))
    params = CDCParams.from_desired(64 << 10)
    # small chunks -> small (L<=64) digest tiles: raise the row tier so
    # dispatches carry enough lanes to amortize the BLAKE3 program
    pipeline = DevicePipeline(params, l_bucket=256, b_bucket=512)
    seg = seg_mib << 20
    row = _HALO + seg

    @jax.jit
    def synth(key):
        s = jax.random.randint(key, (seg,), 0, 256, dtype=jnp.uint8)
        return jnp.concatenate([jnp.zeros(_HALO, dtype=jnp.uint8), s]
                               ).reshape(1, row)

    nv = np.full(1, seg, dtype=np.int32)
    key = jax.random.PRNGKey(41)
    key, kw, kw2, k1 = jax.random.split(key, 4)
    for k in (kw, kw2):  # two warm segments cover the tile combos
        pipeline.manifest_resident_batch(synth(k), nv, strict_overflow=True)

    buf = synth(k1)
    jax.block_until_ready(buf)
    t0 = time.time()
    (chunks, digests), = pipeline.manifest_resident_batch(
        buf, nv, strict_overflow=True)
    dt = time.time() - t0
    mibs = seg_mib / dt

    sub = 8 << 20
    data = bytes(np.asarray(buf[0, _HALO:_HALO + sub]))
    ext = np.concatenate([np.zeros(_HALO, dtype=np.uint8),
                          np.frombuffer(data, dtype=np.uint8)])
    dev_sub, = pipeline.manifest_resident_batch(
        jnp.asarray(ext.reshape(1, -1)), np.full(1, sub, dtype=np.int32))
    _check(dev_sub, data, params, "#4")
    log(f"config#4 large-stream(64KiB): {seg_mib} MiB in {dt:.2f}s = "
        f"{mibs:.1f} MiB/s ({len(chunks)} chunks)")
    return {"mib_s": round(mibs, 2), "chunks": len(chunks)}


def config5_cross_peer(log: Callable) -> Dict:
    """Cross-peer global dedup on the sharded HBM index — config #5."""
    from jax.sharding import Mesh

    from backuwup_tpu.ops.dedup_index import (ShardedDedupIndex,
                                              hashes_to_queries)

    n_hashes = int(os.environ.get("BENCH_C5_HASHES", "200000"))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    rng = np.random.default_rng(51)
    # 4 peers, ~50% of each corpus shared with a common pool
    shared = [rng.bytes(32) for _ in range(n_hashes // 8)]
    peers = []
    for p in range(4):
        own = [rng.bytes(32) for _ in range(n_hashes // 8)]
        picks = rng.choice(len(shared), n_hashes // 8, replace=False)
        peers.append(own + [shared[i] for i in picks])

    # ~162k unique keys at the default sizing: keep the load factor low
    # enough that a 32-step linear probe never exhausts
    cap = 1 << max(18, (5 * n_hashes).bit_length())
    index = ShardedDedupIndex.create(mesh, capacity=cap)
    # warm the insert/probe programs on a throwaway table
    warm = ShardedDedupIndex.create(mesh, capacity=cap)
    wq = hashes_to_queries(peers[0])
    warm.insert(wq, np.ones(len(peers[0]), dtype=np.uint32))
    host_seen = set()
    host_flags = []
    t0 = time.time()
    dev_flags = []
    for corpus in peers:
        q = hashes_to_queries(corpus)
        found = index.insert(q, np.ones(len(corpus), dtype=np.uint32))
        dev_flags.extend(bool(f) for f in found)
    dt = time.time() - t0
    for corpus in peers:
        for h in corpus:
            host_flags.append(h in host_seen)
            host_seen.add(h)
    if dev_flags != host_flags:
        raise RuntimeError("config #5: device/host global dedup mismatch")
    total = sum(len(c) for c in peers)
    rate = total / dt
    ratio = sum(dev_flags) / total
    log(f"config#5 cross-peer: {total} hashes over {len(mesh.devices)} "
        f"device(s) in {dt:.2f}s = {rate:,.0f} hashes/s, global dup "
        f"ratio {ratio:.3f}")
    return {"hashes_s": round(rate), "dup_ratio": round(ratio, 4)}


def run_all(pipeline: DevicePipeline, params: CDCParams, cpu_mibs: float,
            log: Callable) -> Dict:
    out = {}
    for name, fn in (
            ("2_small_files", lambda: config2_small_files(pipeline, params,
                                                          log)),
            ("3_incremental", lambda: config3_incremental(pipeline, params,
                                                          log)),
            ("4_large_stream_64k", lambda: config4_large_stream(log)),
            ("5_cross_peer_dedup", lambda: config5_cross_peer(log))):
        try:
            out[name] = fn()
            if "mib_s" in out[name]:
                out[name]["vs_baseline"] = round(
                    out[name]["mib_s"] / cpu_mibs, 2)
        except Exception as e:  # a config failure must not kill the JSON
            log(f"config {name} FAILED: {e}")
            out[name] = {"error": str(e)[:200]}
    return out
