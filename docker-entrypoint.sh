#!/bin/sh
# Container entry: role comes from BKW_ROLE (server|client); extra args
# pass through to `python -m backuwup_tpu <role>`.
set -e
if [ "${BKW_ROLE:-server}" = "server" ]; then
    exec python -m backuwup_tpu server \
        --bind "${SERVER_BIND:-0.0.0.0:9999}" \
        --db "${SERVER_DB:-/data/server.db}" "$@"
else
    exec python -m backuwup_tpu client "$@"
fi
