#!/bin/sh
# Container entry: role comes from BKW_ROLE (server|client|check); extra
# args pass through to `python -m backuwup_tpu <role>` (or to bkwlint
# for the check role).
set -e
case "${BKW_ROLE:-server}" in
server)
    exec python -m backuwup_tpu server \
        --bind "${SERVER_BIND:-0.0.0.0:9999}" \
        --db "${SERVER_DB:-/data/server.db}" "$@"
    ;;
check)
    # static invariant gate (bkwlint): exits 0 clean / 1 findings /
    # 3 stale baseline — usable as a CI step on the built image
    exec python -m backuwup_tpu.analysis /app/backuwup_tpu \
        --doc /app/docs/observability.md \
        --baseline /app/.bkwlint-baseline.json "$@"
    ;;
*)
    exec python -m backuwup_tpu client "$@"
    ;;
esac
