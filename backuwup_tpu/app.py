"""Client application: wires identity, store, networking, and engine.

The equivalent of the reference client's ``main()`` boot sequence
(``client/src/main.rs:44-85``): load-or-create identity, register/login,
start the push channel, install the P2P request handlers (store incoming
peer data; serve restores), and expose backup/restore entry points.
"""

from __future__ import annotations

import asyncio
import os
from pathlib import Path
from typing import Optional

from . import wire
from .crypto import KeyManager
from .engine import Engine
from .net.client import ServerClient
from .net.p2p import P2PNode, ReceivedFilesWriter, Receiver
from .obs import diagnose as obs_diagnose
from .obs import slo as obs_slo
from .obs.invariants import InvariantMonitor
from .obs.series import SeriesRecorder
from .ops.backend import ChunkerBackend
from .store import Store
from .ui.messenger import Messenger


class ClientApp:
    def __init__(self, config_dir: Optional[Path] = None,
                 data_dir: Optional[Path] = None,
                 server_addr: Optional[str] = None,
                 backend: Optional[ChunkerBackend] = None,
                 messenger: Optional[Messenger] = None,
                 dedup_mesh=None,
                 root_secret: Optional[bytes] = None,
                 tls: Optional[bool] = None,
                 status_port: Optional[int] = None):
        """``root_secret`` injects a recovered identity (the
        restore-from-phrase flow, ``identity.rs:46-69``): the secret is
        persisted and all keys re-derive deterministically, so a disaster
        recovery proceeds as this identity.  Raises if the store already
        holds a *different* identity.

        ``status_port`` (or ``BKW_STATUS_PORT``) opts the client into a
        loopback /metrics + /healthz listener; port 0 picks an ephemeral
        port, exposed as :attr:`status_port` after :meth:`start`."""
        self.store = Store(config_dir, data_base=data_dir)
        self.messenger = messenger or Messenger()
        secret = self.store.get_root_secret()
        if root_secret is not None:
            if secret is not None and secret != root_secret:
                self.store.close()
                raise ValueError(
                    "store already holds a different identity; refusing to "
                    "overwrite it with the recovered secret")
            self.keys = KeyManager.from_secret(root_secret)
            if secret is None:
                self.store.set_root_secret(root_secret)
            self.fresh_identity = secret is None
        elif secret is None:
            self.keys = KeyManager.generate()
            self.store.set_root_secret(self.keys.root_secret)
            self.store.set_obfuscation_key(os.urandom(4))
            self.fresh_identity = True
        else:
            self.keys = KeyManager.from_secret(secret)
            self.fresh_identity = False
        if self.store.get_obfuscation_key() is None:
            self.store.set_obfuscation_key(os.urandom(4))
        self.server = ServerClient(self.keys, self.store, addr=server_addr,
                                   tls=tls)
        self.node = P2PNode(self.keys, self.store, self.server)
        self.node.on_transport_request = self._accept_peer_data
        self.node.on_restore_request = self._serve_restore
        self.node.on_restore_fetch_request = self._serve_restore_fetch
        self.node.on_reclaim_request = self._serve_reclaim
        self.node.on_audit_request = self._serve_audit
        self.server.on_backup_matched = self._backup_matched
        self.server.on_audit_due = self._audit_due
        self.engine = Engine(self.keys, self.store, self.server, self.node,
                             backend=backend, messenger=self.messenger,
                             dedup_mesh=dedup_mesh)
        self.monitor = InvariantMonitor(self.store, index=self.engine.index,
                                        client=self.client_id.hex()[:8])
        # live SLO plane: ring-buffer history over the catalog's families
        # plus the durability scoreboard, burn-rate evaluation riding the
        # same cadence, diagnosis on breach (docs/observability.md §SLOs)
        slo_catalog = obs_slo.parse_catalog()
        families = sorted({o.family for o in slo_catalog}
                          | {o.total_family for o in slo_catalog
                             if o.total_family}
                          | {"bkw_durability_status",
                             "bkw_durability_repair_debt_bytes"})
        self.series = SeriesRecorder(families)
        self.slo = obs_slo.SLOMonitor(
            self.series, catalog=slo_catalog,
            on_breach=lambda breach: obs_diagnose.explain(
                breach, recorder=self.series),
            client=self.client_id.hex()[:8])
        self._audit_task: Optional[asyncio.Task] = None
        self._monitor_task: Optional[asyncio.Task] = None
        self._slo_task: Optional[asyncio.Task] = None
        if status_port is None:
            env_port = os.environ.get("BKW_STATUS_PORT", "")
            status_port = int(env_port) if env_port else None
        self._status_port_req = status_port
        self._status_server = None
        self.status_port: Optional[int] = None

    @classmethod
    def from_phrase(cls, phrase: str, **kwargs) -> "ClientApp":
        """Rebuild an identity from its recovery phrase — word or base32
        form (cli.rs:26-51)."""
        from .crypto import parse_recovery
        return cls(root_secret=parse_recovery(phrase), **kwargs)

    @property
    def client_id(self) -> bytes:
        return self.keys.client_id

    # --- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """Register (first run) / login, then open the push channel."""
        if not self.store.is_initialized():
            await self.server.register()
            self.store.set_initialized()
        await self.server.login()
        self.server.start_ws()
        await asyncio.wait_for(self.server.ws_connected.wait(), 10)
        # reconcile disk against the config DB before ANY scheduler runs:
        # a previous process may have died mid-commit, and the schedulers
        # must start from a consistent world (docs/crash_consistency.md)
        recovery = await self.engine.recover()
        self.messenger.log(
            f"recovery: reconciled {recovery['reconciled']} item(s),"
            f" backlog packfiles={recovery['packfiles_pending']}"
            f" stripes={recovery['stripes_underplaced']}"
            f" in {recovery['elapsed_s']:.3f}s")
        self._audit_task = asyncio.create_task(
            self.engine.audit_scheduler())
        self._monitor_task = asyncio.create_task(
            # the durability sweep doubles as the receiver-side TTL
            # janitor's clock, so abandoned partials age out without a
            # restart (engine.expire_partials also runs in recovery)
            self.monitor.run(janitor=self.engine.expire_partials))
        self._slo_task = asyncio.create_task(
            # series sampling and burn-rate evaluation ride one cadence so
            # every evaluation judges a freshly appended point
            self.series.run(on_sample=self.slo.evaluate))
        if self._status_port_req is not None:
            from .obs.expo import StatusServer
            self._status_server = StatusServer(
                port=self._status_port_req,
                health_fn=lambda: {
                    "client_id": self.client_id.hex(),
                    "busy": self.engine._exclusive.locked(),
                    # sweep on demand: health is never staler than the ask
                    "durability": self.monitor.sweep().summary,
                    "slo": self.slo.summary(),
                    "status": obs_slo.join_status(
                        self.monitor.last_report.status,
                        self.slo.summary()["status"])},
                before_metrics=lambda: self.monitor.sweep())
            self.status_port = await self._status_server.start()
            self.messenger.log(
                f"status listener on 127.0.0.1:{self.status_port}")
        self.messenger.log("connected to coordination server")

    async def stop(self) -> None:
        if self._status_server is not None:
            await self._status_server.stop()
            self._status_server = None
            self.status_port = None
        if self._audit_task is not None:
            self._audit_task.cancel()
            try:
                await self._audit_task
            except (asyncio.CancelledError, Exception):
                pass
            self._audit_task = None
        if self._monitor_task is not None:
            self._monitor_task.cancel()
            try:
                await self._monitor_task
            except (asyncio.CancelledError, Exception):
                pass
            self._monitor_task = None
        if self._slo_task is not None:
            self._slo_task.cancel()
            try:
                await self._slo_task
            except (asyncio.CancelledError, Exception):
                pass
            self._slo_task = None
        await self.engine.aclose()
        await self.server.close()
        self.store.close()

    # --- push handlers -----------------------------------------------------

    async def _backup_matched(self, msg: wire.BackupMatched) -> None:
        """Record the negotiated allowance for both roles
        (send.rs:312-335)."""
        self.store.add_peer_negotiated(msg.destination_id,
                                       msg.storage_available)
        self.messenger.log(
            f"matched with {bytes(msg.destination_id).hex()[:8]} for "
            f"{msg.storage_available} bytes")

    async def _accept_peer_data(self, source: bytes, transport) -> None:
        writer = ReceivedFilesWriter(self.store, source)
        count = await Receiver(transport, writer.sink,
                               part_sink=writer.sink_part,
                               resume_query=writer.resume_offer).run()
        self.messenger.log(
            f"stored {count} files for peer {bytes(source).hex()[:8]}")

    async def _serve_restore(self, source: bytes, transport) -> None:
        sent = await self.node.serve_restore(source, transport)
        self.messenger.log(
            f"served {sent} files back to {bytes(source).hex()[:8]}")

    async def _serve_restore_fetch(self, source: bytes, transport) -> None:
        sent = await self.node.serve_restore_fetch(source, transport)
        self.messenger.log(
            f"served {sent} fetched item(s) back to "
            f"{bytes(source).hex()[:8]}")

    async def _serve_reclaim(self, source: bytes, transport) -> None:
        freed = await self.node.serve_reclaim(source, transport)
        self.messenger.log(
            f"reclaimed {freed} byte(s) for {bytes(source).hex()[:8]}")

    async def _serve_audit(self, source: bytes, transport) -> None:
        answered = await self.node.serve_audit(source, transport,
                                               self.engine.backend)
        self.messenger.log(
            f"answered {answered} audit challenges for "
            f"{bytes(source).hex()[:8]}")

    async def _audit_due(self, msg: wire.AuditDue) -> None:
        """Server nudge: another client's audit of this peer failed."""
        self.engine.note_audit_due(msg.peer_id)
        self.messenger.log(
            f"audit of {bytes(msg.peer_id).hex()[:8]} requested by server")

    # --- commands (ws_dispatcher.rs:16-23) ---------------------------------

    async def backup(self, root: Optional[Path] = None) -> bytes:
        self.messenger.backup_started()
        try:
            snapshot = await self.engine.run_backup(root)
            self.messenger.backup_finished(snapshot)
            return snapshot
        except Exception as e:
            self.messenger.log(f"backup failed: {e}")
            raise

    async def audit(self) -> dict:
        """Run one verifier round over every peer whose audit is due."""
        return await self.engine.run_audit_round()

    async def restore(self, dest: Optional[Path] = None) -> Path:
        self.messenger.restore_started()
        try:
            path = await self.engine.run_restore(dest)
            self.messenger.restore_finished()
            return path
        except Exception as e:
            self.messenger.log(f"restore failed: {e}")
            raise
