"""Client control plane: typed server RPCs + persistent push channel.

Re-designs ``client/src/net_server/`` (requests.rs + mod.rs): every call is
a typed JSON POST; authentication failures trigger one transparent re-login
(``retry_with_login``, requests.rs:212-235); a persistent WebSocket carries
server push messages (BackupMatched / IncomingP2PConnection /
FinalizeP2PConnection / Ping) with an infinite reconnect loop
(``net_server/mod.rs:26-55``).

Server address resolution honors the ``SERVER_ADDR`` env seam the reference
uses for testing (requests.rs:246-258).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Callable, Optional

import aiohttp

from .. import defaults, wire
from ..crypto import KeyManager
from ..obs import trace as obs_trace
from ..store import Store
from ..utils import retry


class ServerError(Exception):
    """Base of the typed error taxonomy (wire.ErrorKind; the reference
    client pattern-matches the 8 ErrorType variants,
    server_message.rs:43-54)."""

    KIND = wire.ErrorKind.FAILURE

    def __init__(self, detail: str = "", kind: str = None):
        self.kind = kind or self.KIND
        self.detail = detail
        super().__init__(f"{self.kind}: {detail}" if detail else self.kind)


class Unauthorized(ServerError):
    KIND = wire.ErrorKind.UNAUTHORIZED


class ClientNotFound(ServerError):
    KIND = wire.ErrorKind.CLIENT_NOT_FOUND


class DestinationUnreachable(ServerError):
    KIND = wire.ErrorKind.DESTINATION_UNREACHABLE


class NoBackups(ServerError):
    KIND = wire.ErrorKind.NO_BACKUPS


class RetryLater(ServerError):
    KIND = wire.ErrorKind.RETRY


class BadRequest(ServerError):
    KIND = wire.ErrorKind.BAD_REQUEST


class ClientExists(BadRequest):
    """409-status BadRequest: the identity is already registered (the
    restore-from-phrase path hits this and proceeds to login)."""


class ServerFault(ServerError):
    KIND = wire.ErrorKind.SERVER_ERROR


_KIND_TO_EXC = {
    wire.ErrorKind.UNAUTHORIZED: Unauthorized,
    wire.ErrorKind.CLIENT_NOT_FOUND: ClientNotFound,
    wire.ErrorKind.DESTINATION_UNREACHABLE: DestinationUnreachable,
    wire.ErrorKind.NO_BACKUPS: NoBackups,
    wire.ErrorKind.RETRY: RetryLater,
    wire.ErrorKind.BAD_REQUEST: BadRequest,
    wire.ErrorKind.SERVER_ERROR: ServerFault,
    wire.ErrorKind.FAILURE: ServerError,
}


def server_addr() -> str:
    return os.environ.get("SERVER_ADDR", "127.0.0.1:8080")


def use_tls() -> bool:
    """TLS-by-default with a USE_TLS=0 off-switch for local testing,
    mirroring client/src/defaults.rs:6-7 + requests.rs:246-258."""
    return os.environ.get("USE_TLS", "1") not in ("0", "false", "no")


def _ssl_client_context():
    """Client-side SSL context; TLS_CA_FILE pins a (self-signed) CA."""
    import ssl

    ca = os.environ.get("TLS_CA_FILE")
    if ca:
        return ssl.create_default_context(cafile=ca)
    return ssl.create_default_context()


class ServerClient:
    """One client's control-plane connection to the coordination server.

    ``addr`` accepts a single ``host:port`` or a LIST of them (a
    federated deployment, docs/server.md §Federation — order them owner
    node first).  Failover rules, chosen so a request is never submitted
    twice:

    * only a DIAL-level failure (``aiohttp.ClientConnectorError`` — the
      request never reached any server) rotates to the next URL and
      retries; once any response arrives, the outcome is final for that
      call (a timeout or dropped response might have been processed);
    * a 421 :class:`wire.NodeRedirect` is followed at most once per
      call, and only toward a URL already on the configured list;
    * after a refused dial or a failed redirect hop the client pins
      itself (``fed_pinned`` rides in the POST body) for
      ``FEDERATION_CLIENT_PIN_S`` so servers stop redirecting it while
      its view of the ring is demonstrably stale — no ping-pong.
    """

    def __init__(self, keys: KeyManager, store: Store,
                 addr=None, tls: Optional[bool] = None):
        self.keys = keys
        self.store = store
        if addr is None:
            addr = server_addr()
        self.addrs = ([str(a) for a in addr]
                      if isinstance(addr, (list, tuple)) else [str(addr)])
        self._addr_i = 0
        self.failovers = 0  # dial-level URL rotations (test/scorecard hook)
        self._pinned_until = 0.0
        self.tls = use_tls() if tls is None else tls
        self._http: Optional[aiohttp.ClientSession] = None
        self._ws_task: Optional[asyncio.Task] = None
        # push-handler tasks (backup-matched / p2p rendezvous); cancelled
        # on close so none outlive the event loop (teardown hygiene)
        self._handler_tasks: set = set()
        self.on_backup_matched: Optional[Callable] = None
        self.on_incoming_p2p: Optional[Callable] = None
        self.on_finalize_p2p: Optional[Callable] = None
        self.on_audit_due: Optional[Callable] = None
        self.ws_connected = asyncio.Event()

    # --- federated address book --------------------------------------------

    @property
    def addr(self) -> str:
        return self.addrs[self._addr_i]

    @property
    def base(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.addr}"

    def _rotate(self) -> None:
        """Dial failed: pin + advance to the next configured node."""
        self.failovers += 1
        self._pinned_until = (asyncio.get_event_loop().time()
                              + defaults.FEDERATION_CLIENT_PIN_S)
        self._addr_i = (self._addr_i + 1) % len(self.addrs)

    def _pinned(self) -> bool:
        return asyncio.get_event_loop().time() < self._pinned_until

    def _take_redirect(self, url: str) -> bool:
        """Follow a NodeRedirect only toward a URL already on the
        configured list (and not the one we are already using)."""
        scheme = "https" if self.tls else "http"
        target = url.rstrip("/")
        for i, a in enumerate(self.addrs):
            if f"{scheme}://{a}" == target and i != self._addr_i:
                self._addr_i = i
                return True
        return False

    async def _session(self) -> aiohttp.ClientSession:
        if self._http is None or self._http.closed:
            if self.tls:
                connector = aiohttp.TCPConnector(ssl=_ssl_client_context())
                self._http = aiohttp.ClientSession(connector=connector)
            else:
                self._http = aiohttp.ClientSession()
        return self._http

    async def close(self) -> None:
        if self._ws_task is not None:
            self._ws_task.cancel()
            try:
                await self._ws_task
            except (asyncio.CancelledError, Exception):
                pass
            self._ws_task = None
        for t in list(self._handler_tasks):
            t.cancel()
        if self._handler_tasks:
            await asyncio.gather(*self._handler_tasks,
                                 return_exceptions=True)
            self._handler_tasks.clear()
        if self._http is not None and not self._http.closed:
            await self._http.close()

    def _spawn_handler(self, coro) -> None:
        task = asyncio.create_task(coro)
        self._handler_tasks.add(task)
        task.add_done_callback(self._handler_tasks.discard)

    # --- raw RPC -----------------------------------------------------------

    async def _post(self, path: str, msg: wire.JsonMessage) -> wire.JsonMessage:
        with obs_trace.span(f"client{path}"):
            return await self._post_traced(path, msg)

    def _payload(self, msg: wire.JsonMessage) -> str:
        doc = json.loads(msg.to_json())
        tid = obs_trace.current_trace_id()
        if tid:
            # extra JSON keys: from_json ignores unknown keys, so old
            # servers interoperate; new ones join the trace (obs/trace.py)
            doc["trace_id"] = tid
        if self._pinned():
            doc["fed_pinned"] = True
        return json.dumps(doc, separators=(",", ":"), sort_keys=True)

    async def _post_traced(self, path: str,
                           msg: wire.JsonMessage) -> wire.JsonMessage:
        http = await self._session()
        # dial failures may try every configured node once; any received
        # response is final (see the class docstring's no-double-submit
        # rule).  Single-address clients keep the pre-federation shape:
        # one attempt, the connect error propagates.
        dials_left = len(self.addrs) if len(self.addrs) > 1 else 1
        redirected = False
        while True:
            try:
                async with http.post(self.base + path,
                                     data=self._payload(msg)) as resp:
                    body = await resp.text()
                    status = resp.status
            except aiohttp.ClientConnectorError:
                dials_left -= 1
                if dials_left <= 0:
                    raise
                self._rotate()
                continue
            try:
                out = wire.JsonMessage.from_json(body)
            except ValueError:
                out = wire.Error(kind=wire.ErrorKind.FAILURE,
                                 detail=f"unparseable response: {body[:200]}")
            if status == 421 and isinstance(out, wire.NodeRedirect):
                if not redirected and self._take_redirect(out.url):
                    redirected = True
                    continue
                raise RetryLater(f"misdirected request: {out.url}")
            if status >= 400 or isinstance(out, wire.Error):
                kind = getattr(out, "kind", wire.ErrorKind.FAILURE)
                detail = getattr(out, "detail", "")
                if status == 409 and kind == wire.ErrorKind.BAD_REQUEST:
                    raise ClientExists(detail)
                exc = _KIND_TO_EXC.get(kind, ServerError)
                raise exc(detail)
            return out

    # --- identity flows (identity.rs) --------------------------------------

    async def register(self) -> None:
        challenge = await self._post("/register/begin",
                                     wire.ClientRegistrationRequest(
                                         pubkey=self.keys.client_id))
        try:
            await self._post("/register/complete",
                             wire.ClientRegistrationAuth(
                                 pubkey=self.keys.client_id,
                                 challenge_response=self.keys.sign(
                                     challenge.nonce)))
        except ClientExists:
            # a recovered identity (restore-from-phrase) is already
            # registered; proceed to login (identity.rs:46-69)
            pass

    async def login(self) -> bytes:
        challenge = await self._post("/login/begin", wire.ClientLoginRequest(
            pubkey=self.keys.client_id))
        out = await self._post("/login/complete", wire.ClientLoginAuth(
            pubkey=self.keys.client_id,
            challenge_response=self.keys.sign(challenge.nonce)))
        self.store.set_auth_token(out.token)
        return out.token

    async def _token(self) -> bytes:
        token = self.store.get_auth_token()
        if token is None:
            token = await self.login()
        return token

    async def _with_login(self, call):
        """Re-auth once on 401 (requests.rs:212-235)."""
        try:
            return await call(await self._token())
        except Unauthorized:
            self.store.set_auth_token(None)
            return await call(await self.login())

    # --- typed API (requests.rs) -------------------------------------------

    async def backup_storage_request(self, storage_required: int,
                                     min_peers: int = 1) -> None:
        """``min_peers > 1`` asks the matchmaker to spread the grant over
        that many distinct candidates (erasure stripes need k+m holders)."""
        await self._with_login(lambda t: self._post(
            "/backups/request",
            wire.BackupRequest(session_token=t,
                               storage_required=storage_required,
                               min_peers=min_peers)))

    async def backup_done(self, snapshot_hash: bytes) -> None:
        await self._with_login(lambda t: self._post(
            "/backups/done",
            wire.BackupDone(session_token=t, snapshot_hash=snapshot_hash)))

    async def backup_restore(self) -> wire.BackupRestoreInfo:
        return await self._with_login(lambda t: self._post(
            "/backups/restore", wire.BackupRestoreRequest(session_token=t)))

    async def p2p_connection_begin(self, destination: bytes,
                                   session_nonce: bytes) -> None:
        await self._with_login(lambda t: self._post(
            "/p2p/connection/begin", wire.BeginP2PConnectionRequest(
                session_token=t, destination_client_id=destination,
                session_nonce=session_nonce)))

    async def p2p_connection_confirm(self, source: bytes, addr: str) -> None:
        await self._with_login(lambda t: self._post(
            "/p2p/connection/confirm", wire.ConfirmP2PConnectionRequest(
                session_token=t, source_client_id=source,
                destination_ip_address=addr)))

    async def audit_report(self, peer_id: bytes, passed: bool,
                           detail: str = "") -> None:
        await self._with_login(lambda t: self._post(
            "/audit/report", wire.AuditReport(
                session_token=t, peer_id=bytes(peer_id), passed=passed,
                detail=detail)))

    async def repair_report(self, peer_id: bytes, packfiles_lost: int,
                            bytes_lost: int, bytes_replaced: int) -> None:
        await self._with_login(lambda t: self._post(
            "/repair/report", wire.RepairReport(
                session_token=t, peer_id=bytes(peer_id),
                packfiles_lost=int(packfiles_lost),
                bytes_lost=int(bytes_lost),
                bytes_replaced=int(bytes_replaced))))

    # --- push channel (net_server/mod.rs) ----------------------------------

    def start_ws(self) -> asyncio.Task:
        if self._ws_task is None or self._ws_task.done():
            self._ws_task = asyncio.create_task(self._ws_loop())
        return self._ws_task

    async def _ws_loop(self) -> None:
        backoff = retry.Backoff(retry.WS_RECONNECT)
        while True:
            try:
                token = await self._token()
                http = await self._session()
                async with http.ws_connect(
                        self.base + "/ws",
                        headers={"Authorization": bytes(token).hex()}) as ws:
                    self.ws_connected.set()
                    # an accepted connection ends the outage: the next
                    # failure backs off from the base delay again
                    backoff.reset()
                    async for msg in ws:
                        if msg.type != aiohttp.WSMsgType.TEXT:
                            break
                        await self._dispatch(msg.data)
            except Unauthorized:
                self.store.set_auth_token(None)
            except asyncio.CancelledError:
                raise
            except aiohttp.ClientConnectorError as e:
                # refused dial: this node is down — rotate to the next
                # configured node before backing off
                if len(self.addrs) > 1:
                    self._rotate()
                logging.getLogger(__name__).debug(
                    "server WS dial failed: %s; rotating + reconnecting", e)
            except aiohttp.WSServerHandshakeError as e:
                # session tokens are node-local: after a failover the
                # next node rejects the stale token — drop it so the
                # retry re-logs-in there
                if e.status == 401:
                    self.store.set_auth_token(None)
                logging.getLogger(__name__).debug(
                    "server WS handshake failed: %s; reconnecting", e)
            except (aiohttp.ClientError, ServerError, OSError,
                    RuntimeError) as e:
                # reconnect loop (net_server/mod.rs:26-55): log, back off,
                # retry — but never swallow unrelated programming errors
                logging.getLogger(__name__).debug(
                    "server WS dropped: %s; reconnecting", e)
            self.ws_connected.clear()
            # unified jittered backoff (utils/retry.py), unbounded: the
            # push channel must always come back eventually
            await backoff.sleep()

    async def _dispatch(self, raw: str) -> None:
        try:
            msg = wire.JsonMessage.from_json(raw)
        except ValueError:
            return
        # each push handled in its own task (net_server/mod.rs:58-90)
        if isinstance(msg, wire.BackupMatched) and self.on_backup_matched:
            self._spawn_handler(self.on_backup_matched(msg))
        elif isinstance(msg, wire.IncomingP2PConnection) and self.on_incoming_p2p:
            self._spawn_handler(self.on_incoming_p2p(msg))
        elif isinstance(msg, wire.FinalizeP2PConnection) and self.on_finalize_p2p:
            self._spawn_handler(self.on_finalize_p2p(msg))
        elif isinstance(msg, wire.AuditDue) and self.on_audit_due:
            self._spawn_handler(self.on_audit_due(msg))
