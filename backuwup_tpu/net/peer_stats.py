"""Per-peer transfer estimators: EWMA throughput/latency/success.

The measurement foundation for WAN-aware scheduling (ROADMAP): every
``TransferResult`` the :class:`~backuwup_tpu.net.transfer.\
TransferScheduler` finalizes feeds one :meth:`PeerStats.observe`, which

* updates per-peer EWMAs — throughput (``size / send_s`` of successful
  sends), latency (the full send+ack seconds), success ratio — seeded
  at the first sample so a fresh peer isn't averaged against zero;
* exposes them as peer-labeled gauges plus additive per-peer wait/send
  histograms (NEW families; the PR-4 unlabeled transfer histograms keep
  their exact series — the scorecard and engine stage sums depend on
  them);
* persists the EWMA state to the client config DB (``peer_stats``
  table) so capacity knowledge survives a restart — a client that comes
  back after a week still knows which holders were slow.

Estimates are observability/scheduling hints only: they MUST never
gate correctness (a slow peer still holds real shards).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace
from typing import Dict, List, Optional

from .. import defaults
from ..obs import journal as obs_journal
from ..obs import metrics as obs_metrics
from ..store import PeerStatsRow
from ..utils import clock as clockmod

_THROUGHPUT = obs_metrics.gauge(
    "bkw_peer_throughput_bytes_per_second",
    "EWMA payload throughput per peer over successful transfers",
    labelnames=("peer",))
_LATENCY = obs_metrics.gauge(
    "bkw_peer_latency_seconds",
    "EWMA send+ack seconds per peer over successful transfers",
    labelnames=("peer",))
_SUCCESS = obs_metrics.gauge(
    "bkw_peer_success_ratio",
    "EWMA transfer success ratio per peer (1.0 = never fails)",
    labelnames=("peer",))
_SAMPLES = obs_metrics.counter(
    "bkw_peer_transfer_samples_total",
    "TransferResults folded into a peer's estimators",
    labelnames=("peer",))
_WAIT_SECONDS = obs_metrics.histogram(
    "bkw_peer_transfer_wait_seconds",
    "Scheduler admission wait per peer",
    labelnames=("peer",))
_SEND_SECONDS = obs_metrics.histogram(
    "bkw_peer_transfer_send_seconds",
    "Wire send+ack seconds per peer",
    labelnames=("peer",))
_DEMOTIONS = obs_metrics.counter(
    "bkw_placement_demotions_total",
    "Placement-demotion transitions (capacity-based, not audit)",
    labelnames=("action",))


def peer_label(peer_id: bytes) -> str:
    """The metric label for a peer: short hex, same truncation the
    messenger uses for transfer frames."""
    return bytes(peer_id).hex()[:16]


@dataclass(frozen=True)
class PeerEstimate:
    """Current view of one peer (a thin alias over the persisted row)."""

    peer: bytes
    throughput_bps: float = 0.0
    latency_s: float = 0.0
    success: float = 1.0
    samples: int = 0
    updated: float = 0.0


class PeerStats:
    """EWMA estimator bank, optionally backed by a :class:`Store`.

    Thread-safe: the scheduler finalizes results on the event loop but
    tests and the repair path may observe from other threads.
    """

    def __init__(self, store=None, alpha: Optional[float] = None,
                 clock=None):
        self.store = store
        self.alpha = defaults.PEER_STATS_ALPHA if alpha is None else alpha
        self.clock = clockmod.resolve(clock)
        self._lock = threading.Lock()
        self._est: Dict[bytes, PeerEstimate] = {}
        self._demoted: set = set()
        if store is not None:
            for row in store.all_peer_stats():
                est = PeerEstimate(
                    peer=bytes(row.peer),
                    throughput_bps=row.throughput_bps,
                    latency_s=row.latency_s, success=row.success,
                    samples=row.samples, updated=row.updated)
                self._est[est.peer] = est
                if row.placement_demoted:
                    self._demoted.add(est.peer)
                self._export(est)

    def _export(self, est: PeerEstimate) -> None:
        label = peer_label(est.peer)
        _THROUGHPUT.set(est.throughput_bps, peer=label)
        _LATENCY.set(est.latency_s, peer=label)
        _SUCCESS.set(est.success, peer=label)

    def _ewma(self, prev: float, sample: float, first: bool) -> float:
        if first:
            return sample
        return (1.0 - self.alpha) * prev + self.alpha * sample

    def observe(self, result, now: Optional[float] = None) -> PeerEstimate:
        """Fold one finalized ``TransferResult``-shaped object (needs
        ``peer_id``/``size``/``ok``/``wait_s``/``send_s``) into the
        peer's estimators; returns the updated estimate."""
        peer = bytes(result.peer_id)
        label = peer_label(peer)
        now = self.clock.now() if now is None else now
        with self._lock:
            est = self._est.get(peer, PeerEstimate(peer=peer))
            first = est.samples == 0
            ok = bool(result.ok)
            success = self._ewma(est.success, 1.0 if ok else 0.0, first)
            throughput, latency = est.throughput_bps, est.latency_s
            if ok and result.send_s > 0:
                # failures say nothing about capacity, only reliability:
                # the rate estimators move on successful sends alone
                first_ok = est.throughput_bps == 0.0 and est.latency_s == 0.0
                throughput = self._ewma(
                    throughput, result.size / result.send_s, first_ok)
                latency = self._ewma(latency, result.send_s, first_ok)
            est = replace(est, throughput_bps=throughput,
                          latency_s=latency, success=success,
                          samples=est.samples + 1, updated=now)
            self._est[peer] = est
            self._export(est)
            _SAMPLES.inc(peer=label)
            _WAIT_SECONDS.observe(max(result.wait_s, 0.0), peer=label)
            _SEND_SECONDS.observe(max(result.send_s, 0.0), peer=label)
            if self.store is not None:
                try:
                    self.store.put_peer_stats(PeerStatsRow(
                        peer=peer, throughput_bps=est.throughput_bps,
                        latency_s=est.latency_s, success=est.success,
                        samples=est.samples, updated=est.updated))
                except Exception:
                    pass  # telemetry must never fail a transfer
            self._update_demotion(est, now)
            return est

    def _update_demotion(self, est: PeerEstimate, now: float) -> None:
        """Capacity-based placement demotion/recovery (holds _lock).

        Persistently flaky peers (success EWMA under the demote floor
        after enough samples) stop receiving NEW placements; a run of
        successes — or, lazily, the probation window in
        ``Store.placement_demoted_peers`` — recovers them.  Never touches
        the audit ledger: proven data loss is a different, harsher state.
        """
        if est.samples < defaults.PLACEMENT_DEMOTE_MIN_SAMPLES:
            return
        demoted = est.peer in self._demoted
        if not demoted and est.success < defaults.PLACEMENT_DEMOTE_SUCCESS:
            self._demoted.add(est.peer)
            self._flip_demotion(est.peer, True, now, "demote")
        elif demoted and est.success >= defaults.PLACEMENT_RECOVER_SUCCESS:
            self._demoted.discard(est.peer)
            self._flip_demotion(est.peer, False, now, "recover")

    def _flip_demotion(self, peer: bytes, demoted: bool, now: float,
                       action: str) -> None:
        _DEMOTIONS.inc(action=action)
        obs_journal.emit("placement_demotion", peer=peer_label(peer),
                         action=action)
        if self.store is not None:
            try:
                self.store.set_placement_demoted(peer, demoted, now=now)
            except Exception:
                pass  # telemetry must never fail a transfer

    def get(self, peer_id: bytes) -> Optional[PeerEstimate]:
        with self._lock:
            return self._est.get(bytes(peer_id))

    def all(self) -> List[PeerEstimate]:
        with self._lock:
            return list(self._est.values())
