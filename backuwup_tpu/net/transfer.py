"""Bounded-concurrency transfer scheduler: the send plane's fan-out seam.

The reference transmits strictly one file at a time (``send.rs`` awaits
every ack inline); this module lets the engine keep many uploads in
flight at once — every missing shard of a stripe to its distinct peer,
and several whole packfiles to several connected peers — while keeping
the three invariants the serial code had for free (docs/transfer.md):

* **Per-peer ordering.**  One ``asyncio.Lock`` per peer serializes the
  actual sends to that peer, and submissions park on the lock in FIFO
  order, so a peer observes the same file sequence the serial loop would
  have produced.  This is load-bearing: a Transport assigns its signed
  sequence number synchronously inside ``send_data`` and the receiver
  rejects any reordering as a "sequence break", so two concurrent
  ``send_data`` calls on one transport would poison the session.
* **Bounded in-flight bytes.**  Admission waits until the payload fits
  under ``max_inflight_bytes`` (and ``max_transfers``); a transfer larger
  than the whole budget is still admitted when nothing else is in flight
  so oversize files cannot deadlock the plane.  The cap bounds the RAM
  the plane holds *in addition to* the Orchestrator's on-disk buffer
  accounting — payloads are read inside the submitted coroutine, after
  admission, so queued transfers hold no bytes.
* **Failure isolation.**  Each transfer's exception is captured in its
  ``TransferResult``; sibling transfers to other peers run to completion
  and the caller decides per-peer what to drop — exactly the blast
  radius a failed peer had under the serial loop.

Telemetry flows through ``messenger.transfer`` per completed transfer
(in-flight gauges, wait/send stage times) so the UI can watch the plane
breathe.  The fault plane (utils/faults.py) hooks the Transport layer
below this module and keeps working unchanged.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Dict, List, Optional

from .. import defaults
from ..obs import journal as obs_journal
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .p2p import P2PError, SendProgress

_WAIT_SECONDS = obs_metrics.histogram(
    "bkw_transfer_wait_seconds",
    "Seconds a transfer waits on per-peer ordering + byte admission")
_SEND_SECONDS = obs_metrics.histogram(
    "bkw_transfer_send_seconds",
    "Seconds spent in ws.send + ack per transfer")
_TRANSFERS = obs_metrics.counter(
    "bkw_transfers_total", "Completed transfers by outcome", ("outcome",))
_BYTES_SENT = obs_metrics.counter(
    "bkw_transfer_bytes_total", "Payload bytes successfully transferred")
#: resume-plane waste gauge: payload bytes shipped more than once because
#: a transfer was cut and continued (engine._send_resumable accounts the
#: overlap between attempts).  The wan scenario gates on this staying
#: under budget — resume means re-sending the tail, not the file.
BYTES_RESENT = obs_metrics.counter(
    "bkw_transfer_bytes_resent_total",
    "Payload bytes re-sent across resume attempts")
_INFLIGHT = obs_metrics.gauge(
    "bkw_transfer_inflight", "Transfers currently admitted")
_INFLIGHT_BYTES = obs_metrics.gauge(
    "bkw_transfer_inflight_bytes", "Payload bytes currently admitted")
# --- restore data plane (download lanes; docs/transfer.md) -------------------
RESTORE_BYTES_PULLED = obs_metrics.counter(
    "bkw_restore_bytes_pulled_total",
    "Payload bytes pulled through download lanes, by source peer",
    ("peer",))
RESTORE_HEDGES = obs_metrics.counter(
    "bkw_restore_hedges_total",
    "Hedged redundant pulls by outcome: won = the hedge's shard was used,"
    " lost = the stalled primary finished first anyway, wasted = neither"
    " pull delivered", ("outcome",))
RESTORE_SOURCES = obs_metrics.histogram(
    "bkw_restore_sources_per_stripe",
    "Distinct source peers a restored stripe's shards were pulled from",
    buckets=(1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 12.0))


@dataclass
class TransferResult:
    """Outcome of one scheduled transfer (never raises past the plane)."""

    peer_id: bytes
    size: int
    ok: bool
    error: Optional[BaseException] = None
    label: str = ""
    wait_s: float = 0.0  # admission + per-peer ordering queue time
    send_s: float = 0.0  # time inside the send coroutine


class TransferScheduler:
    """Admission control + per-peer ordering for concurrent uploads.

    ``submit`` returns an ``asyncio.Task`` resolving to a
    ``TransferResult``; the task never raises (cancellation aside), so a
    ``gather`` over a batch cannot be torn down by one bad peer.
    """

    def __init__(self, max_inflight_bytes: Optional[int] = None,
                 max_transfers: Optional[int] = None, messenger=None,
                 peer_stats=None):
        self.max_inflight_bytes = int(
            defaults.TRANSFER_INFLIGHT_BYTE_CAP
            if max_inflight_bytes is None else max_inflight_bytes)
        self.max_transfers = int(
            defaults.TRANSFER_MAX_INFLIGHT
            if max_transfers is None else max_transfers)
        self.messenger = messenger
        self.peer_stats = peer_stats  # net/peer_stats.py estimator bank
        self.inflight_bytes = 0
        self.inflight_count = 0
        self.completed = 0
        self.failed = 0
        self.bytes_sent = 0
        self.bytes_pulled = 0
        self.stage_s = {"wait": 0.0, "send": 0.0}
        self._cond = asyncio.Condition()
        self._peer_locks: Dict[bytes, asyncio.Lock] = {}
        self._peer_pending: Dict[bytes, int] = {}

    def peer_busy(self, peer_id: bytes) -> bool:
        """True while any submitted transfer to ``peer_id`` is unresolved
        (parked behind the per-peer lock or actively sending).  Connection
        management consults this before closing a transport: dropping a
        socket with pending jobs strands their ack waits and forces an
        abort-and-resume redial for work that was proceeding fine."""
        return self._peer_pending.get(bytes(peer_id), 0) > 0

    # --- admission (the in-flight byte cap) --------------------------------

    async def _admit(self, size: int) -> None:
        async with self._cond:
            while self.inflight_count > 0 and (
                    self.inflight_count >= self.max_transfers
                    or self.inflight_bytes + size > self.max_inflight_bytes):
                await self._cond.wait()
            self.inflight_count += 1
            self.inflight_bytes += size
            _INFLIGHT.set(self.inflight_count)
            _INFLIGHT_BYTES.set(self.inflight_bytes)

    async def _release(self, size: int) -> None:
        async with self._cond:
            self.inflight_count -= 1
            self.inflight_bytes -= size
            _INFLIGHT.set(self.inflight_count)
            _INFLIGHT_BYTES.set(self.inflight_bytes)
            self._cond.notify_all()

    # --- submission --------------------------------------------------------

    def submit(self, peer_id: bytes, size: int,
               send: Callable[[], Awaitable[None]],
               label: str = "") -> "asyncio.Task[TransferResult]":
        """Schedule ``send()`` (which reads + transmits + does post-ack
        bookkeeping) as one bounded, peer-ordered transfer."""
        return asyncio.ensure_future(
            self._run(bytes(peer_id), int(size), send, label))

    def submit_pull(self, peer_id: bytes, size: int,
                    pull: Callable[[], Awaitable[Optional[int]]],
                    label: str = "") -> "asyncio.Task[TransferResult]":
        """Schedule ``pull()`` — a download from ``peer_id`` — on the same
        plane: same per-peer ordering (a pull and an upload to one peer
        must not interleave on one signed-sequence session), same byte
        admission (``size`` is the expected payload), same failure
        isolation.  ``pull()`` may return the actual byte count received;
        successful pulls feed the peer estimators as receive-direction
        samples and ``bkw_restore_bytes_pulled_total{peer}``."""
        return asyncio.ensure_future(
            self._run(bytes(peer_id), int(size), pull, label,
                      direction="pull"))

    async def _run(self, peer_id: bytes, size: int,
                   send: Callable[[], Awaitable[None]],
                   label: str, direction: str = "send") -> TransferResult:
        # pending-count bookkeeping wraps the whole job — including the
        # park behind the per-peer lock — so peer_busy() covers queued
        # work and survives cancellation mid-wait
        self._peer_pending[peer_id] = self._peer_pending.get(peer_id, 0) + 1
        try:
            return await self._run_locked(peer_id, size, send, label,
                                          direction)
        finally:
            n = self._peer_pending.get(peer_id, 1) - 1
            if n <= 0:
                self._peer_pending.pop(peer_id, None)
            else:
                self._peer_pending[peer_id] = n

    async def _run_locked(self, peer_id: bytes, size: int,
                          send: Callable[[], Awaitable[None]],
                          label: str, direction: str = "send"
                          ) -> TransferResult:
        t0 = time.monotonic()
        # Per-peer lock first: asyncio.Lock wakes waiters FIFO and tasks
        # run synchronously up to their first await, so same-peer
        # transfers send in submission order.  Admission happens inside
        # the lock so parked transfers hold no byte budget.
        lock = self._peer_locks.setdefault(peer_id, asyncio.Lock())
        async with lock:
            await self._admit(size)
            t1 = time.monotonic()
            try:
                # the span inherits the submitting backup's trace id (the
                # contextvar copied into this task at submit time) and is
                # what _sign_body stamps onto the envelope
                with obs_trace.span("transfer." + direction):
                    out = await send()
                result = TransferResult(peer_id, size, True, label=label)
                if isinstance(out, int) and out >= 0:
                    # downloads report actual bytes received; the
                    # estimators should learn the real rate, not the plan
                    result.size = out
            except (Exception, asyncio.TimeoutError) as e:
                result = TransferResult(peer_id, size, False, error=e,
                                        label=label)
            finally:
                t2 = time.monotonic()
                await self._release(size)
        result.wait_s = t1 - t0
        result.send_s = t2 - t1
        self.stage_s["wait"] += result.wait_s
        self.stage_s["send"] += result.send_s
        _WAIT_SECONDS.observe(result.wait_s)
        _SEND_SECONDS.observe(result.send_s)
        ok_word = "sent" if direction == "send" else "pulled"
        _TRANSFERS.inc(outcome=ok_word if result.ok else "failed")
        if result.ok:
            self.completed += 1
            if direction == "send":
                self.bytes_sent += size
                _BYTES_SENT.inc(size)
            else:
                self.bytes_pulled += result.size
                RESTORE_BYTES_PULLED.inc(result.size,
                                         peer=peer_id.hex()[:16])
        else:
            self.failed += 1
        if self.peer_stats is not None:
            try:
                self.peer_stats.observe(result)
            except Exception:
                pass  # estimators are hints; never fail a transfer
        if self.messenger is not None:
            self.messenger.transfer(
                peer_id.hex()[:16], ok_word if result.ok else "failed",
                size=size, inflight=self.inflight_count,
                inflight_bytes=self.inflight_bytes,
                wait_ms=result.wait_s * 1000.0,
                send_ms=result.send_s * 1000.0, label=label)
        return result

    # --- shared resume loop (upload, restore and repair all ride it) --------

    @staticmethod
    async def run_resumable(transport, peer_id: bytes, data: bytes,
                            file_info, file_id: bytes, *,
                            throughput_bps: float = 0.0,
                            redial: Optional[Callable] = None,
                            on_drop: Optional[Callable] = None,
                            resume: Optional[bool] = None,
                            attempts: Optional[int] = None) -> None:
        """``send_file`` with the abort-and-resume loop around it
        (formerly ``Engine._send_resumable`` — it lives in the scheduler
        now so every send path shares one loop).

        A mid-transfer failure (cut link, stalled ack) drops the poisoned
        transport via ``on_drop``, reconnects via ``redial`` (an async
        callable returning a fresh started Transport — the caller owns
        connection bookkeeping), and continues the chunked send from the
        receiver's verified offset, up to ``attempts`` reconnects before
        the failure surfaces.  Bytes shipped more than once across
        attempts are accounted to ``bkw_transfer_bytes_resent_total``
        (the wan scenario's budget)."""
        peer_id = bytes(peer_id)
        if resume is None:
            resume = bool(defaults.TRANSFER_RESUME_ENABLED)
        if attempts is None:
            attempts = int(defaults.TRANSFER_RESUME_ATTEMPTS)
        hwm = 0  # high-water wire offset across attempts
        t = transport
        for attempt in range(attempts + 1):
            prog = SendProgress()
            try:
                await t.send_file(data, file_info, file_id, resume=resume,
                                  throughput_bps=throughput_bps,
                                  progress=prog)
                BYTES_RESENT.inc(max(0, min(prog.offset, hwm)
                                     - prog.started))
                return
            except P2PError as e:
                # the overlap between this attempt's shipped range and
                # anything shipped before is waste the resume plane
                # failed to avoid
                BYTES_RESENT.inc(max(0, min(prog.offset, hwm)
                                     - prog.started))
                hwm = max(hwm, prog.offset)
                if on_drop is not None:
                    await on_drop()
                if attempt >= attempts or redial is None:
                    raise
                obs_journal.emit("transfer_resume",
                                 peer=peer_id.hex()[:16],
                                 attempt=attempt + 1,
                                 offset=prog.offset, error=str(e))
                t = await redial()

    # --- download lanes: re-queue + hedging ---------------------------------

    async def pull_with_requeue(self, sources: List[bytes], size: int,
                                make_pull: Callable, label: str = ""
                                ) -> Optional[TransferResult]:
        """One logical download over a ranked candidate list: run
        ``make_pull(peer)()`` on the best source; when it fails or stalls
        out, re-queue the same work behind the next-healthiest candidate
        instead of hammering the peer that just failed.  Returns the first
        successful result, the last failure when every candidate failed,
        or None when ``sources`` is empty."""
        last: Optional[TransferResult] = None
        for peer in list(sources):
            res = await self.submit_pull(peer, size, make_pull(peer),
                                         label=label)
            if res.ok:
                return res
            last = res
            obs_journal.emit("restore_requeue",
                             peer=bytes(peer).hex()[:16], label=label,
                             error=str(res.error))
        return last

    async def pull_hedged(self, primary: "asyncio.Task[TransferResult]",
                          spawn_hedge: Callable, hedge_after_s: float
                          ) -> Optional[TransferResult]:
        """Race a lagging download against a redundant one.

        ``primary`` is an already-submitted pull task.  If it neither
        completes nor fails within ``hedge_after_s``, ``spawn_hedge()`` is
        invoked to launch a redundant pull (of an equivalent spare shard,
        from a different holder; it may return None when no spare is
        available) and the two race — the first success wins and the
        loser is cancelled, so a stalled holder costs the hedge delay,
        never the full deadline.  Outcomes land in
        ``bkw_restore_hedges_total``: won (the hedge delivered), lost
        (the primary recovered first anyway), wasted (both failed)."""
        try:
            return await asyncio.wait_for(asyncio.shield(primary),
                                          hedge_after_s)
        except asyncio.TimeoutError:
            pass  # primary is lagging: hedge it
        except asyncio.CancelledError:
            raise
        hedge = spawn_hedge()
        if hedge is None:
            try:
                return await primary
            except asyncio.CancelledError:
                return None
        done, pending = await asyncio.wait(
            {primary, hedge}, return_when=asyncio.FIRST_COMPLETED)

        def _result(task):
            try:
                return task.result()
            except asyncio.CancelledError:
                return None

        first_ok = None
        for task in done:
            r = _result(task)
            if r is not None and r.ok:
                # prefer the primary when both landed in the same tick:
                # its bytes were already counted and the hedge was waste
                if first_ok is None or task is primary:
                    first_ok = (task, r)
        if first_ok is not None:
            for task in pending:
                task.cancel()
            outcome = "lost" if first_ok[0] is primary else "won"
            RESTORE_HEDGES.inc(outcome=outcome)
            return first_ok[1]
        # the first finisher failed; the race is decided by the survivor
        survivor = next(iter(pending), None)
        sr = None
        if survivor is not None:
            try:
                sr = await survivor
            except asyncio.CancelledError:
                sr = None
        if sr is not None and sr.ok:
            RESTORE_HEDGES.inc(
                outcome="won" if survivor is hedge else "lost")
            return sr
        RESTORE_HEDGES.inc(outcome="wasted")
        for task in done:
            r = _result(task)
            if r is not None:
                return r
        return sr

    @staticmethod
    async def gather(tasks: List["asyncio.Task[TransferResult]"]
                     ) -> List[TransferResult]:
        """Await a batch; results arrive in submission order and no
        exception escapes (each task resolves to a TransferResult)."""
        if not tasks:
            return []
        return list(await asyncio.gather(*tasks))

    @staticmethod
    async def as_completed(tasks: List["asyncio.Task[TransferResult]"]):
        """Yield each ``TransferResult`` the moment its transfer
        resolves (completion order, not submission order) — the reap
        side of continuous admission (docs/dataflow.md): the caller
        reacts to a failed peer while its siblings are still on the
        wire instead of after the whole batch gathers."""
        pending = set(tasks)
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                yield t.result()
