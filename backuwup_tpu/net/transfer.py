"""Bounded-concurrency transfer scheduler: the send plane's fan-out seam.

The reference transmits strictly one file at a time (``send.rs`` awaits
every ack inline); this module lets the engine keep many uploads in
flight at once — every missing shard of a stripe to its distinct peer,
and several whole packfiles to several connected peers — while keeping
the three invariants the serial code had for free (docs/transfer.md):

* **Per-peer ordering.**  One ``asyncio.Lock`` per peer serializes the
  actual sends to that peer, and submissions park on the lock in FIFO
  order, so a peer observes the same file sequence the serial loop would
  have produced.  This is load-bearing: a Transport assigns its signed
  sequence number synchronously inside ``send_data`` and the receiver
  rejects any reordering as a "sequence break", so two concurrent
  ``send_data`` calls on one transport would poison the session.
* **Bounded in-flight bytes.**  Admission waits until the payload fits
  under ``max_inflight_bytes`` (and ``max_transfers``); a transfer larger
  than the whole budget is still admitted when nothing else is in flight
  so oversize files cannot deadlock the plane.  The cap bounds the RAM
  the plane holds *in addition to* the Orchestrator's on-disk buffer
  accounting — payloads are read inside the submitted coroutine, after
  admission, so queued transfers hold no bytes.
* **Failure isolation.**  Each transfer's exception is captured in its
  ``TransferResult``; sibling transfers to other peers run to completion
  and the caller decides per-peer what to drop — exactly the blast
  radius a failed peer had under the serial loop.

Telemetry flows through ``messenger.transfer`` per completed transfer
(in-flight gauges, wait/send stage times) so the UI can watch the plane
breathe.  The fault plane (utils/faults.py) hooks the Transport layer
below this module and keeps working unchanged.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Dict, List, Optional

from .. import defaults
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

_WAIT_SECONDS = obs_metrics.histogram(
    "bkw_transfer_wait_seconds",
    "Seconds a transfer waits on per-peer ordering + byte admission")
_SEND_SECONDS = obs_metrics.histogram(
    "bkw_transfer_send_seconds",
    "Seconds spent in ws.send + ack per transfer")
_TRANSFERS = obs_metrics.counter(
    "bkw_transfers_total", "Completed transfers by outcome", ("outcome",))
_BYTES_SENT = obs_metrics.counter(
    "bkw_transfer_bytes_total", "Payload bytes successfully transferred")
#: resume-plane waste gauge: payload bytes shipped more than once because
#: a transfer was cut and continued (engine._send_resumable accounts the
#: overlap between attempts).  The wan scenario gates on this staying
#: under budget — resume means re-sending the tail, not the file.
BYTES_RESENT = obs_metrics.counter(
    "bkw_transfer_bytes_resent_total",
    "Payload bytes re-sent across resume attempts")
_INFLIGHT = obs_metrics.gauge(
    "bkw_transfer_inflight", "Transfers currently admitted")
_INFLIGHT_BYTES = obs_metrics.gauge(
    "bkw_transfer_inflight_bytes", "Payload bytes currently admitted")


@dataclass
class TransferResult:
    """Outcome of one scheduled transfer (never raises past the plane)."""

    peer_id: bytes
    size: int
    ok: bool
    error: Optional[BaseException] = None
    label: str = ""
    wait_s: float = 0.0  # admission + per-peer ordering queue time
    send_s: float = 0.0  # time inside the send coroutine


class TransferScheduler:
    """Admission control + per-peer ordering for concurrent uploads.

    ``submit`` returns an ``asyncio.Task`` resolving to a
    ``TransferResult``; the task never raises (cancellation aside), so a
    ``gather`` over a batch cannot be torn down by one bad peer.
    """

    def __init__(self, max_inflight_bytes: Optional[int] = None,
                 max_transfers: Optional[int] = None, messenger=None,
                 peer_stats=None):
        self.max_inflight_bytes = int(
            defaults.TRANSFER_INFLIGHT_BYTE_CAP
            if max_inflight_bytes is None else max_inflight_bytes)
        self.max_transfers = int(
            defaults.TRANSFER_MAX_INFLIGHT
            if max_transfers is None else max_transfers)
        self.messenger = messenger
        self.peer_stats = peer_stats  # net/peer_stats.py estimator bank
        self.inflight_bytes = 0
        self.inflight_count = 0
        self.completed = 0
        self.failed = 0
        self.bytes_sent = 0
        self.stage_s = {"wait": 0.0, "send": 0.0}
        self._cond = asyncio.Condition()
        self._peer_locks: Dict[bytes, asyncio.Lock] = {}

    # --- admission (the in-flight byte cap) --------------------------------

    async def _admit(self, size: int) -> None:
        async with self._cond:
            while self.inflight_count > 0 and (
                    self.inflight_count >= self.max_transfers
                    or self.inflight_bytes + size > self.max_inflight_bytes):
                await self._cond.wait()
            self.inflight_count += 1
            self.inflight_bytes += size
            _INFLIGHT.set(self.inflight_count)
            _INFLIGHT_BYTES.set(self.inflight_bytes)

    async def _release(self, size: int) -> None:
        async with self._cond:
            self.inflight_count -= 1
            self.inflight_bytes -= size
            _INFLIGHT.set(self.inflight_count)
            _INFLIGHT_BYTES.set(self.inflight_bytes)
            self._cond.notify_all()

    # --- submission --------------------------------------------------------

    def submit(self, peer_id: bytes, size: int,
               send: Callable[[], Awaitable[None]],
               label: str = "") -> "asyncio.Task[TransferResult]":
        """Schedule ``send()`` (which reads + transmits + does post-ack
        bookkeeping) as one bounded, peer-ordered transfer."""
        return asyncio.ensure_future(
            self._run(bytes(peer_id), int(size), send, label))

    async def _run(self, peer_id: bytes, size: int,
                   send: Callable[[], Awaitable[None]],
                   label: str) -> TransferResult:
        t0 = time.monotonic()
        # Per-peer lock first: asyncio.Lock wakes waiters FIFO and tasks
        # run synchronously up to their first await, so same-peer
        # transfers send in submission order.  Admission happens inside
        # the lock so parked transfers hold no byte budget.
        lock = self._peer_locks.setdefault(peer_id, asyncio.Lock())
        async with lock:
            await self._admit(size)
            t1 = time.monotonic()
            try:
                # the span inherits the submitting backup's trace id (the
                # contextvar copied into this task at submit time) and is
                # what _sign_body stamps onto the envelope
                with obs_trace.span("transfer.send"):
                    await send()
                result = TransferResult(peer_id, size, True, label=label)
            except (Exception, asyncio.TimeoutError) as e:
                result = TransferResult(peer_id, size, False, error=e,
                                        label=label)
            finally:
                t2 = time.monotonic()
                await self._release(size)
        result.wait_s = t1 - t0
        result.send_s = t2 - t1
        self.stage_s["wait"] += result.wait_s
        self.stage_s["send"] += result.send_s
        _WAIT_SECONDS.observe(result.wait_s)
        _SEND_SECONDS.observe(result.send_s)
        _TRANSFERS.inc(outcome="sent" if result.ok else "failed")
        if result.ok:
            self.completed += 1
            self.bytes_sent += size
            _BYTES_SENT.inc(size)
        else:
            self.failed += 1
        if self.peer_stats is not None:
            try:
                self.peer_stats.observe(result)
            except Exception:
                pass  # estimators are hints; never fail a transfer
        if self.messenger is not None:
            self.messenger.transfer(
                peer_id.hex()[:16], "sent" if result.ok else "failed",
                size=size, inflight=self.inflight_count,
                inflight_bytes=self.inflight_bytes,
                wait_ms=result.wait_s * 1000.0,
                send_ms=result.send_s * 1000.0, label=label)
        return result

    @staticmethod
    async def gather(tasks: List["asyncio.Task[TransferResult]"]
                     ) -> List[TransferResult]:
        """Await a batch; results arrive in submission order and no
        exception escapes (each task resolves to a TransferResult)."""
        if not tasks:
            return []
        return list(await asyncio.gather(*tasks))
