"""Swappable server storage backend (the PR-10 scale-out seam).

The coordination plane's persistent state is small and *naturally
shard-keyed*: every row the server writes — client identity, negotiation
edges, snapshots, audit verdicts, repair reports — is keyed by a client
pubkey (or a pubkey pair).  :class:`ServerStore` pins that contract down
as an abstract interface so the request tier in ``net/server.py`` stays
stateless: a Postgres/Vitess-style horizontally sharded twin can slot in
behind the same method set, routing each call by its leading pubkey
argument, without the handlers changing.

:class:`SqliteServerStore` is the embedded implementation, in two modes:

* **write-behind (default)** — a single writer thread owns the sqlite
  connection; every operation (reads included, which buys read-your-
  writes ordering for free) is submitted to an op queue and executed on
  that thread.  The writer drains whatever has queued since the last
  batch and commits ONCE per drain — group commit: under load, hundreds
  of single-row writes amortize one ``COMMIT`` (and one fsync when
  fsync discipline is on).  Callers get a future that resolves only
  *after* the commit, so an ``await store.aio.save_snapshot(...)`` in a
  handler is a durability barrier: the response cannot be written until
  the row is committed, yet the event loop never blocks — the commit
  happens on the writer thread (asserted by the swarm test's event-loop
  stall detector and by :attr:`commit_threads`).
* **direct** (``write_behind=False``, the :class:`ServerDB` shim) — the
  pre-PR-10 shape: every call executes inline on the calling thread and
  commits immediately.  Kept as the measured baseline for bench config
  ``12_swarm`` and for tests that predate the writer thread.  Unlike
  the original, calls are serialized under an RLock: the original
  shared one ``check_same_thread=False`` connection across threads with
  no serialization at all (the latent bug this PR's regression test
  hammers).

Fsync discipline follows ``utils/durable.py`` semantics: when
``durable.FSYNC_ENABLED`` (the ``BKW_FSYNC`` switch) a file-backed
database runs ``PRAGMA synchronous=FULL`` so a group commit is a real
durability barrier; with fsync disabled it drops to ``NORMAL`` (the
pure-tmpfs test posture).  Both store modes apply the same pragma so the
bench's baseline-vs-sharded comparison is durability-for-durability.
"""

from __future__ import annotations

import abc
import asyncio
import queue
import sqlite3
import threading
import time
from concurrent.futures import Future
from pathlib import Path
from typing import Dict, List, Optional

from .. import defaults
from ..obs import metrics as obs_metrics
from ..utils import durable
from .ring import partition_of as ring_partition_of

_COMMITS = obs_metrics.counter(
    "bkw_server_store_commits_total",
    "Server-store sqlite commits by mode (group = write-behind batch)",
    ("mode",))
_BATCH_OPS = obs_metrics.histogram(
    "bkw_server_store_batch_ops",
    "Operations drained per write-behind group commit",
    buckets=obs_metrics.log_buckets(1.0, 2.0, 11))
_OP_QUEUE_DEPTH = obs_metrics.gauge(
    "bkw_server_store_queue_depth",
    "Write-behind operations waiting for the writer thread")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS clients (
    pubkey BLOB PRIMARY KEY,
    registered REAL NOT NULL,
    last_login REAL
);
CREATE TABLE IF NOT EXISTS peer_backups (
    source BLOB NOT NULL,
    destination BLOB NOT NULL,
    size_negotiated INTEGER NOT NULL,
    timestamp REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS snapshots (
    client_pubkey BLOB NOT NULL,
    snapshot_hash BLOB NOT NULL,
    timestamp REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS snapshots_by_client
    ON snapshots (client_pubkey, timestamp);
CREATE TABLE IF NOT EXISTS audit_reports (
    reporter BLOB NOT NULL,
    peer BLOB NOT NULL,
    passed INTEGER NOT NULL,
    detail TEXT NOT NULL DEFAULT '',
    timestamp REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS audit_reports_by_peer
    ON audit_reports (peer, timestamp);
CREATE TABLE IF NOT EXISTS repair_reports (
    reporter BLOB NOT NULL,
    peer BLOB NOT NULL,
    packfiles_lost INTEGER NOT NULL,
    bytes_lost INTEGER NOT NULL,
    bytes_replaced INTEGER NOT NULL,
    timestamp REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS metadata (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL
);
"""

#: Bump when the schema changes shape; pre-versioning databases (PR 1 and
#: earlier, which had no ``metadata`` table) count as version 1.
SCHEMA_VERSION = 2

#: THE migration seam: ``{from_version: [SQL statements]}`` applied in
#: sequence by the boot-time migrate to reach ``from_version + 1``.
#: Statements must be idempotent (IF NOT EXISTS / OR IGNORE) because a
#: crash between a migration and the version stamp replays it on the next
#: boot.  A Postgres twin of SqliteServerStore would run the same ladder.
_MIGRATIONS = {
    # v1 (PR 1) -> v2: repair_reports + the metadata table itself.  Both
    # already appear in _SCHEMA's CREATE IF NOT EXISTS, so this rung is
    # empty — it exists to document the pattern for the next real change.
    1: [],
}


class ServerStore(abc.ABC):
    """Abstract coordination-plane store, keyed by client pubkey.

    Every method's FIRST pubkey argument is its shard key; a distributed
    implementation routes on it.  ``peer_backups`` rows are dual-homed
    (one copy under each endpoint's shard) in such a deployment — the
    sqlite implementation keeps one table and both query directions.

    Implementations must expose:

    * the synchronous method set below (tests and setup scripts call
      them directly; they may block briefly),
    * :attr:`aio` — the same methods as awaitables that never block the
      event loop AND, for writes, resolve only once the write is
      durable (the request tier's durability barrier),
    * :meth:`flush` / :meth:`close` lifecycle hooks.
    """

    @abc.abstractmethod
    def register_client(self, pubkey: bytes) -> None: ...

    @abc.abstractmethod
    def client_exists(self, pubkey: bytes) -> bool: ...

    @abc.abstractmethod
    def client_update_logged_in(self, pubkey: bytes) -> None: ...

    @abc.abstractmethod
    def save_storage_negotiated(self, source: bytes, destination: bytes,
                                size: int) -> None: ...

    @abc.abstractmethod
    def delete_storage_negotiated(self, source: bytes, destination: bytes,
                                  size: int) -> None: ...

    @abc.abstractmethod
    def save_snapshot(self, pubkey: bytes, snapshot_hash: bytes) -> None: ...

    @abc.abstractmethod
    def get_latest_client_snapshot(self,
                                   pubkey: bytes) -> Optional[bytes]: ...

    @abc.abstractmethod
    def get_client_negotiated_peers(self, pubkey: bytes) -> list: ...

    @abc.abstractmethod
    def get_clients_storing_on(self, pubkey: bytes) -> list: ...

    @abc.abstractmethod
    def save_audit_report(self, reporter: bytes, peer: bytes, passed: bool,
                          detail: str) -> None: ...

    @abc.abstractmethod
    def save_repair_report(self, reporter: bytes, peer: bytes,
                           packfiles_lost: int, bytes_lost: int,
                           bytes_replaced: int) -> None: ...

    @abc.abstractmethod
    def reclaim_negotiation(self, client: bytes, peer: bytes) -> int: ...

    @abc.abstractmethod
    def audit_failing_reporters(self, peer: bytes,
                                window_s: float) -> int: ...

    @abc.abstractmethod
    def schema_version(self) -> int: ...

    def flush(self) -> None:
        """Barrier: every previously submitted write is durable on
        return."""

    def close(self) -> None:
        """Stop background machinery; the store stays usable for
        post-shutdown reads (tests inspect state after server.stop())."""


class _AioFacade:
    """``store.aio.<method>(...)`` — the handler-facing async view.

    Write-behind: wraps the op's :class:`~concurrent.futures.Future` so
    the coroutine resumes only after the writer thread's group commit.
    Direct mode: runs the sync method inline on the event loop —
    deliberately preserving the pre-PR-10 blocking-commit behavior for
    the bench baseline.
    """

    def __init__(self, store: "SqliteServerStore"):
        self._store = store

    def __getattr__(self, name: str):
        op = getattr(type(self._store), "_op_" + name, None)
        if op is None:
            raise AttributeError(name)
        store = self._store

        async def call(*args):
            if not store.write_behind:
                return getattr(store, name)(*args)
            import asyncio
            return await asyncio.wrap_future(store._submit(op, args))

        call.__name__ = name
        return call


class SqliteServerStore(ServerStore):
    """Embedded sqlite ServerStore; see the module docstring for the
    write-behind/direct split."""

    def __init__(self, path, write_behind: bool = True):
        self.path = path
        self.write_behind = bool(write_behind)
        #: thread idents observed executing COMMIT for request-path ops
        #: (NOT the constructor's schema bootstrap) — the swarm test
        #: asserts the event-loop thread never appears here.
        self.commit_threads: set = set()
        self._db = sqlite3.connect(path, check_same_thread=False)
        if path != ":memory:":
            self._db.execute("PRAGMA journal_mode=WAL")
            # Federation opens the same partition files from several
            # store instances (node revive, multi-process bench legs):
            # wait out a sibling's group commit instead of raising
            # "database is locked" into a request handler.
            self._db.execute("PRAGMA busy_timeout=5000")
            # fsync-disciplined group commit (utils/durable.py semantics):
            # FULL makes each COMMIT a durability barrier; with fsync
            # globally off (BKW_FSYNC=0 test runs) NORMAL suffices.
            self._db.execute("PRAGMA synchronous=%s"
                             % ("FULL" if durable.FSYNC_ENABLED
                                else "NORMAL"))
        self._db.executescript(_SCHEMA)
        self._db.commit()
        self._migrate()  # raises synchronously on a newer-schema database
        self._direct_lock = threading.RLock()
        self._submit_lock = threading.Lock()
        self._closed = False
        self._ops: "queue.SimpleQueue" = queue.SimpleQueue()
        self._depth = 0
        self._writer: Optional[threading.Thread] = None
        if self.write_behind:
            self._writer = threading.Thread(
                target=self._writer_loop, name="serverstore-writer",
                daemon=True)
            self._writer.start()

    # --- write-behind machinery --------------------------------------------

    def _submit(self, op, args) -> Future:
        fut: Future = Future()
        with self._submit_lock:
            if self._closed or not self.write_behind:
                # post-close (or direct-mode) fallback: run inline,
                # serialized, committed immediately
                try:
                    with self._direct_lock:
                        result = op(self._db, *args)
                        self._commit("direct")
                    fut.set_result(result)
                except BaseException as e:
                    fut.set_exception(e)
                return fut
            self._ops.put((op, args, fut))
            self._depth += 1
            _OP_QUEUE_DEPTH.set(self._depth)
        return fut

    def _writer_loop(self) -> None:
        while True:
            head = self._ops.get()
            if head is None:
                return
            batch = [head]
            # group commit: drain everything already queued (bounded so a
            # firehose cannot starve the commit), execute, commit ONCE,
            # then resolve every future — durability before acknowledgment
            while len(batch) < defaults.SERVER_STORE_MAX_BATCH:
                try:
                    nxt = self._ops.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._ops.put(None)  # re-arm shutdown for next round
                    break
                batch.append(nxt)
            with self._submit_lock:
                self._depth -= len(batch)
                _OP_QUEUE_DEPTH.set(max(self._depth, 0))
            results = []
            for op, args, _fut in batch:
                try:
                    results.append((True, op(self._db, *args)))
                except BaseException as e:  # per-op isolation
                    results.append((False, e))
            self._commit("group")
            _BATCH_OPS.observe(float(len(batch)))
            for (ok, value), (_op, _args, fut) in zip(results, batch):
                if ok:
                    fut.set_result(value)
                else:
                    fut.set_exception(value)

    def _commit(self, mode: str) -> None:
        if self._db.in_transaction:
            self._db.commit()
            _COMMITS.inc(mode=mode)
            self.commit_threads.add(threading.get_ident())

    def flush(self) -> None:
        if self.write_behind and not self._closed:
            self._submit(lambda _conn: None, ()).result()

    def close(self) -> None:
        """Drain the op queue, stop the writer thread, and flip to the
        inline fallback (the connection stays open so post-shutdown test
        reads keep working)."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        if self._writer is not None:
            self._ops.put(None)
            self._writer.join(timeout=10)
            self._writer = None

    # --- sync + async facades ----------------------------------------------

    @property
    def aio(self) -> _AioFacade:
        return _AioFacade(self)

    def _run(self, op, *args):
        if self.write_behind:
            return self._submit(op, args).result()
        with self._direct_lock:
            result = op(self._db, *args)
            self._commit("direct")
            return result

    # --- schema ------------------------------------------------------------

    def _migrate(self) -> None:
        """Boot-time schema version check (runs on the constructing
        thread, before the writer starts, so version errors raise
        synchronously).

        * fresh or pre-versioning database -> run the ladder from v1 and
          stamp :data:`SCHEMA_VERSION` (the _SCHEMA script is idempotent,
          so replaying it on a v1 database upgrades it in place);
        * versioned database older than the code -> apply each rung of
          :data:`_MIGRATIONS` in order, stamping after each one;
        * database NEWER than the code -> refuse to start: old code
          writing rows a newer schema reinterprets is silent corruption.
        """
        row = self._db.execute(
            "SELECT value FROM metadata WHERE key = 'schema_version'"
        ).fetchone()
        version = int(row[0]) if row is not None else 1
        if version > SCHEMA_VERSION:
            raise RuntimeError(
                f"database schema v{version} is newer than this server"
                f" (v{SCHEMA_VERSION}); upgrade the server binary")
        while version < SCHEMA_VERSION:
            for stmt in _MIGRATIONS.get(version, ()):
                self._db.execute(stmt)
            version += 1
            self._db.execute(
                "INSERT INTO metadata (key, value) VALUES"
                " ('schema_version', ?) ON CONFLICT(key)"
                " DO UPDATE SET value = excluded.value", (str(version),))
            self._db.commit()
        if row is None:
            self._db.execute(
                "INSERT OR IGNORE INTO metadata (key, value) VALUES"
                " ('schema_version', ?)", (str(SCHEMA_VERSION),))
            self._db.commit()

    # --- operations (each = one statement batch on the writer's conn) ------
    # The _op_* staticmethods are the single source of truth: the sync
    # facade and store.aio both execute exactly these against the one
    # connection, so ordering and read-your-writes hold in every mode.

    @staticmethod
    def _op_schema_version(conn) -> int:
        row = conn.execute(
            "SELECT value FROM metadata WHERE key = 'schema_version'"
        ).fetchone()
        return int(row[0])

    @staticmethod
    def _op_register_client(conn, pubkey: bytes) -> None:
        conn.execute(
            "INSERT OR IGNORE INTO clients (pubkey, registered) VALUES (?, ?)",
            (pubkey, time.time()))

    @staticmethod
    def _op_client_exists(conn, pubkey: bytes) -> bool:
        return conn.execute("SELECT 1 FROM clients WHERE pubkey = ?",
                            (pubkey,)).fetchone() is not None

    @staticmethod
    def _op_client_update_logged_in(conn, pubkey: bytes) -> None:
        conn.execute("UPDATE clients SET last_login = ? WHERE pubkey = ?",
                     (time.time(), pubkey))

    @staticmethod
    def _op_save_storage_negotiated(conn, source: bytes, destination: bytes,
                                    size: int) -> None:
        conn.execute(
            "INSERT INTO peer_backups (source, destination, size_negotiated,"
            " timestamp) VALUES (?, ?, ?, ?)",
            (source, destination, size, time.time()))

    @staticmethod
    def _op_delete_storage_negotiated(conn, source: bytes,
                                      destination: bytes, size: int) -> None:
        conn.execute(
            "DELETE FROM peer_backups WHERE rowid = ("
            " SELECT rowid FROM peer_backups WHERE source = ?"
            " AND destination = ? AND size_negotiated = ?"
            " ORDER BY timestamp DESC LIMIT 1)",
            (source, destination, size))

    @staticmethod
    def _op_save_snapshot(conn, pubkey: bytes, snapshot_hash: bytes) -> None:
        conn.execute(
            "INSERT INTO snapshots (client_pubkey, snapshot_hash, timestamp)"
            " VALUES (?, ?, ?)", (pubkey, snapshot_hash, time.time()))

    @staticmethod
    def _op_get_latest_client_snapshot(conn,
                                       pubkey: bytes) -> Optional[bytes]:
        row = conn.execute(
            "SELECT snapshot_hash FROM snapshots WHERE client_pubkey = ?"
            " ORDER BY timestamp DESC LIMIT 1", (pubkey,)).fetchone()
        return None if row is None else bytes(row[0])

    @staticmethod
    def _op_get_client_negotiated_peers(conn, pubkey: bytes) -> list:
        rows = conn.execute(
            "SELECT DISTINCT destination FROM peer_backups WHERE source = ?",
            (pubkey,)).fetchall()
        return [bytes(r[0]) for r in rows]

    @staticmethod
    def _op_get_clients_storing_on(conn, pubkey: bytes) -> list:
        rows = conn.execute(
            "SELECT DISTINCT source FROM peer_backups WHERE destination = ?",
            (pubkey,)).fetchall()
        return [bytes(r[0]) for r in rows]

    @staticmethod
    def _op_save_audit_report(conn, reporter: bytes, peer: bytes,
                              passed: bool, detail: str) -> None:
        conn.execute(
            "INSERT INTO audit_reports (reporter, peer, passed, detail,"
            " timestamp) VALUES (?, ?, ?, ?, ?)",
            (reporter, peer, int(passed), detail, time.time()))

    @staticmethod
    def _op_save_repair_report(conn, reporter: bytes, peer: bytes,
                               packfiles_lost: int, bytes_lost: int,
                               bytes_replaced: int) -> None:
        conn.execute(
            "INSERT INTO repair_reports (reporter, peer, packfiles_lost,"
            " bytes_lost, bytes_replaced, timestamp) VALUES (?, ?, ?, ?, ?, ?)",
            (reporter, peer, int(packfiles_lost), int(bytes_lost),
             int(bytes_replaced), time.time()))

    @staticmethod
    def _op_reclaim_negotiation(conn, client: bytes, peer: bytes) -> int:
        cur = conn.execute(
            "DELETE FROM peer_backups WHERE (source = ? AND destination = ?)"
            " OR (source = ? AND destination = ?)",
            (client, peer, peer, client))
        return cur.rowcount

    @staticmethod
    def _op_audit_failing_reporters(conn, peer: bytes,
                                    window_s: float) -> int:
        rows = conn.execute(
            "SELECT reporter, passed FROM audit_reports"
            " WHERE peer = ? AND timestamp >= ? ORDER BY timestamp",
            (peer, time.time() - window_s)).fetchall()
        latest: Dict[bytes, int] = {}
        for reporter, passed in rows:
            latest[bytes(reporter)] = passed
        return sum(1 for passed in latest.values() if not passed)

    # --- the ServerDB-compatible sync surface -------------------------------

    def schema_version(self) -> int:
        return self._run(self._op_schema_version)

    def register_client(self, pubkey: bytes) -> None:
        self._run(self._op_register_client, pubkey)

    def client_exists(self, pubkey: bytes) -> bool:
        return self._run(self._op_client_exists, pubkey)

    def client_update_logged_in(self, pubkey: bytes) -> None:
        self._run(self._op_client_update_logged_in, pubkey)

    def save_storage_negotiated(self, source: bytes, destination: bytes,
                                size: int) -> None:
        self._run(self._op_save_storage_negotiated, source, destination,
                  size)

    def delete_storage_negotiated(self, source: bytes, destination: bytes,
                                  size: int) -> None:
        """Roll back one just-recorded negotiation (failed-push
        compensation in matchmaking fulfill)."""
        self._run(self._op_delete_storage_negotiated, source, destination,
                  size)

    def save_snapshot(self, pubkey: bytes, snapshot_hash: bytes) -> None:
        self._run(self._op_save_snapshot, pubkey, snapshot_hash)

    def get_latest_client_snapshot(self, pubkey: bytes) -> Optional[bytes]:
        return self._run(self._op_get_latest_client_snapshot, pubkey)

    def get_client_negotiated_peers(self, pubkey: bytes) -> list:
        return self._run(self._op_get_client_negotiated_peers, pubkey)

    def get_clients_storing_on(self, pubkey: bytes) -> list:
        """Sources with data on ``pubkey`` (the reverse negotiation
        edge)."""
        return self._run(self._op_get_clients_storing_on, pubkey)

    def save_audit_report(self, reporter: bytes, peer: bytes, passed: bool,
                          detail: str) -> None:
        self._run(self._op_save_audit_report, reporter, peer, passed,
                  detail)

    def save_repair_report(self, reporter: bytes, peer: bytes,
                           packfiles_lost: int, bytes_lost: int,
                           bytes_replaced: int) -> None:
        self._run(self._op_save_repair_report, reporter, peer,
                  packfiles_lost, bytes_lost, bytes_replaced)

    def reclaim_negotiation(self, client: bytes, peer: bytes) -> int:
        """Retire every negotiation edge between ``client`` and a lost
        ``peer`` (both directions): the allowance is unusable, and
        restore peer lists must stop naming the dead peer.  Returns rows
        removed."""
        return self._run(self._op_reclaim_negotiation, client, peer)

    def audit_failing_reporters(self, peer: bytes, window_s: float) -> int:
        """Distinct reporters whose LATEST report on ``peer`` within the
        window is a failure.  A later pass from the same reporter clears
        its vote, so a recovered peer re-enters matchmaking without any
        server-side state surgery."""
        return self._run(self._op_audit_failing_reporters, peer, window_s)


class ServerDB(SqliteServerStore):
    """The pre-PR-10 direct-mode store, kept name-compatible.

    Everything executes inline on the calling thread with an immediate
    commit (now under a lock — the original shared its connection across
    threads unserialized).  ``CoordinationServer(legacy=True)`` and the
    bench's single-lock baseline leg use this; new code wants
    :class:`SqliteServerStore`.
    """

    def __init__(self, path):
        super().__init__(path, write_behind=False)


class _PartitionedAio:
    """``store.aio.<method>`` for :class:`PartitionedServerStore`:
    routed ops delegate to the owning partition's own aio facade;
    fan-out ops gather across every partition and merge."""

    def __init__(self, store: "PartitionedServerStore"):
        self._store = store

    def __getattr__(self, name: str):
        if getattr(type(self._store.parts[0]), "_op_" + name, None) is None:
            raise AttributeError(name)
        store = self._store

        async def call(*args):
            return await store._dispatch_async(name, args)

        call.__name__ = name
        return call


class PartitionedServerStore(ServerStore):
    """N per-partition sqlite stores behind the one ServerStore ABC.

    The federation deployment unit (docs/server.md §Federation): every
    coordination node opens the SAME partition directory and routes each
    call by its leading pubkey (``ring.partition_of`` — the convention
    the ABC docstring promises), so store correctness never depends on
    WHICH node served a request.  A wrong-node arrival is merely slower
    (cross-partition WAL contention), never wrong — and node kill/revive
    cannot lose state because the partition files outlive any one
    server instance.

    Cross-partition reads fan out and merge:

    * ``get_clients_storing_on`` — reverse edges live under each
      source's partition: union (first-seen order) across partitions.
    * ``audit_failing_reporters`` — all of one reporter's reports land
      in the reporter's partition, so each partition's latest-per-
      reporter verdict is already globally latest: sum the counts.
    * ``reclaim_negotiation`` — the two edge directions live under the
      two endpoints' partitions: run on both (once if they collide) and
      sum removed rows.

    Everything else routes to exactly one partition, preserving the
    single-writer group-commit durability barrier per partition.
    """

    _FAN_OUT = frozenset({"get_clients_storing_on",
                          "audit_failing_reporters"})

    def __init__(self, root, partitions: Optional[int] = None,
                 write_behind: bool = True):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        n = max(1, int(partitions or defaults.SERVER_STORE_PARTITIONS))
        self.write_behind = bool(write_behind)
        self.parts: List[SqliteServerStore] = [
            SqliteServerStore(str(self.root / f"part_{i:02d}.db"),
                              write_behind=write_behind)
            for i in range(n)]

    def partition_for(self, pubkey: bytes) -> SqliteServerStore:
        return self.parts[ring_partition_of(pubkey, len(self.parts))]

    @property
    def commit_threads(self) -> set:
        out: set = set()
        for p in self.parts:
            out |= p.commit_threads
        return out

    # --- dispatch ----------------------------------------------------------

    def _reclaim_targets(self, client: bytes,
                         peer: bytes) -> List[SqliteServerStore]:
        a, b = self.partition_for(client), self.partition_for(peer)
        return [a] if a is b else [a, b]

    @staticmethod
    def _merge_distinct(results: List[list]) -> list:
        seen, out = set(), []
        for part in results:
            for pk in part:
                if pk not in seen:
                    seen.add(pk)
                    out.append(pk)
        return out

    def _dispatch_sync(self, name: str, args):
        if name == "schema_version":
            return self.parts[0].schema_version()
        if name in self._FAN_OUT:
            results = [getattr(p, name)(*args) for p in self.parts]
            if name == "audit_failing_reporters":
                return sum(results)
            return self._merge_distinct(results)
        if name == "reclaim_negotiation":
            return sum(p.reclaim_negotiation(*args)
                       for p in self._reclaim_targets(*args))
        return getattr(self.partition_for(args[0]), name)(*args)

    async def _dispatch_async(self, name: str, args):
        if name == "schema_version":
            return await self.parts[0].aio.schema_version()
        if name in self._FAN_OUT:
            results = await asyncio.gather(
                *(getattr(p.aio, name)(*args) for p in self.parts))
            if name == "audit_failing_reporters":
                return sum(results)
            return self._merge_distinct(list(results))
        if name == "reclaim_negotiation":
            counts = await asyncio.gather(
                *(p.aio.reclaim_negotiation(*args)
                  for p in self._reclaim_targets(*args)))
            return sum(counts)
        part = self.partition_for(args[0])
        return await getattr(part.aio, name)(*args)

    @property
    def aio(self) -> _PartitionedAio:
        return _PartitionedAio(self)

    # --- lifecycle ---------------------------------------------------------

    def flush(self) -> None:
        for p in self.parts:
            p.flush()

    def close(self) -> None:
        for p in self.parts:
            p.close()

    # --- the ServerStore surface, routed ------------------------------------

    def schema_version(self) -> int:
        return self._dispatch_sync("schema_version", ())

    def register_client(self, pubkey: bytes) -> None:
        self._dispatch_sync("register_client", (pubkey,))

    def client_exists(self, pubkey: bytes) -> bool:
        return self._dispatch_sync("client_exists", (pubkey,))

    def client_update_logged_in(self, pubkey: bytes) -> None:
        self._dispatch_sync("client_update_logged_in", (pubkey,))

    def save_storage_negotiated(self, source: bytes, destination: bytes,
                                size: int) -> None:
        self._dispatch_sync("save_storage_negotiated",
                            (source, destination, size))

    def delete_storage_negotiated(self, source: bytes, destination: bytes,
                                  size: int) -> None:
        self._dispatch_sync("delete_storage_negotiated",
                            (source, destination, size))

    def save_snapshot(self, pubkey: bytes, snapshot_hash: bytes) -> None:
        self._dispatch_sync("save_snapshot", (pubkey, snapshot_hash))

    def get_latest_client_snapshot(self, pubkey: bytes) -> Optional[bytes]:
        return self._dispatch_sync("get_latest_client_snapshot", (pubkey,))

    def get_client_negotiated_peers(self, pubkey: bytes) -> list:
        return self._dispatch_sync("get_client_negotiated_peers", (pubkey,))

    def get_clients_storing_on(self, pubkey: bytes) -> list:
        return self._dispatch_sync("get_clients_storing_on", (pubkey,))

    def save_audit_report(self, reporter: bytes, peer: bytes, passed: bool,
                          detail: str) -> None:
        self._dispatch_sync("save_audit_report",
                            (reporter, peer, passed, detail))

    def save_repair_report(self, reporter: bytes, peer: bytes,
                           packfiles_lost: int, bytes_lost: int,
                           bytes_replaced: int) -> None:
        self._dispatch_sync("save_repair_report",
                            (reporter, peer, packfiles_lost, bytes_lost,
                             bytes_replaced))

    def reclaim_negotiation(self, client: bytes, peer: bytes) -> int:
        return self._dispatch_sync("reclaim_negotiation", (client, peer))

    def audit_failing_reporters(self, peer: bytes, window_s: float) -> int:
        return self._dispatch_sync("audit_failing_reporters",
                                   (peer, window_s))
