"""Swappable server storage backend (the PR-10 scale-out seam).

The coordination plane's persistent state is small and *naturally
shard-keyed*: every row the server writes — client identity, negotiation
edges, snapshots, audit verdicts, repair reports — is keyed by a client
pubkey (or a pubkey pair).  :class:`ServerStore` pins that contract down
as an abstract interface so the request tier in ``net/server.py`` stays
stateless: a Postgres/Vitess-style horizontally sharded twin can slot in
behind the same method set, routing each call by its leading pubkey
argument, without the handlers changing.

:class:`SqliteServerStore` is the embedded implementation, in two modes:

* **write-behind (default)** — a single writer thread owns the sqlite
  connection; every operation (reads included, which buys read-your-
  writes ordering for free) is submitted to an op queue and executed on
  that thread.  The writer drains whatever has queued since the last
  batch and commits ONCE per drain — group commit: under load, hundreds
  of single-row writes amortize one ``COMMIT`` (and one fsync when
  fsync discipline is on).  Callers get a future that resolves only
  *after* the commit, so an ``await store.aio.save_snapshot(...)`` in a
  handler is a durability barrier: the response cannot be written until
  the row is committed, yet the event loop never blocks — the commit
  happens on the writer thread (asserted by the swarm test's event-loop
  stall detector and by :attr:`commit_threads`).
* **direct** (``write_behind=False``, the :class:`ServerDB` shim) — the
  pre-PR-10 shape: every call executes inline on the calling thread and
  commits immediately.  Kept as the measured baseline for bench config
  ``12_swarm`` and for tests that predate the writer thread.  Unlike
  the original, calls are serialized under an RLock: the original
  shared one ``check_same_thread=False`` connection across threads with
  no serialization at all (the latent bug this PR's regression test
  hammers).

Fsync discipline follows ``utils/durable.py`` semantics: when
``durable.FSYNC_ENABLED`` (the ``BKW_FSYNC`` switch) a file-backed
database runs ``PRAGMA synchronous=FULL`` so a group commit is a real
durability barrier; with fsync disabled it drops to ``NORMAL`` (the
pure-tmpfs test posture).  Both store modes apply the same pragma so the
bench's baseline-vs-sharded comparison is durability-for-durability.
"""

from __future__ import annotations

import abc
import asyncio
import json
import queue
import sqlite3
import threading
import time
from concurrent.futures import Future
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from .. import defaults
from ..obs import metrics as obs_metrics
from ..utils import durable, faults
from .ring import partition_of as ring_partition_of

_COMMITS = obs_metrics.counter(
    "bkw_server_store_commits_total",
    "Server-store sqlite commits by mode (group = write-behind batch)",
    ("mode",))
_BATCH_OPS = obs_metrics.histogram(
    "bkw_server_store_batch_ops",
    "Operations drained per write-behind group commit",
    buckets=obs_metrics.log_buckets(1.0, 2.0, 11))
_OP_QUEUE_DEPTH = obs_metrics.gauge(
    "bkw_server_store_queue_depth",
    "Write-behind operations waiting for the writer thread")

# --- replication families (docs/server.md §Replication) ----------------------
_REPL_SHIPS = obs_metrics.counter(
    "bkw_repl_ship_total",
    "Log-ship attempts to ring successors by outcome (acked / gap_refill /"
    " fenced / failed / degraded)", ("outcome",))
_REPL_SHIP_SECONDS = obs_metrics.histogram(
    "bkw_repl_ship_seconds",
    "Wall seconds per successor ship RPC (writer thread, inside the group"
    " commit)", buckets=obs_metrics.log_buckets(1e-4, 2.0, 16))
_REPL_LOG_RECORDS = obs_metrics.counter(
    "bkw_repl_log_records_total",
    "Operation-log records appended, by the appender's role", ("role",))
_REPL_ACK_LAG = obs_metrics.gauge(
    "bkw_repl_ack_lag_records",
    "Primary-side replication lag: log records not yet acked by the most"
    " current live successor")
_REPL_PROMOTES = obs_metrics.counter(
    "bkw_repl_promotes_total",
    "Successor promotions (epoch bump + log-tail replay)")
_REPL_PROMOTE_SECONDS = obs_metrics.histogram(
    "bkw_repl_promote_seconds",
    "Wall seconds per promotion (epoch commit + replay)",
    buckets=obs_metrics.log_buckets(1e-3, 2.0, 14))
_REPL_FENCED = obs_metrics.counter(
    "bkw_repl_fenced_total",
    "Stale-epoch ships refused (zombie primary fenced)")
_REPL_EPOCH = obs_metrics.gauge(
    "bkw_repl_epoch",
    "Current fencing epoch per store partition", ("partition",))
_REPL_FORWARDS = obs_metrics.counter(
    "bkw_repl_forwards_total",
    "Cross-node op forwards to a partition's owner by outcome",
    ("outcome",))

# --- replication crash seams: import-time registration so the crash matrix
# discovers them without a hand-kept list (C1 convention; BKW003 resolves
# these module-level constants at their crashpoint() call sites) --------------
_CP_REPL_APPEND_PRE = faults.register_crash_site("repl.log.append.pre")
_CP_REPL_APPEND_POST = faults.register_crash_site("repl.log.append.post")
_CP_REPL_SHIP_ACKED = faults.register_crash_site("repl.ship.acked")
_CP_REPL_PROMOTE_PRE = faults.register_crash_site("repl.promote.pre")
_CP_REPL_PROMOTE_POST = faults.register_crash_site("repl.promote.post")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS clients (
    pubkey BLOB PRIMARY KEY,
    registered REAL NOT NULL,
    last_login REAL
);
CREATE TABLE IF NOT EXISTS peer_backups (
    source BLOB NOT NULL,
    destination BLOB NOT NULL,
    size_negotiated INTEGER NOT NULL,
    timestamp REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS peer_backups_by_source
    ON peer_backups (source, destination);
CREATE INDEX IF NOT EXISTS peer_backups_by_destination
    ON peer_backups (destination, source);
CREATE TABLE IF NOT EXISTS snapshots (
    client_pubkey BLOB NOT NULL,
    snapshot_hash BLOB NOT NULL,
    timestamp REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS snapshots_by_client
    ON snapshots (client_pubkey, timestamp);
CREATE TABLE IF NOT EXISTS audit_reports (
    reporter BLOB NOT NULL,
    peer BLOB NOT NULL,
    passed INTEGER NOT NULL,
    detail TEXT NOT NULL DEFAULT '',
    timestamp REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS audit_reports_by_peer
    ON audit_reports (peer, timestamp);
CREATE TABLE IF NOT EXISTS repair_reports (
    reporter BLOB NOT NULL,
    peer BLOB NOT NULL,
    packfiles_lost INTEGER NOT NULL,
    bytes_lost INTEGER NOT NULL,
    bytes_replaced INTEGER NOT NULL,
    timestamp REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS metadata (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL
);
"""

#: Bump when the schema changes shape; pre-versioning databases (PR 1 and
#: earlier, which had no ``metadata`` table) count as version 1.
SCHEMA_VERSION = 2

#: THE migration seam: ``{from_version: [SQL statements]}`` applied in
#: sequence by the boot-time migrate to reach ``from_version + 1``.
#: Statements must be idempotent (IF NOT EXISTS / OR IGNORE) because a
#: crash between a migration and the version stamp replays it on the next
#: boot.  A Postgres twin of SqliteServerStore would run the same ladder.
_MIGRATIONS = {
    # v1 (PR 1) -> v2: repair_reports + the metadata table itself.  Both
    # already appear in _SCHEMA's CREATE IF NOT EXISTS, so this rung is
    # empty — it exists to document the pattern for the next real change.
    1: [],
}


class ServerStore(abc.ABC):
    """Abstract coordination-plane store, keyed by client pubkey.

    Every method's FIRST pubkey argument is its shard key; a distributed
    implementation routes on it.  ``peer_backups`` rows are dual-homed
    (one copy under each endpoint's shard) in such a deployment — the
    sqlite implementation keeps one table and both query directions.

    Implementations must expose:

    * the synchronous method set below (tests and setup scripts call
      them directly; they may block briefly),
    * :attr:`aio` — the same methods as awaitables that never block the
      event loop AND, for writes, resolve only once the write is
      durable (the request tier's durability barrier),
    * :meth:`flush` / :meth:`close` lifecycle hooks.
    """

    @abc.abstractmethod
    def register_client(self, pubkey: bytes) -> None: ...

    @abc.abstractmethod
    def client_exists(self, pubkey: bytes) -> bool: ...

    @abc.abstractmethod
    def client_update_logged_in(self, pubkey: bytes) -> None: ...

    @abc.abstractmethod
    def save_storage_negotiated(self, source: bytes, destination: bytes,
                                size: int) -> None: ...

    @abc.abstractmethod
    def delete_storage_negotiated(self, source: bytes, destination: bytes,
                                  size: int) -> None: ...

    @abc.abstractmethod
    def save_snapshot(self, pubkey: bytes, snapshot_hash: bytes) -> None: ...

    @abc.abstractmethod
    def get_latest_client_snapshot(self,
                                   pubkey: bytes) -> Optional[bytes]: ...

    @abc.abstractmethod
    def get_client_negotiated_peers(self, pubkey: bytes) -> list: ...

    @abc.abstractmethod
    def get_clients_storing_on(self, pubkey: bytes) -> list: ...

    @abc.abstractmethod
    def save_audit_report(self, reporter: bytes, peer: bytes, passed: bool,
                          detail: str) -> None: ...

    @abc.abstractmethod
    def save_repair_report(self, reporter: bytes, peer: bytes,
                           packfiles_lost: int, bytes_lost: int,
                           bytes_replaced: int) -> None: ...

    @abc.abstractmethod
    def reclaim_negotiation(self, client: bytes, peer: bytes) -> int: ...

    @abc.abstractmethod
    def audit_failing_reporters(self, peer: bytes,
                                window_s: float) -> int: ...

    @abc.abstractmethod
    def schema_version(self) -> int: ...

    def flush(self) -> None:
        """Barrier: every previously submitted write is durable on
        return."""

    def close(self) -> None:
        """Stop background machinery; the store stays usable for
        post-shutdown reads (tests inspect state after server.stop())."""


class _AioFacade:
    """``store.aio.<method>(...)`` — the handler-facing async view.

    Write-behind: wraps the op's :class:`~concurrent.futures.Future` so
    the coroutine resumes only after the writer thread's group commit.
    Direct mode: runs the sync method inline on the event loop —
    deliberately preserving the pre-PR-10 blocking-commit behavior for
    the bench baseline.
    """

    def __init__(self, store: "SqliteServerStore"):
        self._store = store

    def __getattr__(self, name: str):
        op = getattr(type(self._store), "_op_" + name, None)
        if op is None:
            raise AttributeError(name)
        store = self._store

        async def call(*args):
            if not store.write_behind:
                return getattr(store, name)(*args)
            import asyncio
            return await asyncio.wrap_future(store._submit(op, args))

        call.__name__ = name
        return call


class SqliteServerStore(ServerStore):
    """Embedded sqlite ServerStore; see the module docstring for the
    write-behind/direct split."""

    def __init__(self, path, write_behind: bool = True):
        self.path = path
        self.write_behind = bool(write_behind)
        #: thread idents observed executing COMMIT for request-path ops
        #: (NOT the constructor's schema bootstrap) — the swarm test
        #: asserts the event-loop thread never appears here.
        self.commit_threads: set = set()
        self._db = sqlite3.connect(path, check_same_thread=False)
        if path != ":memory:":
            self._db.execute("PRAGMA journal_mode=WAL")
            # Federation opens the same partition files from several
            # store instances (node revive, multi-process bench legs):
            # wait out a sibling's group commit instead of raising
            # "database is locked" into a request handler.
            self._db.execute("PRAGMA busy_timeout=5000")
            # fsync-disciplined group commit (utils/durable.py semantics):
            # FULL makes each COMMIT a durability barrier; with fsync
            # globally off (BKW_FSYNC=0 test runs) NORMAL suffices.
            self._db.execute("PRAGMA synchronous=%s"
                             % ("FULL" if durable.FSYNC_ENABLED
                                else "NORMAL"))
        self._db.executescript(_SCHEMA)
        self._db.commit()
        self._migrate()  # raises synchronously on a newer-schema database
        self._direct_lock = threading.RLock()
        self._submit_lock = threading.Lock()
        self._closed = False
        self._ops: "queue.SimpleQueue" = queue.SimpleQueue()
        self._depth = 0
        self._writer: Optional[threading.Thread] = None
        if self.write_behind:
            self._writer = threading.Thread(
                target=self._writer_loop, name="serverstore-writer",
                daemon=True)
            self._writer.start()

    # --- write-behind machinery --------------------------------------------

    def _submit(self, op, args) -> Future:
        fut: Future = Future()
        with self._submit_lock:
            if self._closed or not self.write_behind:
                # post-close (or direct-mode) fallback: run inline,
                # serialized, committed immediately
                try:
                    with self._direct_lock:
                        result = op(self._db, *args)
                        self._commit("direct")
                    fut.set_result(result)
                except BaseException as e:
                    fut.set_exception(e)
                return fut
            self._ops.put((op, args, fut))
            self._depth += 1
            _OP_QUEUE_DEPTH.set(self._depth)
        return fut

    def _writer_loop(self) -> None:
        while True:
            head = self._ops.get()
            if head is None:
                return
            batch = [head]
            # group commit: drain everything already queued (bounded so a
            # firehose cannot starve the commit), execute, commit ONCE,
            # then resolve every future — durability before acknowledgment
            while len(batch) < defaults.SERVER_STORE_MAX_BATCH:
                try:
                    nxt = self._ops.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._ops.put(None)  # re-arm shutdown for next round
                    break
                batch.append(nxt)
            with self._submit_lock:
                self._depth -= len(batch)
                _OP_QUEUE_DEPTH.set(max(self._depth, 0))
            try:
                results = self._execute_batch(batch)
            except faults.CrashInjected as e:
                # an armed replication-seam crash fired mid-batch: the
                # process is "dead" — fail the batch so waiters observe
                # it, and stop the writer (recovery happens at reopen)
                for _op, _args, fut in batch:
                    fut.set_exception(e)
                return
            except BaseException as e:
                # batch-level failure (e.g. a fenced zombie primary):
                # nothing was applied; fail every waiter, stay alive
                for _op, _args, fut in batch:
                    fut.set_exception(e)
                continue
            _BATCH_OPS.observe(float(len(batch)))
            for (ok, value), (_op, _args, fut) in zip(results, batch):
                if ok:
                    fut.set_result(value)
                else:
                    fut.set_exception(value)

    def _execute_batch(self, batch) -> list:
        """Execute one drained batch against the writer's connection and
        commit ONCE; returns ``[(ok, value-or-exc), ...]`` aligned with
        ``batch``.  The replication subclass overrides this — the log
        append + successor ship happen here, inside the durability
        barrier, before any caller's future resolves."""
        results = []
        for op, args, _fut in batch:
            try:
                results.append((True, op(self._db, *args)))
            except BaseException as e:  # per-op isolation
                results.append((False, e))
        self._commit("group")
        return results

    def _commit(self, mode: str) -> None:
        if self._db.in_transaction:
            self._db.commit()
            _COMMITS.inc(mode=mode)
            self.commit_threads.add(threading.get_ident())

    def flush(self) -> None:
        if self.write_behind and not self._closed:
            self._submit(lambda _conn: None, ()).result()

    def close(self) -> None:
        """Drain the op queue, stop the writer thread, and flip to the
        inline fallback (the connection stays open so post-shutdown test
        reads keep working)."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        if self._writer is not None:
            self._ops.put(None)
            self._writer.join(timeout=10)
            self._writer = None

    # --- sync + async facades ----------------------------------------------

    @property
    def aio(self) -> _AioFacade:
        return _AioFacade(self)

    def _run(self, op, *args):
        if self.write_behind:
            return self._submit(op, args).result()
        with self._direct_lock:
            result = op(self._db, *args)
            self._commit("direct")
            return result

    # --- schema ------------------------------------------------------------

    def _migrate(self) -> None:
        """Boot-time schema version check (runs on the constructing
        thread, before the writer starts, so version errors raise
        synchronously).

        * fresh or pre-versioning database -> run the ladder from v1 and
          stamp :data:`SCHEMA_VERSION` (the _SCHEMA script is idempotent,
          so replaying it on a v1 database upgrades it in place);
        * versioned database older than the code -> apply each rung of
          :data:`_MIGRATIONS` in order, stamping after each one;
        * database NEWER than the code -> refuse to start: old code
          writing rows a newer schema reinterprets is silent corruption.
        """
        row = self._db.execute(
            "SELECT value FROM metadata WHERE key = 'schema_version'"
        ).fetchone()
        version = int(row[0]) if row is not None else 1
        if version > SCHEMA_VERSION:
            raise RuntimeError(
                f"database schema v{version} is newer than this server"
                f" (v{SCHEMA_VERSION}); upgrade the server binary")
        while version < SCHEMA_VERSION:
            for stmt in _MIGRATIONS.get(version, ()):
                self._db.execute(stmt)
            version += 1
            self._db.execute(
                "INSERT INTO metadata (key, value) VALUES"
                " ('schema_version', ?) ON CONFLICT(key)"
                " DO UPDATE SET value = excluded.value", (str(version),))
            self._db.commit()
        if row is None:
            self._db.execute(
                "INSERT OR IGNORE INTO metadata (key, value) VALUES"
                " ('schema_version', ?)", (str(SCHEMA_VERSION),))
            self._db.commit()

    # --- operations (each = one statement batch on the writer's conn) ------
    # The _op_* staticmethods are the single source of truth: the sync
    # facade and store.aio both execute exactly these against the one
    # connection, so ordering and read-your-writes hold in every mode.

    @staticmethod
    def _op_schema_version(conn) -> int:
        row = conn.execute(
            "SELECT value FROM metadata WHERE key = 'schema_version'"
        ).fetchone()
        return int(row[0])

    # Write ops take a trailing ``ts`` (defaulted to now) so a replicated
    # log replay reproduces byte-identical rows: the primary stamps the
    # wall clock ONCE into the log record, and every replica applies that
    # stamp, not its own clock.

    @staticmethod
    def _op_register_client(conn, pubkey: bytes,
                            ts: Optional[float] = None) -> None:
        conn.execute(
            "INSERT OR IGNORE INTO clients (pubkey, registered) VALUES (?, ?)",
            (pubkey, time.time() if ts is None else ts))

    @staticmethod
    def _op_client_exists(conn, pubkey: bytes) -> bool:
        return conn.execute("SELECT 1 FROM clients WHERE pubkey = ?",
                            (pubkey,)).fetchone() is not None

    @staticmethod
    def _op_client_update_logged_in(conn, pubkey: bytes,
                                    ts: Optional[float] = None) -> None:
        conn.execute("UPDATE clients SET last_login = ? WHERE pubkey = ?",
                     (time.time() if ts is None else ts, pubkey))

    @staticmethod
    def _op_save_storage_negotiated(conn, source: bytes, destination: bytes,
                                    size: int,
                                    ts: Optional[float] = None) -> None:
        conn.execute(
            "INSERT INTO peer_backups (source, destination, size_negotiated,"
            " timestamp) VALUES (?, ?, ?, ?)",
            (source, destination, size, time.time() if ts is None else ts))

    @staticmethod
    def _op_delete_storage_negotiated(conn, source: bytes,
                                      destination: bytes, size: int) -> None:
        conn.execute(
            "DELETE FROM peer_backups WHERE rowid = ("
            " SELECT rowid FROM peer_backups WHERE source = ?"
            " AND destination = ? AND size_negotiated = ?"
            " ORDER BY timestamp DESC LIMIT 1)",
            (source, destination, size))

    @staticmethod
    def _op_save_snapshot(conn, pubkey: bytes, snapshot_hash: bytes,
                          ts: Optional[float] = None) -> None:
        conn.execute(
            "INSERT INTO snapshots (client_pubkey, snapshot_hash, timestamp)"
            " VALUES (?, ?, ?)",
            (pubkey, snapshot_hash, time.time() if ts is None else ts))

    @staticmethod
    def _op_get_latest_client_snapshot(conn,
                                       pubkey: bytes) -> Optional[bytes]:
        row = conn.execute(
            "SELECT snapshot_hash FROM snapshots WHERE client_pubkey = ?"
            " ORDER BY timestamp DESC LIMIT 1", (pubkey,)).fetchone()
        return None if row is None else bytes(row[0])

    @staticmethod
    def _op_get_client_negotiated_peers(conn, pubkey: bytes) -> list:
        rows = conn.execute(
            "SELECT DISTINCT destination FROM peer_backups WHERE source = ?",
            (pubkey,)).fetchall()
        return [bytes(r[0]) for r in rows]

    @staticmethod
    def _op_get_clients_storing_on(conn, pubkey: bytes) -> list:
        rows = conn.execute(
            "SELECT DISTINCT source FROM peer_backups WHERE destination = ?",
            (pubkey,)).fetchall()
        return [bytes(r[0]) for r in rows]

    @staticmethod
    def _op_save_audit_report(conn, reporter: bytes, peer: bytes,
                              passed: bool, detail: str,
                              ts: Optional[float] = None) -> None:
        conn.execute(
            "INSERT INTO audit_reports (reporter, peer, passed, detail,"
            " timestamp) VALUES (?, ?, ?, ?, ?)",
            (reporter, peer, int(passed), detail,
             time.time() if ts is None else ts))

    @staticmethod
    def _op_save_repair_report(conn, reporter: bytes, peer: bytes,
                               packfiles_lost: int, bytes_lost: int,
                               bytes_replaced: int,
                               ts: Optional[float] = None) -> None:
        conn.execute(
            "INSERT INTO repair_reports (reporter, peer, packfiles_lost,"
            " bytes_lost, bytes_replaced, timestamp) VALUES (?, ?, ?, ?, ?, ?)",
            (reporter, peer, int(packfiles_lost), int(bytes_lost),
             int(bytes_replaced), time.time() if ts is None else ts))

    @staticmethod
    def _op_reclaim_negotiation(conn, client: bytes, peer: bytes) -> int:
        cur = conn.execute(
            "DELETE FROM peer_backups WHERE (source = ? AND destination = ?)"
            " OR (source = ? AND destination = ?)",
            (client, peer, peer, client))
        return cur.rowcount

    @staticmethod
    def _op_audit_failing_reporters(conn, peer: bytes,
                                    window_s: float) -> int:
        rows = conn.execute(
            "SELECT reporter, passed FROM audit_reports"
            " WHERE peer = ? AND timestamp >= ? ORDER BY timestamp",
            (peer, time.time() - window_s)).fetchall()
        latest: Dict[bytes, int] = {}
        for reporter, passed in rows:
            latest[bytes(reporter)] = passed
        return sum(1 for passed in latest.values() if not passed)

    # --- the ServerDB-compatible sync surface -------------------------------

    def schema_version(self) -> int:
        return self._run(self._op_schema_version)

    def register_client(self, pubkey: bytes) -> None:
        self._run(self._op_register_client, pubkey)

    def client_exists(self, pubkey: bytes) -> bool:
        return self._run(self._op_client_exists, pubkey)

    def client_update_logged_in(self, pubkey: bytes) -> None:
        self._run(self._op_client_update_logged_in, pubkey)

    def save_storage_negotiated(self, source: bytes, destination: bytes,
                                size: int) -> None:
        self._run(self._op_save_storage_negotiated, source, destination,
                  size)

    def delete_storage_negotiated(self, source: bytes, destination: bytes,
                                  size: int) -> None:
        """Roll back one just-recorded negotiation (failed-push
        compensation in matchmaking fulfill)."""
        self._run(self._op_delete_storage_negotiated, source, destination,
                  size)

    def save_snapshot(self, pubkey: bytes, snapshot_hash: bytes) -> None:
        self._run(self._op_save_snapshot, pubkey, snapshot_hash)

    def get_latest_client_snapshot(self, pubkey: bytes) -> Optional[bytes]:
        return self._run(self._op_get_latest_client_snapshot, pubkey)

    def get_client_negotiated_peers(self, pubkey: bytes) -> list:
        return self._run(self._op_get_client_negotiated_peers, pubkey)

    def get_clients_storing_on(self, pubkey: bytes) -> list:
        """Sources with data on ``pubkey`` (the reverse negotiation
        edge)."""
        return self._run(self._op_get_clients_storing_on, pubkey)

    def save_audit_report(self, reporter: bytes, peer: bytes, passed: bool,
                          detail: str) -> None:
        self._run(self._op_save_audit_report, reporter, peer, passed,
                  detail)

    def save_repair_report(self, reporter: bytes, peer: bytes,
                           packfiles_lost: int, bytes_lost: int,
                           bytes_replaced: int) -> None:
        self._run(self._op_save_repair_report, reporter, peer,
                  packfiles_lost, bytes_lost, bytes_replaced)

    def reclaim_negotiation(self, client: bytes, peer: bytes) -> int:
        """Retire every negotiation edge between ``client`` and a lost
        ``peer`` (both directions): the allowance is unusable, and
        restore peer lists must stop naming the dead peer.  Returns rows
        removed."""
        return self._run(self._op_reclaim_negotiation, client, peer)

    def audit_failing_reporters(self, peer: bytes, window_s: float) -> int:
        """Distinct reporters whose LATEST report on ``peer`` within the
        window is a failure.  A later pass from the same reporter clears
        its vote, so a recovered peer re-enters matchmaking without any
        server-side state surgery."""
        return self._run(self._op_audit_failing_reporters, peer, window_s)


class ServerDB(SqliteServerStore):
    """The pre-PR-10 direct-mode store, kept name-compatible.

    Everything executes inline on the calling thread with an immediate
    commit (now under a lock — the original shared its connection across
    threads unserialized).  ``CoordinationServer(legacy=True)`` and the
    bench's single-lock baseline leg use this; new code wants
    :class:`SqliteServerStore`.
    """

    def __init__(self, path):
        super().__init__(path, write_behind=False)


class _PartitionedAio:
    """``store.aio.<method>`` for :class:`PartitionedServerStore`:
    routed ops delegate to the owning partition's own aio facade;
    fan-out ops gather across every partition and merge."""

    def __init__(self, store: "PartitionedServerStore"):
        self._store = store

    def __getattr__(self, name: str):
        if getattr(type(self._store.parts[0]), "_op_" + name, None) is None:
            raise AttributeError(name)
        store = self._store

        async def call(*args):
            return await store._dispatch_async(name, args)

        call.__name__ = name
        return call


class PartitionedServerStore(ServerStore):
    """N per-partition sqlite stores behind the one ServerStore ABC.

    The federation deployment unit (docs/server.md §Federation): every
    coordination node opens the SAME partition directory and routes each
    call by its leading pubkey (``ring.partition_of`` — the convention
    the ABC docstring promises), so store correctness never depends on
    WHICH node served a request.  A wrong-node arrival is merely slower
    (cross-partition WAL contention), never wrong — and node kill/revive
    cannot lose state because the partition files outlive any one
    server instance.

    Cross-partition reads fan out and merge:

    * ``get_clients_storing_on`` — reverse edges live under each
      source's partition: union (first-seen order) across partitions.
    * ``audit_failing_reporters`` — all of one reporter's reports land
      in the reporter's partition, so each partition's latest-per-
      reporter verdict is already globally latest: sum the counts.
    * ``reclaim_negotiation`` — the two edge directions live under the
      two endpoints' partitions: run on both (once if they collide) and
      sum removed rows.

    Everything else routes to exactly one partition, preserving the
    single-writer group-commit durability barrier per partition.
    """

    _FAN_OUT = frozenset({"get_clients_storing_on",
                          "audit_failing_reporters"})

    def __init__(self, root, partitions: Optional[int] = None,
                 write_behind: bool = True):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        n = max(1, int(partitions or defaults.SERVER_STORE_PARTITIONS))
        self.write_behind = bool(write_behind)
        self.parts: List[SqliteServerStore] = [
            SqliteServerStore(str(self.root / f"part_{i:02d}.db"),
                              write_behind=write_behind)
            for i in range(n)]

    def partition_for(self, pubkey: bytes) -> SqliteServerStore:
        return self.parts[ring_partition_of(pubkey, len(self.parts))]

    @property
    def commit_threads(self) -> set:
        out: set = set()
        for p in self.parts:
            out |= p.commit_threads
        return out

    # --- dispatch ----------------------------------------------------------

    def _reclaim_targets(self, client: bytes,
                         peer: bytes) -> List[SqliteServerStore]:
        a, b = self.partition_for(client), self.partition_for(peer)
        return [a] if a is b else [a, b]

    @staticmethod
    def _merge_distinct(results: List[list]) -> list:
        seen, out = set(), []
        for part in results:
            for pk in part:
                if pk not in seen:
                    seen.add(pk)
                    out.append(pk)
        return out

    def _dispatch_sync(self, name: str, args):
        if name == "schema_version":
            return self.parts[0].schema_version()
        if name in self._FAN_OUT:
            results = [getattr(p, name)(*args) for p in self.parts]
            if name == "audit_failing_reporters":
                return sum(results)
            return self._merge_distinct(results)
        if name == "reclaim_negotiation":
            return sum(p.reclaim_negotiation(*args)
                       for p in self._reclaim_targets(*args))
        return getattr(self.partition_for(args[0]), name)(*args)

    async def _dispatch_async(self, name: str, args):
        if name == "schema_version":
            return await self.parts[0].aio.schema_version()
        if name in self._FAN_OUT:
            results = await asyncio.gather(
                *(getattr(p.aio, name)(*args) for p in self.parts))
            if name == "audit_failing_reporters":
                return sum(results)
            return self._merge_distinct(list(results))
        if name == "reclaim_negotiation":
            counts = await asyncio.gather(
                *(p.aio.reclaim_negotiation(*args)
                  for p in self._reclaim_targets(*args)))
            return sum(counts)
        part = self.partition_for(args[0])
        return await getattr(part.aio, name)(*args)

    @property
    def aio(self) -> _PartitionedAio:
        return _PartitionedAio(self)

    # --- lifecycle ---------------------------------------------------------

    def flush(self) -> None:
        for p in self.parts:
            p.flush()

    def close(self) -> None:
        for p in self.parts:
            p.close()

    # --- the ServerStore surface, routed ------------------------------------

    def schema_version(self) -> int:
        return self._dispatch_sync("schema_version", ())

    def register_client(self, pubkey: bytes) -> None:
        self._dispatch_sync("register_client", (pubkey,))

    def client_exists(self, pubkey: bytes) -> bool:
        return self._dispatch_sync("client_exists", (pubkey,))

    def client_update_logged_in(self, pubkey: bytes) -> None:
        self._dispatch_sync("client_update_logged_in", (pubkey,))

    def save_storage_negotiated(self, source: bytes, destination: bytes,
                                size: int) -> None:
        self._dispatch_sync("save_storage_negotiated",
                            (source, destination, size))

    def delete_storage_negotiated(self, source: bytes, destination: bytes,
                                  size: int) -> None:
        self._dispatch_sync("delete_storage_negotiated",
                            (source, destination, size))

    def save_snapshot(self, pubkey: bytes, snapshot_hash: bytes) -> None:
        self._dispatch_sync("save_snapshot", (pubkey, snapshot_hash))

    def get_latest_client_snapshot(self, pubkey: bytes) -> Optional[bytes]:
        return self._dispatch_sync("get_latest_client_snapshot", (pubkey,))

    def get_client_negotiated_peers(self, pubkey: bytes) -> list:
        return self._dispatch_sync("get_client_negotiated_peers", (pubkey,))

    def get_clients_storing_on(self, pubkey: bytes) -> list:
        return self._dispatch_sync("get_clients_storing_on", (pubkey,))

    def save_audit_report(self, reporter: bytes, peer: bytes, passed: bool,
                          detail: str) -> None:
        self._dispatch_sync("save_audit_report",
                            (reporter, peer, passed, detail))

    def save_repair_report(self, reporter: bytes, peer: bytes,
                           packfiles_lost: int, bytes_lost: int,
                           bytes_replaced: int) -> None:
        self._dispatch_sync("save_repair_report",
                            (reporter, peer, packfiles_lost, bytes_lost,
                             bytes_replaced))

    def reclaim_negotiation(self, client: bytes, peer: bytes) -> int:
        return self._dispatch_sync("reclaim_negotiation", (client, peer))

    def audit_failing_reporters(self, peer: bytes, window_s: float) -> int:
        return self._dispatch_sync("audit_failing_reporters",
                                   (peer, window_s))


# --- replicated coordination metadata (docs/server.md §Replication) ----------

#: Mutating operations, and whether each takes the trailing replay
#: timestamp.  Only these ship: reads never enter the log, so a replica
#: replay touches exactly the rows the primary's commit touched.
_REPL_WRITE_OPS: Dict[str, bool] = {
    "register_client": True,
    "client_update_logged_in": True,
    "save_storage_negotiated": True,
    "delete_storage_negotiated": False,
    "save_snapshot": True,
    "save_audit_report": True,
    "save_repair_report": True,
    "reclaim_negotiation": False,
}


def encode_value(v: Any) -> Any:
    """JSON-safe encoding for log records and forwarded op args/results:
    bytes ride as ``{"__b": hex}``, containers recurse, scalars pass."""
    if isinstance(v, (bytes, bytearray, memoryview)):
        return {"__b": bytes(v).hex()}
    if isinstance(v, (list, tuple)):
        return [encode_value(x) for x in v]
    return v


def decode_value(v: Any) -> Any:
    if isinstance(v, dict) and "__b" in v:
        return bytes.fromhex(v["__b"])
    if isinstance(v, list):
        return [decode_value(x) for x in v]
    return v


class ReplicationFenced(RuntimeError):
    """A write was refused because this node's epoch is stale — a
    successor was promoted past it.  The holder must rejoin as a
    successor; the new owner (when known) rides along for rerouting."""

    def __init__(self, epoch: int, owner: Optional[str] = None,
                 partition: Optional[int] = None):
        super().__init__(
            f"partition fenced at epoch {epoch}"
            + (f" (owner {owner})" if owner else ""))
        self.epoch = int(epoch)
        self.owner = owner
        self.partition = partition


class OpLog:
    """Per-partition replicated operation log: append-only JSONL plus a
    durable epoch sidecar.

    * Records are ``{"lsn", "epoch", "op", "args", "ts"}``, one per
      line, bytes args hex-tagged (:func:`encode_value`).  Appends are
      flushed and fsynced under the ``BKW_FSYNC`` discipline before the
      caller proceeds — the record IS the durability unit the write's
      future waits on.
    * A torn tail (crash mid-append) is tolerated on load: parsing stops
      at the first undecodable line, so only fully-durable records are
      ever replayed — the classic redo-log contract.
    * The fencing epoch lives in a ``<log>.meta.json`` sidecar committed
      via ``durable.write_replace``; it changes only at promotion (bump)
      and higher-epoch ship adoption, both crashpoint-adjacent call
      sites.
    """

    def __init__(self, path):
        self.path = Path(path)
        self.meta_path = self.path.with_name(self.path.name + ".meta.json")
        self.epoch = 0
        #: set durably when a divergent tail is truncated: records the
        #: store's sqlite may reflect log records that no longer exist,
        #: so the owner must rebuild from the log before trusting it
        self.dirty = False
        self.records: List[dict] = []
        self._load()

    def _load(self) -> None:
        if self.meta_path.exists():
            try:
                meta = json.loads(self.meta_path.read_text())
                self.epoch = int(meta.get("epoch", 0))
                self.dirty = bool(meta.get("dirty", False))
            except (ValueError, OSError):
                self.epoch = 0
                self.dirty = False
        if not self.path.exists():
            return
        with open(self.path, "rb") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                    int(rec["lsn"])
                except (ValueError, KeyError, TypeError):
                    break  # torn tail: a crash cut the last append short
                self.records.append(rec)

    @property
    def last_lsn(self) -> int:
        return int(self.records[-1]["lsn"]) if self.records else 0

    def tail(self, after_lsn: int) -> List[dict]:
        return [r for r in self.records if int(r["lsn"]) > after_lsn]

    @staticmethod
    def _lines(records: List[dict]) -> bytes:
        return b"".join(
            json.dumps(r, separators=(",", ":")).encode() + b"\n"
            for r in records)

    def append(self, records: List[dict]) -> None:
        with open(self.path, "ab") as fh:
            fh.write(self._lines(records))
        durable.fsync_file(self.path)
        self.records.extend(records)

    @staticmethod
    def _meta_bytes(epoch: int, dirty: bool) -> bytes:
        return json.dumps({"epoch": int(epoch),
                           "dirty": bool(dirty)}).encode()

    def set_epoch(self, epoch: int) -> None:
        # durable before in-memory: a crash between the two re-reads the
        # committed state at reopen (callers bracket with crashpoints)
        durable.write_replace(self.meta_path,
                              self._meta_bytes(epoch, self.dirty))
        self.epoch = int(epoch)

    def set_dirty(self, dirty: bool) -> None:
        durable.write_replace(self.meta_path,
                              self._meta_bytes(self.epoch, dirty))
        self.dirty = bool(dirty)

    def truncate_after(self, lsn: int) -> None:
        """Atomically drop every record with lsn > ``lsn`` — the
        divergent tail a fenced zombie logged but never got acked."""
        keep = [r for r in self.records if int(r["lsn"]) <= lsn]
        durable.write_replace(self.path, self._lines(keep))
        self.records = keep


class _ReplPartitionStore(SqliteServerStore):
    """One partition of a :class:`ReplicatedServerStore`: the sqlite
    store plus its operation log, successor chain, and fencing state.

    The write-behind group commit is extended, not replaced: the
    overridden :meth:`_execute_batch` stamps each mutating op into a log
    record, makes the records durable, ships them synchronously to the
    ring successors, and only then applies them to sqlite (advancing the
    ``repl_applied_lsn`` metadata row in the SAME transaction — the
    exactly-once cursor a replay consults) and resolves the batch's
    futures.  Crash anywhere in that sequence and either the records
    never became durable (no caller was acked) or a reopen/promote
    replays them idempotently.

    A node holds one of these per partition whether it owns it or not:
    a successor's copy accepts ships into its log (:meth:`accept_ship`)
    and applies NOTHING until :meth:`promote` — so its sqlite can never
    diverge from acked history, and a fenced zombie's unacked tail is a
    pure log artifact the truncation repairs.
    """

    def __init__(self, path, log_path, partition: int, node_id: str):
        self.partition = int(partition)
        self.node_id = str(node_id)
        self.log = OpLog(log_path)
        self.owner: Optional[str] = None
        self.successors: List[str] = []
        #: sync ship hook ``(node_id, payload) -> response dict``; runs
        #: on the writer thread (never the event loop), wired by the
        #: server layer / tests.  ``None`` = standalone, nothing ships.
        self.ship: Optional[Callable[[str, dict], dict]] = None
        self.fenced = False
        self._repl_lock = threading.RLock()
        self._acked: Dict[str, int] = {}
        self._ship_down: Dict[str, float] = {}
        super().__init__(path, write_behind=True)
        # reopen-time divergence repair: the durable dirty flag marks
        # an interrupted rebuild; a cursor past the log's end is the
        # flag's own crash window (truncation durable, flag not yet)
        if self.log.dirty or self.applied_lsn() > self.log.last_lsn:
            self._rebuild()
        _REPL_EPOCH.set(float(self.log.epoch), partition=str(partition))

    # --- primary side -------------------------------------------------------

    def _execute_batch(self, batch) -> list:
        with self._repl_lock:
            exec_args: Dict[int, tuple] = {}
            staged: List[dict] = []
            lsn = self.log.last_lsn
            pre_lsn = lsn
            for i, (op, args, _fut) in enumerate(batch):
                name = op.__name__
                name = name[4:] if name.startswith("_op_") else name
                takes_ts = _REPL_WRITE_OPS.get(name)
                if takes_ts is None:
                    continue  # read (or the flush no-op): never ships
                if self.fenced:
                    raise ReplicationFenced(self.log.epoch, self.owner,
                                            self.partition)
                lsn += 1
                rec = {"lsn": lsn, "epoch": self.log.epoch, "op": name,
                       "args": encode_value(list(args)),
                       "ts": round(time.time(), 6)}
                staged.append(rec)
                if takes_ts:
                    exec_args[i] = tuple(args) + (rec["ts"],)
            if staged:
                faults.crashpoint(_CP_REPL_APPEND_PRE)
                self.log.append(staged)
                faults.crashpoint(_CP_REPL_APPEND_POST)
                _REPL_LOG_RECORDS.inc(float(len(staged)), role="primary")
                self._ship_tail(staged)  # raises ReplicationFenced on a
                #                          stale epoch — nothing applied
                faults.crashpoint(_CP_REPL_SHIP_ACKED)
                # roll forward any older durable-but-unapplied tail (the
                # crash-between-ship-and-commit seam) in this same txn
                applied = self._op_applied_lsn(self._db)
                for rec in self.log.tail(applied):
                    if int(rec["lsn"]) > pre_lsn:
                        break
                    self._apply_record(self._db, rec)
            results = []
            for i, (op, args, _fut) in enumerate(batch):
                try:
                    results.append(
                        (True, op(self._db, *exec_args.get(i, args))))
                except BaseException as e:  # per-op isolation
                    results.append((False, e))
            if staged:
                self._set_applied(self._db, staged[-1]["lsn"])
            self._commit("group")
            return results

    def _ship_tail(self, records: List[dict]) -> None:
        """Synchronously ship freshly logged records to every live
        successor.  Requires no ack only when the chain is empty or
        entirely dark (degraded — counted, and the gap refills when a
        successor answers again); a fenced response raises."""
        chain = [n for n in self.successors if n != self.node_id]
        if not chain or self.ship is None:
            return
        payload = {"partition": self.partition, "epoch": self.log.epoch,
                   "from_lsn": records[0]["lsn"], "records": records}
        # Zero acks means the resolving write futures would be backed by
        # NOTHING but this node's disk — the one state the protocol
        # promises not to ack from.  So the first round honours the
        # ship-down backoff (don't stall the writer on known-dark
        # peers), but an ack-less batch retries the ENTIRE chain,
        # backoff ignored: a slow successor still beats no successor.
        acked: set = set()
        for attempt in range(defaults.REPL_SHIP_RETRIES + 1):
            now = time.time()
            for node in chain:
                if node in acked:
                    continue
                if attempt == 0 and self._ship_down.get(node, 0.0) > now:
                    continue
                if self._ship_one(node, payload):
                    acked.add(node)
            if acked:
                break
            if attempt < defaults.REPL_SHIP_RETRIES:
                time.sleep(defaults.REPL_SHIP_RETRY_BASE_S * (2 ** attempt))
        if not acked:
            _REPL_SHIPS.inc(outcome="degraded")
        lag = self.log.last_lsn - max(self._acked.values(), default=0)
        _REPL_ACK_LAG.set(float(max(lag, 0)))

    def _ship_one(self, node: str, payload: dict) -> bool:
        t0 = time.time()
        try:
            resp = self.ship(node, payload)
        except Exception:
            self._mark_ship_down(node)
            _REPL_SHIP_SECONDS.observe(time.time() - t0)
            return False
        _REPL_SHIP_SECONDS.observe(time.time() - t0)
        if resp.get("fenced"):
            # the successor knows a higher epoch: WE are the zombie.
            # Nothing from this batch applies; the write futures fail
            # and the server layer flips this node to successor role.
            _REPL_SHIPS.inc(outcome="fenced")
            self.fenced = True
            raise ReplicationFenced(int(resp.get("epoch", -1)),
                                    resp.get("owner"), self.partition)
        if resp.get("need_from") is not None:
            # the successor missed ships (it was down while we proceeded
            # degraded): re-ship its whole missing tail once
            _REPL_SHIPS.inc(outcome="gap_refill")
            tail = self.log.tail(int(resp["need_from"]) - 1)
            refill = dict(payload)
            refill["from_lsn"] = tail[0]["lsn"] if tail \
                else payload["from_lsn"]
            refill["records"] = tail
            try:
                resp = self.ship(node, refill)
            except Exception:
                self._mark_ship_down(node)
                return False
        if resp.get("acked"):
            _REPL_SHIPS.inc(outcome="acked")
            self._ship_down.pop(node, None)
            self._acked[node] = int(resp.get("lsn", 0))
            return True
        return False

    def _mark_ship_down(self, node: str) -> None:
        self._ship_down[node] = (time.time()
                                 + defaults.FEDERATION_PEER_BACKOFF_S)
        _REPL_SHIPS.inc(outcome="failed")

    # --- successor side -----------------------------------------------------

    def accept_ship(self, epoch: int, from_lsn: int,
                    records: List[dict]) -> dict:
        """Successor intake for one shipped tail.  Stale epochs are
        fenced; a higher epoch is adopted (truncating any divergent
        local tail the fenced zombie had shipped us); a gap asks the
        primary to re-ship from our next lsn.  Records land in the LOG
        only — application waits for :meth:`promote` — except after a
        truncation, which forces a full rebuild (see :meth:`_rebuild`)
        because sqlite may hold effects of the records just dropped."""
        resp, rebuild = self._accept_ship_locked(epoch, from_lsn,
                                                 records)
        if rebuild:
            # outside _repl_lock: the rebuild runs on the writer
            # thread, whose _execute_batch takes the lock itself
            self._rebuild()
        return resp

    def _accept_ship_locked(self, epoch: int, from_lsn: int,
                            records: List[dict]):
        rebuild = False
        with self._repl_lock:
            if epoch < self.log.epoch:
                _REPL_FENCED.inc()
                return {"fenced": True, "epoch": self.log.epoch,
                        "owner": self.owner}, False
            if epoch > self.log.epoch:
                faults.crashpoint(_CP_REPL_APPEND_PRE)
                if self.log.last_lsn >= from_lsn:
                    self.log.truncate_after(int(from_lsn) - 1)
                    self.log.set_dirty(True)
                    rebuild = True
                self.log.set_epoch(epoch)
                faults.crashpoint(_CP_REPL_APPEND_POST)
                self.fenced = False
                _REPL_EPOCH.set(float(epoch),
                                partition=str(self.partition))
            if from_lsn > self.log.last_lsn + 1:
                return {"need_from": self.log.last_lsn + 1,
                        "epoch": self.log.epoch}, rebuild
            fresh = [r for r in records
                     if int(r["lsn"]) > self.log.last_lsn]
            if fresh:
                faults.crashpoint(_CP_REPL_APPEND_PRE)
                self.log.append(fresh)
                faults.crashpoint(_CP_REPL_APPEND_POST)
                _REPL_LOG_RECORDS.inc(float(len(fresh)), role="successor")
            return {"acked": True, "lsn": self.log.last_lsn,
                    "epoch": self.log.epoch}, rebuild

    def promote(self) -> int:
        """Assume primary role for this partition: bump the fencing
        epoch durably, then replay the unapplied log tail into sqlite.
        Idempotent under a crash at any point — the epoch bump replays
        (another +1 is harmless: epochs only need monotonicity), and
        the replay's applied-lsn cursor advances in the same transaction
        as the rows it applies."""
        t0 = time.time()
        with self._repl_lock:
            faults.crashpoint(_CP_REPL_PROMOTE_PRE)
            self.log.set_epoch(self.log.epoch + 1)
        self.replay()
        faults.crashpoint(_CP_REPL_PROMOTE_POST)
        with self._repl_lock:
            self.fenced = False
            self.owner = self.node_id
        _REPL_PROMOTES.inc()
        _REPL_PROMOTE_SECONDS.observe(time.time() - t0)
        _REPL_EPOCH.set(float(self.log.epoch),
                        partition=str(self.partition))
        return self.log.epoch

    def replay(self) -> int:
        """Apply every fully-durable log record past the applied-lsn
        cursor (one writer-thread transaction); returns records
        applied.  Running it twice is a no-op — the row-level-diff
        idempotence the fencing gate checks."""
        return self._run(self._replay_conn)

    def _rebuild(self) -> int:
        """Rebuild sqlite from the full log after a divergent-tail
        truncation.  A fenced zombie's degraded-mode writes were
        APPLIED locally (their futures resolved against this node's
        disk alone), so after the truncation drops those records the
        applied-lsn cursor lies: it counts lsns the log no longer
        holds, which would make replay silently skip the new primary's
        records at the same lsns.  Wiping the data tables and
        re-applying the whole log restores the invariant that sqlite
        is exactly the log prefix up to the cursor.  The dirty flag is
        cleared only after the rebuild transaction commits — a crash
        mid-rebuild re-runs it at reopen."""
        n = self._run(self._op_rebuild)
        self.log.set_dirty(False)
        return n

    def _op_rebuild(self, conn) -> int:
        for table in ("clients", "peer_backups", "snapshots",
                      "audit_reports", "repair_reports"):
            conn.execute("DELETE FROM " + table)
        for rec in self.log.records:
            self._apply_record(conn, rec)
        self._set_applied(conn, self.log.last_lsn)
        return len(self.log.records)

    def _replay_conn(self, conn) -> int:
        applied = self._op_applied_lsn(conn)
        tail = self.log.tail(applied)
        for rec in tail:
            self._apply_record(conn, rec)
        if tail:
            self._set_applied(conn, tail[-1]["lsn"])
        return len(tail)

    @staticmethod
    def _apply_record(conn, rec: dict) -> None:
        op = getattr(SqliteServerStore, "_op_" + rec["op"])
        args = decode_value(list(rec["args"]))
        if _REPL_WRITE_OPS.get(rec["op"]):
            args = args + [rec["ts"]]
        op(conn, *args)

    # --- the exactly-once cursor -------------------------------------------

    @staticmethod
    def _op_applied_lsn(conn) -> int:
        row = conn.execute(
            "SELECT value FROM metadata WHERE key = 'repl_applied_lsn'"
        ).fetchone()
        return int(row[0]) if row is not None else 0

    @staticmethod
    def _set_applied(conn, lsn: int) -> None:
        conn.execute(
            "INSERT INTO metadata (key, value) VALUES"
            " ('repl_applied_lsn', ?) ON CONFLICT(key)"
            " DO UPDATE SET value = excluded.value", (str(int(lsn)),))

    def applied_lsn(self) -> int:
        return self._run(self._op_applied_lsn)


class _ReplicatedAio:
    """``store.aio.<method>`` for :class:`ReplicatedServerStore`:
    locally owned partitions use the partition's own write-behind
    facade; foreign partitions forward to their owner."""

    def __init__(self, store: "ReplicatedServerStore"):
        self._store = store

    def __getattr__(self, name: str):
        if getattr(SqliteServerStore, "_op_" + name, None) is None:
            raise AttributeError(name)
        store = self._store

        async def call(*args):
            return await store._dispatch_async(name, args)

        call.__name__ = name
        return call


class ReplicatedServerStore(ServerStore):
    """Per-node replicated store: N :class:`_ReplPartitionStore` files
    under this node's OWN directory (nothing shared — node death is
    observable at the storage layer), with partition ownership decided
    by the ring and every write log-shipped to the partition's ring
    successors before its future resolves.

    Standalone (no federation) every partition is self-owned with an
    empty chain, and the store behaves exactly like
    :class:`PartitionedServerStore` — the conformance suite runs it
    that way.  Under federation the server layer installs the topology
    (:meth:`set_topology`), the sync ship hook, and the forward hooks
    for ops whose partition lives elsewhere; :meth:`promote` is the
    promote-on-death entry the probe loop calls.
    """

    _FAN_OUT = PartitionedServerStore._FAN_OUT

    def __init__(self, root, node_id: str = "n0",
                 partitions: Optional[int] = None):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.node_id = str(node_id)
        self.write_behind = True
        n = max(1, int(partitions or defaults.SERVER_STORE_PARTITIONS))
        self.parts: List[_ReplPartitionStore] = [
            _ReplPartitionStore(str(self.root / f"part_{i:02d}.db"),
                                str(self.root / f"part_{i:02d}.log"),
                                i, self.node_id)
            for i in range(n)]
        #: partition -> owning node id; self-owns-all until the server
        #: layer installs the ring-derived topology
        self.owners: Dict[int, str] = {}
        for i, part in enumerate(self.parts):
            self.owners[i] = self.node_id
            part.owner = self.node_id
        #: forward hooks for ops on foreign-owned partitions, wired by
        #: the server layer: sync ``(owner, body) -> response`` and its
        #: async twin.  ``None`` = execute locally (standalone mode).
        self.forward_sync: Optional[Callable[[str, dict], dict]] = None
        self.forward_async = None

    # --- topology ----------------------------------------------------------

    def partition_index(self, pubkey: bytes) -> int:
        return ring_partition_of(pubkey, len(self.parts))

    def partition_for(self, pubkey: bytes) -> _ReplPartitionStore:
        return self.parts[self.partition_index(pubkey)]

    def set_topology(self, owners: Optional[Dict[int, str]] = None,
                     successors: Optional[Dict[int, List[str]]] = None,
                     ship: Optional[Callable[[str, dict], dict]] = None,
                     ) -> None:
        for i, part in enumerate(self.parts):
            if owners is not None and i in owners:
                self.owners[i] = owners[i]
                part.owner = owners[i]
            if successors is not None:
                part.successors = [n for n in successors.get(i, [])
                                   if n != self.node_id]
            if ship is not None:
                part.ship = ship

    def set_owner(self, partition: int, node_id: str) -> None:
        self.owners[int(partition)] = node_id
        self.parts[int(partition)].owner = node_id

    def promote(self, partition: int) -> int:
        epoch = self.parts[int(partition)].promote()
        self.set_owner(int(partition), self.node_id)
        return epoch

    def accept_ship(self, payload: dict) -> dict:
        part = self.parts[int(payload["partition"])]
        return part.accept_ship(int(payload["epoch"]),
                                int(payload["from_lsn"]),
                                list(payload.get("records") or []))

    def log_tail(self, partition: int, after_lsn: int) -> dict:
        """This node's log records past ``after_lsn`` for a partition —
        the promote-time reconciliation read (a sibling successor may
        hold acked records the promoting node never saw)."""
        part = self.parts[int(partition)]
        with part._repl_lock:
            return {"epoch": part.log.epoch,
                    "records": part.log.tail(int(after_lsn))}

    def execute_local(self, partition: int, name: str,
                      args: list) -> dict:
        """Serve one forwarded op on a LOCAL partition (the /repl/
        forward intake).  Never re-forwards — a stale owner map on the
        sender gets ``wrong_owner`` back and retries once toward the
        node named here."""
        i = int(partition)
        if getattr(SqliteServerStore, "_op_" + name, None) is None:
            raise ValueError(f"unknown op {name!r}")
        if self.owners.get(i) != self.node_id:
            _REPL_FORWARDS.inc(outcome="wrong_owner")
            return {"wrong_owner": self.owners.get(i)}
        result = getattr(self.parts[i], name)(*decode_value(list(args)))
        return {"result": encode_value(result)}

    @property
    def commit_threads(self) -> set:
        out: set = set()
        for p in self.parts:
            out |= p.commit_threads
        return out

    # --- dispatch ----------------------------------------------------------

    def _target_partitions(self, name: str, args) -> List[int]:
        if name in self._FAN_OUT:
            return list(range(len(self.parts)))
        if name == "reclaim_negotiation":
            idxs = {self.partition_index(args[0]),
                    self.partition_index(args[1])}
            return sorted(idxs)
        return [self.partition_index(args[0])]

    @staticmethod
    def _merge(name: str, results: List[Any]) -> Any:
        if name == "audit_failing_reporters":
            return sum(results)
        if name == "reclaim_negotiation":
            return sum(results)
        if name == "get_clients_storing_on":
            return PartitionedServerStore._merge_distinct(list(results))
        return results[0]

    def _forward_body(self, i: int, name: str, args) -> dict:
        return {"partition": i, "op": name,
                "args": encode_value(list(args))}

    def _dispatch_sync(self, name: str, args):
        if name == "schema_version":
            return self.parts[0].schema_version()
        out = []
        for i in self._target_partitions(name, args):
            if self.owners.get(i) == self.node_id \
                    or self.forward_sync is None:
                out.append(getattr(self.parts[i], name)(*args))
                continue
            resp = self.forward_sync(self.owners[i],
                                     self._forward_body(i, name, args))
            if resp.get("wrong_owner"):
                # stale owner map: adopt the correction, retry once
                self.set_owner(i, resp["wrong_owner"])
                if resp["wrong_owner"] == self.node_id:
                    out.append(getattr(self.parts[i], name)(*args))
                    continue
                resp = self.forward_sync(
                    self.owners[i], self._forward_body(i, name, args))
            _REPL_FORWARDS.inc(outcome="ok")
            out.append(decode_value(resp["result"]))
        if name in self._FAN_OUT or name == "reclaim_negotiation":
            return self._merge(name, out)
        return out[0]

    async def _dispatch_async(self, name: str, args):
        if name == "schema_version":
            return await self.parts[0].aio.schema_version()
        out = []
        for i in self._target_partitions(name, args):
            if self.owners.get(i) == self.node_id \
                    or self.forward_async is None:
                out.append(
                    await getattr(self.parts[i].aio, name)(*args))
                continue
            resp = await self.forward_async(
                self.owners[i], self._forward_body(i, name, args))
            if resp.get("wrong_owner"):
                self.set_owner(i, resp["wrong_owner"])
                if resp["wrong_owner"] == self.node_id:
                    out.append(
                        await getattr(self.parts[i].aio, name)(*args))
                    continue
                resp = await self.forward_async(
                    self.owners[i], self._forward_body(i, name, args))
            _REPL_FORWARDS.inc(outcome="ok")
            out.append(decode_value(resp["result"]))
        if name in self._FAN_OUT or name == "reclaim_negotiation":
            return self._merge(name, out)
        return out[0]

    @property
    def aio(self) -> _ReplicatedAio:
        return _ReplicatedAio(self)

    # --- lifecycle ---------------------------------------------------------

    def flush(self) -> None:
        for p in self.parts:
            p.flush()

    def close(self) -> None:
        for p in self.parts:
            p.close()

    # --- the ServerStore surface, routed ------------------------------------

    def schema_version(self) -> int:
        return self._dispatch_sync("schema_version", ())

    def register_client(self, pubkey: bytes) -> None:
        self._dispatch_sync("register_client", (pubkey,))

    def client_exists(self, pubkey: bytes) -> bool:
        return self._dispatch_sync("client_exists", (pubkey,))

    def client_update_logged_in(self, pubkey: bytes) -> None:
        self._dispatch_sync("client_update_logged_in", (pubkey,))

    def save_storage_negotiated(self, source: bytes, destination: bytes,
                                size: int) -> None:
        self._dispatch_sync("save_storage_negotiated",
                            (source, destination, size))

    def delete_storage_negotiated(self, source: bytes, destination: bytes,
                                  size: int) -> None:
        self._dispatch_sync("delete_storage_negotiated",
                            (source, destination, size))

    def save_snapshot(self, pubkey: bytes, snapshot_hash: bytes) -> None:
        self._dispatch_sync("save_snapshot", (pubkey, snapshot_hash))

    def get_latest_client_snapshot(self, pubkey: bytes) -> Optional[bytes]:
        return self._dispatch_sync("get_latest_client_snapshot", (pubkey,))

    def get_client_negotiated_peers(self, pubkey: bytes) -> list:
        return self._dispatch_sync("get_client_negotiated_peers", (pubkey,))

    def get_clients_storing_on(self, pubkey: bytes) -> list:
        return self._dispatch_sync("get_clients_storing_on", (pubkey,))

    def save_audit_report(self, reporter: bytes, peer: bytes, passed: bool,
                          detail: str) -> None:
        self._dispatch_sync("save_audit_report",
                            (reporter, peer, passed, detail))

    def save_repair_report(self, reporter: bytes, peer: bytes,
                           packfiles_lost: int, bytes_lost: int,
                           bytes_replaced: int) -> None:
        self._dispatch_sync("save_repair_report",
                            (reporter, peer, packfiles_lost, bytes_lost,
                             bytes_replaced))

    def reclaim_negotiation(self, client: bytes, peer: bytes) -> int:
        return self._dispatch_sync("reclaim_negotiation", (client, peer))

    def audit_failing_reporters(self, peer: bytes, window_s: float) -> int:
        return self._dispatch_sync("audit_failing_reporters",
                                   (peer, window_s))
