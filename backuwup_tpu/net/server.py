"""Coordination server: identity, matchmaking, rendezvous, snapshot registry.

Re-designs the reference server (``server/src/``) on aiohttp.  The control
plane never touches backup data (SURVEY.md §1): it does

* **challenge-response auth** on Ed25519 client keys — 30 s challenge TTL,
  24 h session TTL (``client_auth_manager.rs:17-20,49-101``),
* **storage-request matchmaking** — an expiring queue; ``fulfill`` pops
  candidates, matches ``min(remaining, candidate)``, notifies both clients
  over their push channels, records the negotiation in both directions, and
  re-enqueues remainders (``backup_request.rs:73-185``),
* **P2P rendezvous relay** — forwards connection requests/confirmations
  between clients (``handlers/p2p_connection_request.rs``),
* **snapshot registry** — latest snapshot hash per client plus the peer
  list needed for restore (``db.rs:129-187``, ``handlers/backup.rs``).

Since PR 10 the process is structured as a **stateless request tier** over
two swappable planes (docs/server.md):

* persistent state behind :class:`~.serverstore.ServerStore` — by default
  the write-behind :class:`~.serverstore.SqliteServerStore`, whose commits
  run on a dedicated writer thread with group commit; handlers ``await
  store.aio.*`` so a response that promises durability is only written
  after the commit, and the event loop never blocks on sqlite;
* matchmaking in :class:`~.matchmaking.ShardedMatchmaker` — N
  pubkey-sharded in-memory queues with per-shard locks, deadline-heap
  expiry, and cross-shard work stealing.

``CoordinationServer(legacy=True)`` assembles the pre-PR-10 shape (the
direct-commit :class:`~.serverstore.ServerDB` plus the single-lock
:class:`StorageQueue`) as the measured baseline for bench config
``12_swarm``.
"""

from __future__ import annotations

import asyncio
import os
import time
import urllib.request
from typing import Dict, List, Optional, Set

import json

import aiohttp
from aiohttp import WSMsgType, web

from .. import defaults, wire
from ..crypto import verify_signature
from ..obs import expo as obs_expo
from ..obs import invariants as obs_invariants
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace
from .matchmaking import (_MATCHMAKINGS, _QUEUE_DEPTH,  # noqa: F401
                          ShardedMatchmaker)
from .ring import partition_key, successors as ring_successors
from .serverstore import (_MIGRATIONS, _SCHEMA, SCHEMA_VERSION,  # noqa: F401
                          ReplicatedServerStore, ReplicationFenced,
                          ServerDB, ServerStore, SqliteServerStore)

_REQUESTS = obs_metrics.counter(
    "bkw_server_requests_total", "Coordination-server requests by route",
    ("path",))
_REQUEST_SECONDS = obs_metrics.histogram(
    "bkw_server_request_seconds",
    "Coordination-server request latency by canonical route",
    ("route",))
_CONNECTED = obs_metrics.gauge(
    "bkw_server_connected_clients", "Clients on the WS push channel")

# Federation plane (docs/server.md §Federation).  Steal attempts are
# counted once per fulfill-side remote leg (hit/miss/error), serves once
# per /fed/steal RPC answered (hit/empty) — a federated pairing shows up
# as exactly one serve hit on the serving node and one steal hit on the
# requesting node.
_FED_STEALS = obs_metrics.counter(
    "bkw_federation_steals_total",
    "Requester-side cross-node steal attempts by outcome"
    " (hit/miss/error)", ("outcome",))
_FED_STEAL_SERVED = obs_metrics.counter(
    "bkw_federation_steal_served_total",
    "Serving-side /fed/steal RPCs answered by outcome (hit/empty)",
    ("outcome",))
_FED_RPC_SECONDS = obs_metrics.histogram(
    "bkw_federation_rpc_seconds",
    "Inter-node federation RPC latency by op", ("op",))
_FED_NOTIFY_RELAYS = obs_metrics.counter(
    "bkw_federation_notify_relays_total",
    "WS pushes relayed to another node's client by outcome"
    " (delivered/failed)", ("outcome",))
_RING_NODES = obs_metrics.gauge(
    "bkw_ring_nodes", "Coordination nodes on this node's hash ring")
_RING_REDIRECTS = obs_metrics.counter(
    "bkw_ring_redirects_total",
    "Wrong-node arrivals answered with a NodeRedirect (HTTP 421)")

# Families the clients of this process produce into; declared here too
# (get-or-create merges them) so a standalone server's /metrics always
# advertises the core catalog even before any client code is imported.
obs_metrics.histogram("bkw_transfer_send_seconds",
                      "Seconds spent in ws.send + ack per transfer")
obs_metrics.counter("bkw_audit_total", "Audit verdicts by outcome",
                    ("outcome",))
obs_metrics.counter("bkw_repair_rounds_total", "Peer-loss repair rounds run")


class AuthManager:
    """Challenges (30 s) and session tokens (24 h) with expiry
    (client_auth_manager.rs)."""

    def __init__(self):
        self._challenges: Dict[bytes, tuple] = {}  # pubkey -> (nonce, expiry)
        self._sessions: Dict[bytes, tuple] = {}  # token -> (pubkey, expiry)

    def challenge_begin(self, pubkey: bytes) -> bytes:
        nonce = os.urandom(wire.CHALLENGE_NONCE_LEN)
        self._challenges[pubkey] = (
            nonce, time.time() + defaults.AUTH_CHALLENGE_TTL_S)
        return nonce

    def take_challenge(self, pubkey: bytes) -> Optional[bytes]:
        """Pop a live challenge nonce; None when absent/expired (the
        reference distinguishes ChallengeNotFound -> Retry from a bad
        signature -> BadRequest, handlers/mod.rs:52-76)."""
        entry = self._challenges.pop(pubkey, None)
        if entry is None or entry[1] < time.time():
            return None
        return entry[0]

    def session_start(self, pubkey: bytes) -> bytes:
        token = os.urandom(wire.SESSION_TOKEN_LEN)
        self._sessions[token] = (pubkey, time.time() + defaults.SESSION_TTL_S)
        return token

    def get_session(self, token: Optional[bytes]) -> Optional[bytes]:
        if token is None:
            return None
        entry = self._sessions.get(bytes(token))
        if entry is None or entry[1] < time.time():
            self._sessions.pop(bytes(token), None)
            return None
        return entry[0]


class Connections:
    """client-id -> WS push sink registry (server/src/ws.rs:73-109).

    With federation enabled, ``relay`` is an async
    ``(client_id, msg) -> bool`` hook consulted when the client has no
    LOCAL socket: the push is forwarded to the node that does hold it
    (/fed/notify), so p2p rendezvous, AuditDue nudges, and steal-served
    matches reach clients wherever they (re)connected.  ``is_online``
    stays local on purpose — it gates queue admission, and a remote
    socket's liveness is the remote node's business.
    """

    def __init__(self):
        self._socks: Dict[bytes, web.WebSocketResponse] = {}
        self.relay = None

    def register(self, client_id: bytes, ws: web.WebSocketResponse) -> None:
        self._socks[bytes(client_id)] = ws
        _CONNECTED.set(len(self._socks))

    def unregister(self, client_id: bytes, ws: web.WebSocketResponse) -> None:
        if self._socks.get(bytes(client_id)) is ws:
            self._socks.pop(bytes(client_id), None)
        _CONNECTED.set(len(self._socks))

    def count(self) -> int:
        return len(self._socks)

    def is_online(self, client_id: bytes) -> bool:
        return bytes(client_id) in self._socks

    async def notify_local(self, client_id: bytes,
                           msg: wire.JsonMessage) -> bool:
        """Push to a locally connected socket only (the /fed/notify
        handler terminates here — a relay must never re-relay)."""
        ws = self._socks.get(bytes(client_id))
        if ws is None or ws.closed:
            return False
        try:
            await ws.send_str(msg.to_json())
            return True
        except (ConnectionError, RuntimeError):
            self._socks.pop(bytes(client_id), None)
            return False

    async def notify(self, client_id: bytes, msg: wire.JsonMessage) -> bool:
        if await self.notify_local(client_id, msg):
            return True
        if self.relay is not None:
            return await self.relay(bytes(client_id), msg)
        return False


class StorageQueue:
    """The original single-lock matchmaking economy (backup_request.rs):
    an expiring list of (client, bytes-wanted) fulfilled by pairing
    clients with each other.

    Retained as the measured baseline for the sharded matchmaker
    (``CoordinationServer(legacy=True)``, bench config ``12_swarm``) and
    because its semantics tests pin the matchmaking contract both
    implementations honor.  Structural costs, by design: ``_lock`` is
    held across the WHOLE fulfill — db writes and WS pushes included —
    and expiry rescans the list front on every pop."""

    def __init__(self, db, connections: Connections,
                 expiry_s: float = None):
        self.db = db
        self.connections = connections
        self.expiry_s = (defaults.BACKUP_REQUEST_EXPIRY_S
                         if expiry_s is None else expiry_s)
        self._queue: list = []  # (client_id, remaining, expires_at)
        self._lock = asyncio.Lock()

    def _pop_valid(self) -> Optional[tuple]:
        now = time.time()
        while self._queue:
            client, remaining, expires = self._queue.pop(0)
            if expires >= now and self.connections.is_online(client):
                return client, remaining, expires
        return None

    async def fulfill(self, client_id: bytes, storage_required: int,
                      min_peers: int = 1) -> None:
        """Match against queued requests; both sides get BackupMatched for
        min(remaining, candidate); remainders re-enqueue
        (backup_request.rs:73-185).

        ``min_peers > 1`` is the erasure-stripe hint: the requester wants
        its grant spread over at least that many DISTINCT peers (a stripe
        needs k+m holders), so each match is capped at an even share
        instead of letting one storage-rich candidate swallow the whole
        request.  The cap only applies while the queue holds enough other
        candidates to plausibly reach the spread — with a shallower queue
        it falls back to greedy matching, so 2–3-client deployments see
        exactly the pre-erasure behavior.
        """
        if storage_required > defaults.MAX_BACKUP_STORAGE_REQUEST_SIZE:
            raise ValueError("storage request exceeds protocol cap")
        min_peers = max(int(min_peers), 1)
        async with self._lock:
            share_cap = None
            if min_peers > 1:
                others = {c for c, _r, _e in self._queue
                          if c != bytes(client_id)}
                if len(others) >= min_peers:
                    share_cap = -(-storage_required // min_peers)
            remaining = storage_required
            while remaining > 0:
                entry = self._pop_valid()
                if entry is None:
                    break
                candidate, cand_remaining, cand_expires = entry
                if candidate == bytes(client_id):
                    continue  # self-match discarded
                if self.db.audit_failing_reporters(
                        candidate, defaults.AUDIT_REPORT_WINDOW_S) \
                        >= defaults.AUDIT_SERVER_BLOCK_FAILURES:
                    # Independently reported as failing storage audits:
                    # drop its queued request rather than hand it new data.
                    continue
                match = min(remaining, cand_remaining)
                if share_cap is not None:
                    match = min(match, share_cap)
                # Record the negotiation FIRST, then push: a client must
                # never learn of a match the server does not persist (a
                # notified candidate would start treating the requester as a
                # negotiated peer while get_client_negotiated_peers denies
                # it).  A failed candidate push rolls the record back; the
                # reference instead records after notify
                # (backup_request.rs:95-139) and carries that window.
                # Known residual window: a server CRASH between the save and
                # the notify leaves a phantom record neither client knows
                # about.  That is harmless on the send path (the peer simply
                # never dials) and tolerated on restore: the phantom peer
                # refuses the dial as an unknown peer, and the client
                # proceeds anyway when the data from the remaining peers
                # covers the snapshot (engine._restored_coverage_gap).
                self.db.save_storage_negotiated(bytes(client_id), candidate,
                                                match)
                self.db.save_storage_negotiated(candidate, bytes(client_id),
                                                match)
                ok_cand = await self.connections.notify(
                    candidate, wire.BackupMatched(
                        destination_id=bytes(client_id),
                        storage_available=match))
                if not ok_cand:
                    # Candidate unreachable: roll back, drop its queued
                    # request, and try the next one
                    # (backup_request.rs:166-173).
                    self.db.delete_storage_negotiated(
                        bytes(client_id), candidate, match)
                    self.db.delete_storage_negotiated(
                        candidate, bytes(client_id), match)
                    continue
                _MATCHMAKINGS.inc()
                ok_self = await self.connections.notify(
                    bytes(client_id), wire.BackupMatched(
                        destination_id=candidate, storage_available=match))
                if not ok_self:
                    # The requester is unreachable but the candidate has
                    # already been told: keep the record (both sides stay
                    # consistent; the requester discovers the peer on its
                    # next restore/reconnect), re-enqueue the candidate's
                    # remainder, and stop matching for the dead requester.
                    cand_remaining -= match
                    if cand_remaining > 0:
                        self._queue.append((candidate, cand_remaining,
                                            cand_expires))
                    return
                remaining -= match
                cand_remaining -= match
                if cand_remaining > 0:
                    self._queue.append((candidate, cand_remaining,
                                        cand_expires))
            if remaining > 0:
                self._queue.append((bytes(client_id), remaining,
                                    time.time() + self.expiry_s))
            _QUEUE_DEPTH.set(len(self._queue))

    def pending(self) -> int:
        depth = len(self._queue)
        _QUEUE_DEPTH.set(depth)  # point-in-time refresh for scrapers
        return depth


@web.middleware
async def _obs_middleware(request, handler):
    """Per-request observability: count and time by canonical route
    (bounded label cardinality — the route table, not raw paths) and
    adopt the client's trace id from the POST JSON so the server-side
    span journals under the same id as the caller's.  The latency lands
    in ``bkw_server_request_seconds{route}``; the swarm scorecard and
    bench config 12 read their p99 from its buckets."""
    resource = request.match_info.route.resource
    path = resource.canonical if resource is not None else request.path
    _REQUESTS.inc(path=path)
    trace_id = None
    if request.method == "POST" and request.can_read_body:
        try:
            # request.text() caches: handlers re-read the same body
            trace_id = json.loads(await request.text()).get("trace_id")
        except (ValueError, UnicodeDecodeError):
            pass
    t0 = time.monotonic()
    try:
        with obs_trace.bind(trace_id), obs_trace.span(f"server{path}"):
            return await handler(request)
    except ReplicationFenced as e:
        # a zombie primary's write was refused by a higher-epoch chain:
        # flip the local owner table and steer the client to the node
        # that fenced us (it is either the new owner or knows it)
        srv = request.app.get("bkw_server")
        if srv is not None and e.owner and e.partition is not None \
                and isinstance(srv.db, ReplicatedServerStore):
            srv.db.set_owner(e.partition, e.owner)
        url = srv.peers.get(e.owner) if (srv is not None and e.owner) \
            else None
        if url:
            _RING_REDIRECTS.inc()
            raise web.HTTPMisdirectedRequest(
                text=wire.NodeRedirect(url=url).to_json(),
                content_type="application/json")
        raise web.HTTPConflict(
            text=wire.Error(kind=wire.ErrorKind.RETRY,
                            detail=str(e)).to_json(),
            content_type="application/json")
    finally:
        _REQUEST_SECONDS.observe(time.monotonic() - t0, route=path)


class CoordinationServer:
    """The stateless request tier.

    Handlers keep no cross-request state beyond the auth/session maps
    and the live WS registry; persistent state is behind ``self.db`` (a
    :class:`~.serverstore.ServerStore`) and queueing behind
    ``self.queue``.  Durable writes go through ``self.db.aio`` — in the
    default write-behind store the await resolves only after the group
    commit, so the durability-promising responses (registration, login
    bookkeeping, snapshot registration, audit/repair verdicts,
    negotiation records) are acknowledged only once committed, without
    ever running a sqlite commit on the event loop.

    ``legacy=True`` assembles the pre-PR-10 single-lock shape over a
    direct-commit store — the bench baseline.  ``store=`` injects any
    other :class:`~.serverstore.ServerStore` implementation.
    """

    def __init__(self, db_path=":memory:", store: Optional[ServerStore] = None,
                 legacy: bool = False, shards: Optional[int] = None):
        # An injected store has a wider lifecycle than this server: a
        # federated deployment shares one PartitionedServerStore across
        # node instances (and node revive reuses it), so stop() only
        # closes stores this instance constructed.
        self._owns_store = store is None
        if store is None:
            store = (ServerDB(db_path) if legacy
                     else SqliteServerStore(db_path))
        self.db = store
        self.legacy = bool(legacy)
        self.auth = AuthManager()
        self.connections = Connections()
        if legacy:
            self.queue = StorageQueue(self.db, self.connections)
        else:
            self.queue = ShardedMatchmaker(self.db, self.connections,
                                           shards=shards)
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None
        self._started = time.time()
        # federation state (dormant until enable_federation)
        self.node_id: Optional[str] = None
        self.ring = None
        self.peers: Dict[str, str] = {}
        self._fed_http: Optional[aiohttp.ClientSession] = None
        self._peer_down_until: Dict[str, float] = {}
        self._steal_cooldown_until = 0.0
        # replication state (dormant unless the store is replicated)
        self._repl_chains: Dict[int, List[str]] = {}
        self._probe_task: Optional[asyncio.Task] = None
        self._probe_fail: Dict[str, int] = {}
        self._dead_nodes: Set[str] = set()

    # --- helpers -----------------------------------------------------------

    _STATUS_EXC = {400: web.HTTPBadRequest, 401: web.HTTPUnauthorized,
                   404: web.HTTPNotFound, 409: web.HTTPConflict,
                   500: web.HTTPInternalServerError}

    @staticmethod
    def _err(kind: str, detail: str = "",
             status: Optional[int] = None) -> web.HTTPException:
        """Typed error response: one of the 8 wire.ErrorKind payloads at
        its mapped HTTP status (handlers/mod.rs:50-91)."""
        status = status or wire.ERROR_HTTP_STATUS[kind]
        exc = CoordinationServer._STATUS_EXC[status]
        return exc(text=wire.Error(kind=kind, detail=detail).to_json(),
                   content_type="application/json")

    def _session(self, msg) -> bytes:
        client = self.auth.get_session(msg.session_token)
        if client is None:
            raise self._err(wire.ErrorKind.UNAUTHORIZED)
        return client

    @staticmethod
    async def _parse(request, cls):
        try:
            msg = wire.JsonMessage.from_json(await request.text())
        except (ValueError, KeyError) as e:
            raise CoordinationServer._err(wire.ErrorKind.BAD_REQUEST, str(e))
        if not isinstance(msg, cls):
            raise CoordinationServer._err(
                wire.ErrorKind.BAD_REQUEST, f"expected {cls.__name__}")
        return msg

    @staticmethod
    def _ok(msg: wire.JsonMessage = None) -> web.Response:
        return web.Response(text=(msg or wire.Ok()).to_json(),
                            content_type="application/json")

    # --- federation (docs/server.md §Federation) ----------------------------

    def enable_federation(self, node_id: str, ring, peers: Dict[str, str]
                          ) -> None:
        """Join this node to a federated deployment.

        ``ring`` is the shared :class:`~.ring.HashRing` (every node and
        client computes the identical ring from the node list);
        ``peers`` maps node id -> base URL for every node, this one
        included.  Call after :meth:`start` (peer URLs carry the
        OS-assigned ports).  Wires up:

        * the matchmaker's ``remote_steal`` leg — consulted only once
          every local shard is empty, walking ``ring.steal_order`` with
          per-peer dial backoff;
        * WS push relay — pushes for clients connected elsewhere are
          forwarded over /fed/notify, owner node first;
        * wrong-node redirects — session-less entry points answer 421
          with the owner's URL when the arrival is misrouted.

        Trust model: /fed/* is unauthenticated — federation assumes a
        private inter-node network, same trust boundary as the shared
        store files.
        """
        self.node_id = str(node_id)
        self.ring = ring
        self.peers = {str(n): u.rstrip("/") for n, u in peers.items()
                      if str(n) != self.node_id}
        if isinstance(self.queue, ShardedMatchmaker):
            self.queue.remote_steal = self._remote_steal
        self.connections.relay = self._relay_notify
        _RING_NODES.set(len(ring))
        if isinstance(self.db, ReplicatedServerStore):
            self._wire_replication()

    # --- replication (docs/server.md §Replication) ---------------------------

    def _partition_order(self, partition: int) -> List[str]:
        """Takeover seniority for a partition: its ring owner, then the
        ring successors — the same order every node computes, so exactly
        one live node concludes it is next in line."""
        owner = self.ring.owner(partition_key(partition))
        order = [owner] if owner is not None else []
        return order + [n for n in self.ring.steal_order(owner or "")
                        if n not in order]

    def _partition_chain(self, partition: int) -> List[str]:
        """Successor chain from THIS node's perspective: the next
        ``REPL_SUCCESSORS`` seniority members after wherever this node
        sits, which after a takeover deliberately still includes the
        original (dead) owner — ships to it fail harmlessly under
        backoff until the zombie revives, at which point the first ship
        re-fences it and it rejoins as a successor."""
        order = [n for n in self._partition_order(partition)
                 if n != self.node_id]
        return order[:defaults.REPL_SUCCESSORS]

    def _wire_replication(self) -> None:
        store = self.db
        owners: Dict[int, str] = {}
        chains: Dict[int, List[str]] = {}
        for i in range(len(store.parts)):
            owners[i] = self.ring.owner(partition_key(i)) or self.node_id
            chains[i] = (ring_successors(self.ring, i)
                         if owners[i] == self.node_id else [])
            self._repl_chains[i] = chains[i]
        store.set_topology(owners=owners, successors=chains,
                           ship=self._repl_ship)
        store.forward_sync = self._repl_forward_sync
        store.forward_async = self._repl_forward_async
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None and self.peers:
            self._probe_task = loop.create_task(self._probe_loop())

    def _repl_url(self, node_id: str, path: str) -> str:
        url = self.peers.get(node_id)
        if url is None:
            raise ConnectionError(f"unknown peer {node_id!r}")
        return url + path

    def _repl_ship(self, node_id: str, payload: dict) -> dict:
        """Sync ship hook for the store's WRITER THREAD (never the event
        loop): POST one log tail to a successor's /repl/ship.  Synchrony
        is the point — the batch's futures must not resolve until the
        successor's ack (or a deliberate degraded decision) is in."""
        req = urllib.request.Request(
            self._repl_url(node_id, "/repl/ship"),
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(
                req, timeout=defaults.REPL_SHIP_TIMEOUT_S) as resp:
            return json.loads(resp.read())

    def _repl_forward_sync(self, node_id: str, body: dict) -> dict:
        req = urllib.request.Request(
            self._repl_url(node_id, "/repl/forward"),
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(
                req, timeout=defaults.FEDERATION_RPC_TIMEOUT_S) as resp:
            return json.loads(resp.read())

    async def _repl_post(self, node_id: str, path: str, body: dict,
                         op: str) -> dict:
        """Replication RPC: like :meth:`_fed_post` but WITHOUT the
        peer-down negative cache — a forward's owner (or a promote's
        reconciliation source) is the only correct target, so failing
        fast for the whole backoff window would turn one timed-out RPC
        into seconds of refused writes.  Raises instead of None."""
        url = self.peers.get(node_id)
        if url is None:
            raise ConnectionError(f"unknown peer {node_id!r}")
        body = dict(body, trace_id=obs_trace.current_trace_id())
        t0 = time.monotonic()
        try:
            async with self._fed_session().post(
                    url + path, json=body,
                    timeout=aiohttp.ClientTimeout(
                        total=defaults.REPL_FORWARD_TIMEOUT_S)) as resp:
                doc = await resp.json()
            if resp.status != 200:
                raise ConnectionError(
                    f"{path} to {node_id!r}: HTTP {resp.status}")
            return doc
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            # str(asyncio.TimeoutError()) is empty — name the type so
            # the log line says WHAT failed, not just that it did
            raise ConnectionError(
                f"{path} to {node_id!r} failed:"
                f" {e or type(e).__name__}") from e
        finally:
            _FED_RPC_SECONDS.observe(time.monotonic() - t0, op=op)

    async def _repl_forward_async(self, node_id: str, body: dict) -> dict:
        return await self._repl_post(node_id, "/repl/forward", body,
                                     op="forward")

    async def _probe_peer(self, node_id: str) -> bool:
        """One liveness probe: any HTTP answer (even an unhealthy 503)
        means the process is alive — promotion is for DEAD primaries,
        not degraded ones."""
        url = self.peers.get(node_id)
        if url is None:
            return False
        try:
            async with self._fed_session().get(url + "/healthz") as resp:
                await resp.read()
            return True
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return False

    async def _probe_loop(self) -> None:
        while True:
            await asyncio.sleep(defaults.REPL_PROBE_INTERVAL_S)
            try:
                await self._probe_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # probes must never kill the loop
                continue

    async def _probe_once(self) -> None:
        store = self.db
        # who do we care about? every current owner of a partition whose
        # chain we sit on, plus everyone senior to us there (we defer to
        # a live senior rather than racing it to promote)
        for node in list(self.peers):
            if await self._probe_peer(node):
                self._probe_fail[node] = 0
                self._dead_nodes.discard(node)
            else:
                self._probe_fail[node] = self._probe_fail.get(node, 0) + 1
                if self._probe_fail[node] >= defaults.REPL_PROBE_FAILURES:
                    self._dead_nodes.add(node)
        for i in range(len(store.parts)):
            owner = store.owners.get(i)
            if owner == self.node_id or owner not in self._dead_nodes:
                continue
            order = self._partition_order(i)
            if self.node_id not in order:
                continue
            seniors = order[:order.index(self.node_id)]
            if any(n != owner and n not in self._dead_nodes
                   for n in seniors):
                continue  # a live senior will take it
            await self._promote_partition(i)

    async def _promote_partition(self, partition: int) -> None:
        """Promote-on-death: reconcile the log with the surviving chain
        members, replay the tail, assume ownership, re-chain, announce.

        Reconciliation first: the dead primary needed only ONE ack per
        batch, so a sibling successor may hold acked records this node
        never saw.  Pull every live chain member's tail past our lsn and
        merge it (accept_ship dedupes) BEFORE the epoch bump — promoting
        around the longest surviving log is what makes 'acked by >=1
        live successor' equal 'survives the primary's death'."""
        part = self.db.parts[partition]
        order = [n for n in self._partition_order(partition)
                 if n != self.node_id]
        for node in order[:defaults.REPL_SUCCESSORS + 1]:
            if node in self._dead_nodes:
                continue
            # a live sibling may hold the ONLY surviving copy of an
            # acked record, so one failed pull gets one retry before
            # this node promotes around a shorter log
            doc = None
            for attempt in (0, 1):
                try:
                    doc = await self._repl_post(
                        node, "/repl/tail",
                        {"partition": int(partition),
                         "after_lsn": part.log.last_lsn}, op="tail")
                    break
                except ConnectionError:
                    if attempt == 0:
                        await asyncio.sleep(0.2)
            if doc is None:
                continue
            if doc.get("records"):
                await asyncio.to_thread(self.db.accept_ship, {
                    "partition": int(partition),
                    "epoch": max(int(doc.get("epoch", 0)),
                                 part.log.epoch),
                    "from_lsn": part.log.last_lsn + 1,
                    "records": doc["records"]})
        epoch = await asyncio.to_thread(self.db.promote, partition)
        chain = self._partition_chain(partition)
        self._repl_chains[partition] = chain
        self.db.set_topology(successors={partition: chain},
                             ship=self._repl_ship)
        body = {"partition": int(partition), "epoch": int(epoch),
                "owner": self.node_id}
        for node in list(self.peers):
            await self._fed_post(node, "/repl/promote", body, op="promote")

    async def repl_ship(self, request):
        """Inter-node RPC: successor intake for one shipped log tail
        (store-level accept_ship does epoch fencing, gap detection, and
        the durable append — on the writer-pool thread, never here)."""
        if not isinstance(self.db, ReplicatedServerStore):
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "replication not enabled")
        try:
            doc = json.loads(await request.text())
            resp = await asyncio.to_thread(self.db.accept_ship, doc)
        except (ValueError, KeyError, TypeError, IndexError) as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        return web.json_response(resp)

    async def repl_promote(self, request):
        """Inter-node RPC: a promotion announcement.  Adopt the new
        owner for the partition when the epoch is no older than ours —
        a zombie primary hearing this learns it was superseded."""
        if not isinstance(self.db, ReplicatedServerStore):
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "replication not enabled")
        try:
            doc = json.loads(await request.text())
            partition = int(doc["partition"])
            epoch = int(doc["epoch"])
            owner = str(doc["owner"])
            part = self.db.parts[partition]
        except (ValueError, KeyError, TypeError, IndexError) as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        if epoch >= part.log.epoch:
            was_owner = self.db.owners.get(partition) == self.node_id
            self.db.set_owner(partition, owner)
            if owner != self.node_id and was_owner:
                # we were the primary and just learned we are not: stop
                # accepting writes NOW, not at the next fenced ship
                part.fenced = True
        return web.json_response({"ok": True, "epoch": part.log.epoch})

    async def repl_tail(self, request):
        """Inter-node RPC: read this node's log records past a given
        lsn for one partition — the promote-time reconciliation pull."""
        if not isinstance(self.db, ReplicatedServerStore):
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "replication not enabled")
        try:
            doc = json.loads(await request.text())
            resp = await asyncio.to_thread(
                self.db.log_tail, int(doc["partition"]),
                int(doc["after_lsn"]))
        except (ValueError, KeyError, TypeError, IndexError) as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        return web.json_response(resp)

    async def repl_forward(self, request):
        """Inter-node RPC: execute one store op on a LOCAL partition for
        a node that does not own it (the store's forward hooks land
        here).  Never re-forwards — a stale sender gets wrong_owner."""
        if not isinstance(self.db, ReplicatedServerStore):
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "replication not enabled")
        try:
            doc = json.loads(await request.text())
            resp = await asyncio.to_thread(
                self.db.execute_local, int(doc["partition"]),
                str(doc["op"]), list(doc.get("args") or []))
        except (ValueError, KeyError, TypeError, IndexError) as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        return web.json_response(resp)

    def _fed_session(self) -> aiohttp.ClientSession:
        if self._fed_http is None or self._fed_http.closed:
            self._fed_http = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=defaults.FEDERATION_RPC_TIMEOUT_S))
        return self._fed_http

    def _peer_down(self, node_id: str) -> bool:
        return self._peer_down_until.get(node_id, 0.0) > time.monotonic()

    def _mark_peer_down(self, node_id: str) -> None:
        self._peer_down_until[node_id] = (
            time.monotonic() + defaults.FEDERATION_PEER_BACKOFF_S)

    async def _fed_post(self, node_id: str, path: str, body: dict,
                        op: str) -> Optional[dict]:
        """One inter-node RPC: POST ``body`` (plus the current trace id,
        which the peer's _obs_middleware adopts — cross-node spans
        journal under the caller's id) to ``node_id``.  Failures mark
        the peer down for FEDERATION_PEER_BACKOFF_S and return None."""
        url = self.peers.get(node_id)
        if url is None or self._peer_down(node_id):
            return None
        body = dict(body, trace_id=obs_trace.current_trace_id())
        t0 = time.monotonic()
        try:
            async with self._fed_session().post(url + path,
                                                json=body) as resp:
                doc = await resp.json()
            if resp.status != 200:
                return None
            self._peer_down_until.pop(node_id, None)
            return doc
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            self._mark_peer_down(node_id)
            return None
        finally:
            _FED_RPC_SECONDS.observe(time.monotonic() - t0, op=op)

    async def _remote_steal(self, requester: bytes, want: int,
                            share_cap: Optional[int]):
        """The matchmaker's remote leg: walk the other nodes in
        ring-successor order (the federated continuation of the
        home-shard-last walk) and take the first served candidate.

        A full walk that comes back empty means the WHOLE federation is
        starved; retrying the ring on every subsequent fulfill would
        turn global starvation into an RPC storm that throttles local
        throughput (measured: ~4x on loopback).  An empty walk therefore
        arms a short negative cache and the remote leg sits out until it
        expires or a steal hits."""
        if self._steal_cooldown_until > time.monotonic():
            return None
        # arm BEFORE walking: concurrent fulfills that arrive while this
        # walk's RPCs are in flight skip instead of piling on; a hit
        # clears it again below
        self._steal_cooldown_until = (
            time.monotonic() + defaults.FEDERATION_STEAL_COOLDOWN_S)
        tried = 0
        for node in self.ring.steal_order(self.node_id):
            if node not in self.peers or self._peer_down(node):
                continue
            tried += 1
            doc = await self._fed_post(node, "/fed/steal", {
                "requester": bytes(requester).hex(),
                "want": int(want),
                "share_cap": share_cap,
            }, op="steal")
            if doc is None:
                _FED_STEALS.inc(outcome="error")
                continue
            if doc.get("candidate"):
                _FED_STEALS.inc(outcome="hit")
                self._steal_cooldown_until = 0.0
                return bytes.fromhex(doc["candidate"]), int(doc["match"])
        if tried:
            _FED_STEALS.inc(outcome="miss")
        return None

    async def _relay_notify(self, client_id: bytes,
                            msg: wire.JsonMessage) -> bool:
        """Forward a WS push to whichever node holds the client's
        socket: the ring owner first (where the client *should* be),
        then the rest — a failed-over client may be anywhere."""
        if self.ring is None:
            return False
        owner = self.ring.owner(client_id)
        order = [n for n in ([owner] + self.ring.steal_order(self.node_id))
                 if n is not None and n != self.node_id]
        seen = set()
        for node in order:
            if node in seen:
                continue
            seen.add(node)
            doc = await self._fed_post(node, "/fed/notify", {
                "client": bytes(client_id).hex(),
                "msg": msg.to_json(),
            }, op="notify")
            if doc is not None and doc.get("delivered"):
                _FED_NOTIFY_RELAYS.inc(outcome="delivered")
                return True
        _FED_NOTIFY_RELAYS.inc(outcome="failed")
        return False

    async def fed_steal(self, request):
        """Inter-node RPC: serve one matchmaking candidate to a remote
        requester (see ShardedMatchmaker.serve_steal for the
        invariants)."""
        if self.node_id is None or not isinstance(self.queue,
                                                  ShardedMatchmaker):
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "federation not enabled")
        try:
            doc = json.loads(await request.text())
            requester = bytes.fromhex(doc["requester"])
            want = int(doc["want"])
            cap = doc.get("share_cap")
        except (ValueError, KeyError, TypeError) as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        served = await self.queue.serve_steal(
            requester, want, None if cap is None else int(cap))
        if served is None:
            _FED_STEAL_SERVED.inc(outcome="empty")
            return web.json_response({"candidate": None})
        _FED_STEAL_SERVED.inc(outcome="hit")
        return web.json_response({"candidate": served[0].hex(),
                                  "match": served[1]})

    async def fed_notify(self, request):
        """Inter-node RPC: deliver a WS push to a LOCALLY connected
        client (terminates here — never re-relays)."""
        if self.node_id is None:
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "federation not enabled")
        try:
            doc = json.loads(await request.text())
            client = bytes.fromhex(doc["client"])
            msg = wire.JsonMessage.from_json(doc["msg"])
        except (ValueError, KeyError, TypeError) as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        delivered = await self.connections.notify_local(client, msg)
        return web.json_response({"delivered": delivered})

    def _maybe_redirect(self, pubkey: bytes, raw_body: str) -> None:
        """Wrong-node arrival on a session-less entry point: steer the
        client to its ring owner with a 421 NodeRedirect — unless the
        client pinned itself (``fed_pinned``, set after a failed dial or
        redirect hop: whatever node answers then keeps it) or the owner
        looks down.  Requests served in place remain CORRECT either way
        — the store routes by pubkey, not by serving node — so a stale
        client list costs latency, never a matchmaking."""
        if self.ring is None:
            return
        if isinstance(self.db, ReplicatedServerStore):
            # replication routes by partition OWNERSHIP (which promotion
            # moves), not raw ring position — redirect to wherever the
            # pubkey's partition currently lives.  Serving in place
            # stays correct: foreign-partition ops forward to the owner.
            owner = self.db.owners.get(self.db.partition_index(pubkey))
        else:
            owner = self.ring.owner(pubkey)
        if owner is None or owner == self.node_id:
            return
        url = self.peers.get(owner)
        if url is None or self._peer_down(owner):
            return
        try:
            if json.loads(raw_body).get("fed_pinned"):
                return
        except (ValueError, AttributeError):
            pass
        _RING_REDIRECTS.inc()
        raise web.HTTPMisdirectedRequest(
            text=wire.NodeRedirect(url=url).to_json(),
            content_type="application/json")

    # --- handlers (server/src/handlers/) -----------------------------------

    async def register_begin(self, request):
        msg = await self._parse(request, wire.ClientRegistrationRequest)
        self._maybe_redirect(msg.pubkey, await request.text())
        return self._ok(wire.ServerChallenge(
            nonce=self.auth.challenge_begin(msg.pubkey)))

    async def register_complete(self, request):
        msg = await self._parse(request, wire.ClientRegistrationAuth)
        nonce = self.auth.take_challenge(msg.pubkey)
        if nonce is None:
            # expired/unknown challenge: the client should restart the
            # flow (ChallengeNotFound -> Retry, handlers/mod.rs:73)
            raise self._err(wire.ErrorKind.RETRY)
        if not verify_signature(msg.pubkey, nonce, msg.challenge_response):
            raise self._err(wire.ErrorKind.BAD_REQUEST, "bad signature")
        if await self.db.aio.client_exists(msg.pubkey):
            # 409 CONFLICT with a BadRequest payload (ClientExists,
            # handlers/mod.rs:66,79)
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "client already exists", status=409)
        await self.db.aio.register_client(msg.pubkey)
        return self._ok()

    async def login_begin(self, request):
        msg = await self._parse(request, wire.ClientLoginRequest)
        self._maybe_redirect(msg.pubkey, await request.text())
        if not await self.db.aio.client_exists(msg.pubkey):
            raise self._err(wire.ErrorKind.CLIENT_NOT_FOUND)
        return self._ok(wire.ServerChallenge(
            nonce=self.auth.challenge_begin(msg.pubkey)))

    async def login_complete(self, request):
        msg = await self._parse(request, wire.ClientLoginAuth)
        nonce = self.auth.take_challenge(msg.pubkey)
        if nonce is None:
            raise self._err(wire.ErrorKind.RETRY)
        if not verify_signature(msg.pubkey, nonce, msg.challenge_response):
            raise self._err(wire.ErrorKind.BAD_REQUEST, "bad signature")
        await self.db.aio.client_update_logged_in(msg.pubkey)
        return self._ok(wire.LoginToken(token=self.auth.session_start(msg.pubkey)))

    async def backup_request(self, request):
        msg = await self._parse(request, wire.BackupRequest)
        client = self._session(msg)
        try:
            await self.queue.fulfill(client, msg.storage_required,
                                     min_peers=msg.min_peers)
        except ValueError as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        return self._ok()

    async def backup_done(self, request):
        msg = await self._parse(request, wire.BackupDone)
        client = self._session(msg)
        await self.db.aio.save_snapshot(client, msg.snapshot_hash)
        return self._ok()

    async def backup_restore(self, request):
        msg = await self._parse(request, wire.BackupRestoreRequest)
        client = self._session(msg)
        snapshot = await self.db.aio.get_latest_client_snapshot(client)
        if snapshot is None:
            # NoBackupsAvailable -> 404 NoBackups (handlers/backup.rs:30-38)
            raise self._err(wire.ErrorKind.NO_BACKUPS)
        peers = await self.db.aio.get_client_negotiated_peers(client)
        # advertise the deployment's stripe geometry so a from-scratch
        # restore client knows how many peer streams can go dark before
        # coverage is actually at risk (the shard containers themselves
        # are self-describing; this is advisory)
        return self._ok(wire.BackupRestoreInfo(
            snapshot_hash=snapshot, peers=[p.hex() for p in peers],
            rs_k=defaults.RS_K, rs_m=defaults.RS_M))

    async def p2p_begin(self, request):
        msg = await self._parse(request, wire.BeginP2PConnectionRequest)
        client = self._session(msg)
        delivered = await self.connections.notify(
            msg.destination_client_id, wire.IncomingP2PConnection(
                source_client_id=client, session_nonce=msg.session_nonce))
        if not delivered:
            raise self._err(wire.ErrorKind.DESTINATION_UNREACHABLE)
        return self._ok()

    async def p2p_confirm(self, request):
        msg = await self._parse(request, wire.ConfirmP2PConnectionRequest)
        client = self._session(msg)
        delivered = await self.connections.notify(
            msg.source_client_id, wire.FinalizeP2PConnection(
                destination_client_id=client,
                destination_ip_address=msg.destination_ip_address))
        if not delivered:
            raise self._err(wire.ErrorKind.DESTINATION_UNREACHABLE)
        return self._ok()

    async def audit_report(self, request):
        """Record one client's audit verdict on a peer; on failure, nudge
        every other client storing on that peer to audit it soon (the
        server never sees data, only verdicts — SURVEY.md §1 holds)."""
        msg = await self._parse(request, wire.AuditReport)
        client = self._session(msg)
        peer = bytes(msg.peer_id)
        await self.db.aio.save_audit_report(client, peer, bool(msg.passed),
                                            msg.detail or "")
        if not msg.passed:
            for source in await self.db.aio.get_clients_storing_on(peer):
                if source not in (client, peer):
                    await self.connections.notify(
                        source, wire.AuditDue(peer_id=peer))
        return self._ok()

    async def repair_report(self, request):
        """Record a completed peer-loss repair and reclaim the negotiation
        edges between the reporter and the lost peer, so the reporter's
        restore peer list drops the dead peer immediately.  Only the
        reporter's own edges are touched — other clients keep their own
        view of the peer until their own audits/repairs decide."""
        msg = await self._parse(request, wire.RepairReport)
        client = self._session(msg)
        peer = bytes(msg.peer_id)
        if peer == client:
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "cannot repair away from self")
        await self.db.aio.save_repair_report(client, peer, msg.packfiles_lost,
                                             msg.bytes_lost,
                                             msg.bytes_replaced)
        await self.db.aio.reclaim_negotiation(client, peer)
        return self._ok()

    # --- observability exposition (obs/expo.py) -----------------------------

    async def metrics(self, _request):
        self.queue.pending()  # refresh the queue-depth gauge
        _CONNECTED.set(self.connections.count())
        return obs_expo.metrics_response()

    async def healthz(self, _request):
        """Liveness plus the durability invariant summary.  The summary
        aggregates every InvariantMonitor publishing into this process's
        registry — all zeros / ``ok`` for a standalone server (the
        server never sees client placement state), and the live
        cross-client durability picture when clients are colocated (the
        scenario harness, tests, bench).  A violated invariant turns
        the whole document 503 (obs/expo.py)."""
        durability = obs_invariants.summary_from_registry()
        slo = obs_slo.summary_from_registry()
        return obs_expo.health_response(
            schema_version=await self.db.aio.schema_version(),
            queue_depth=self.queue.pending(),
            connected_clients=self.connections.count(),
            uptime_s=round(time.time() - self._started, 3),
            durability=durability,
            slo=slo,
            status=obs_slo.join_status(durability["status"],
                                       slo["status"]))

    async def ws(self, request):
        token = request.headers.get("Authorization")
        try:
            token_bytes = bytes.fromhex(token) if token else None
        except ValueError:
            raise self._err(wire.ErrorKind.UNAUTHORIZED, "malformed token")
        client = self.auth.get_session(token_bytes)
        if client is None:
            raise self._err(wire.ErrorKind.UNAUTHORIZED)
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        self.connections.register(client, ws)
        try:
            async for msg in ws:
                if msg.type in (WSMsgType.ERROR, WSMsgType.CLOSE):
                    break
        finally:
            self.connections.unregister(client, ws)
        return ws

    # --- lifecycle ---------------------------------------------------------

    def app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 20,
                              middlewares=[_obs_middleware])
        app.add_routes([
            web.get("/metrics", self.metrics),
            web.get("/healthz", self.healthz),
            web.post("/register/begin", self.register_begin),
            web.post("/register/complete", self.register_complete),
            web.post("/login/begin", self.login_begin),
            web.post("/login/complete", self.login_complete),
            web.post("/backups/request", self.backup_request),
            web.post("/backups/done", self.backup_done),
            web.post("/backups/restore", self.backup_restore),
            web.post("/p2p/connection/begin", self.p2p_begin),
            web.post("/p2p/connection/confirm", self.p2p_confirm),
            web.post("/audit/report", self.audit_report),
            web.post("/repair/report", self.repair_report),
            web.post("/fed/steal", self.fed_steal),
            web.post("/fed/notify", self.fed_notify),
            web.post("/repl/ship", self.repl_ship),
            web.post("/repl/promote", self.repl_promote),
            web.post("/repl/tail", self.repl_tail),
            web.post("/repl/forward", self.repl_forward),
            web.get("/ws", self.ws),
        ])
        app["bkw_server"] = self
        return app

    async def start(self, host="127.0.0.1", port=0,
                    ssl_context=None) -> int:
        """Serve; with ``ssl_context`` the control plane is HTTPS/WSS (the
        reference is TLS-by-default with a USE_TLS off-switch for local
        testing, requests.rs:246-258, docs/src/client.md:22)."""
        self._runner = web.AppRunner(self.app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port, ssl_context=ssl_context,
                           shutdown_timeout=defaults.SERVER_SHUTDOWN_GRACE_S)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._probe_task is not None:
            self._probe_task.cancel()
            try:
                await self._probe_task
            except asyncio.CancelledError:
                pass
            self._probe_task = None
        if self._fed_http is not None:
            if not self._fed_http.closed:
                await self._fed_http.close()
            self._fed_http = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        # drain + retire the writer thread; the store stays readable
        # (tests inspect server.db after stop).  An injected store is
        # the caller's (a federated deployment shares it across node
        # instances — node kill/revive must not close siblings' store).
        if self._owns_store:
            self.db.close()
