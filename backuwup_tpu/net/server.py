"""Coordination server: identity, matchmaking, rendezvous, snapshot registry.

Re-designs the reference server (``server/src/``) on aiohttp.  The control
plane never touches backup data (SURVEY.md §1): it does

* **challenge-response auth** on Ed25519 client keys — 30 s challenge TTL,
  24 h session TTL (``client_auth_manager.rs:17-20,49-101``),
* **storage-request matchmaking** — an expiring queue; ``fulfill`` pops
  candidates, matches ``min(remaining, candidate)``, notifies both clients
  over their push channels, records the negotiation in both directions, and
  re-enqueues remainders (``backup_request.rs:73-185``),
* **P2P rendezvous relay** — forwards connection requests/confirmations
  between clients (``handlers/p2p_connection_request.rs``),
* **snapshot registry** — latest snapshot hash per client plus the peer
  list needed for restore (``db.rs:129-187``, ``handlers/backup.rs``).

Since PR 10 the process is structured as a **stateless request tier** over
two swappable planes (docs/server.md):

* persistent state behind :class:`~.serverstore.ServerStore` — by default
  the write-behind :class:`~.serverstore.SqliteServerStore`, whose commits
  run on a dedicated writer thread with group commit; handlers ``await
  store.aio.*`` so a response that promises durability is only written
  after the commit, and the event loop never blocks on sqlite;
* matchmaking in :class:`~.matchmaking.ShardedMatchmaker` — N
  pubkey-sharded in-memory queues with per-shard locks, deadline-heap
  expiry, and cross-shard work stealing.

``CoordinationServer(legacy=True)`` assembles the pre-PR-10 shape (the
direct-commit :class:`~.serverstore.ServerDB` plus the single-lock
:class:`StorageQueue`) as the measured baseline for bench config
``12_swarm``.
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Dict, Optional

import json

from aiohttp import WSMsgType, web

from .. import defaults, wire
from ..crypto import verify_signature
from ..obs import expo as obs_expo
from ..obs import invariants as obs_invariants
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .matchmaking import (_MATCHMAKINGS, _QUEUE_DEPTH,  # noqa: F401
                          ShardedMatchmaker)
from .serverstore import (_MIGRATIONS, _SCHEMA, SCHEMA_VERSION,  # noqa: F401
                          ServerDB, ServerStore, SqliteServerStore)

_REQUESTS = obs_metrics.counter(
    "bkw_server_requests_total", "Coordination-server requests by route",
    ("path",))
_REQUEST_SECONDS = obs_metrics.histogram(
    "bkw_server_request_seconds",
    "Coordination-server request latency by canonical route",
    ("route",))
_CONNECTED = obs_metrics.gauge(
    "bkw_server_connected_clients", "Clients on the WS push channel")

# Families the clients of this process produce into; declared here too
# (get-or-create merges them) so a standalone server's /metrics always
# advertises the core catalog even before any client code is imported.
obs_metrics.histogram("bkw_transfer_send_seconds",
                      "Seconds spent in ws.send + ack per transfer")
obs_metrics.counter("bkw_audit_total", "Audit verdicts by outcome",
                    ("outcome",))
obs_metrics.counter("bkw_repair_rounds_total", "Peer-loss repair rounds run")


class AuthManager:
    """Challenges (30 s) and session tokens (24 h) with expiry
    (client_auth_manager.rs)."""

    def __init__(self):
        self._challenges: Dict[bytes, tuple] = {}  # pubkey -> (nonce, expiry)
        self._sessions: Dict[bytes, tuple] = {}  # token -> (pubkey, expiry)

    def challenge_begin(self, pubkey: bytes) -> bytes:
        nonce = os.urandom(wire.CHALLENGE_NONCE_LEN)
        self._challenges[pubkey] = (
            nonce, time.time() + defaults.AUTH_CHALLENGE_TTL_S)
        return nonce

    def take_challenge(self, pubkey: bytes) -> Optional[bytes]:
        """Pop a live challenge nonce; None when absent/expired (the
        reference distinguishes ChallengeNotFound -> Retry from a bad
        signature -> BadRequest, handlers/mod.rs:52-76)."""
        entry = self._challenges.pop(pubkey, None)
        if entry is None or entry[1] < time.time():
            return None
        return entry[0]

    def session_start(self, pubkey: bytes) -> bytes:
        token = os.urandom(wire.SESSION_TOKEN_LEN)
        self._sessions[token] = (pubkey, time.time() + defaults.SESSION_TTL_S)
        return token

    def get_session(self, token: Optional[bytes]) -> Optional[bytes]:
        if token is None:
            return None
        entry = self._sessions.get(bytes(token))
        if entry is None or entry[1] < time.time():
            self._sessions.pop(bytes(token), None)
            return None
        return entry[0]


class Connections:
    """client-id -> WS push sink registry (server/src/ws.rs:73-109)."""

    def __init__(self):
        self._socks: Dict[bytes, web.WebSocketResponse] = {}

    def register(self, client_id: bytes, ws: web.WebSocketResponse) -> None:
        self._socks[bytes(client_id)] = ws
        _CONNECTED.set(len(self._socks))

    def unregister(self, client_id: bytes, ws: web.WebSocketResponse) -> None:
        if self._socks.get(bytes(client_id)) is ws:
            self._socks.pop(bytes(client_id), None)
        _CONNECTED.set(len(self._socks))

    def count(self) -> int:
        return len(self._socks)

    def is_online(self, client_id: bytes) -> bool:
        return bytes(client_id) in self._socks

    async def notify(self, client_id: bytes, msg: wire.JsonMessage) -> bool:
        ws = self._socks.get(bytes(client_id))
        if ws is None or ws.closed:
            return False
        try:
            await ws.send_str(msg.to_json())
            return True
        except (ConnectionError, RuntimeError):
            self._socks.pop(bytes(client_id), None)
            return False


class StorageQueue:
    """The original single-lock matchmaking economy (backup_request.rs):
    an expiring list of (client, bytes-wanted) fulfilled by pairing
    clients with each other.

    Retained as the measured baseline for the sharded matchmaker
    (``CoordinationServer(legacy=True)``, bench config ``12_swarm``) and
    because its semantics tests pin the matchmaking contract both
    implementations honor.  Structural costs, by design: ``_lock`` is
    held across the WHOLE fulfill — db writes and WS pushes included —
    and expiry rescans the list front on every pop."""

    def __init__(self, db, connections: Connections,
                 expiry_s: float = None):
        self.db = db
        self.connections = connections
        self.expiry_s = (defaults.BACKUP_REQUEST_EXPIRY_S
                         if expiry_s is None else expiry_s)
        self._queue: list = []  # (client_id, remaining, expires_at)
        self._lock = asyncio.Lock()

    def _pop_valid(self) -> Optional[tuple]:
        now = time.time()
        while self._queue:
            client, remaining, expires = self._queue.pop(0)
            if expires >= now and self.connections.is_online(client):
                return client, remaining, expires
        return None

    async def fulfill(self, client_id: bytes, storage_required: int,
                      min_peers: int = 1) -> None:
        """Match against queued requests; both sides get BackupMatched for
        min(remaining, candidate); remainders re-enqueue
        (backup_request.rs:73-185).

        ``min_peers > 1`` is the erasure-stripe hint: the requester wants
        its grant spread over at least that many DISTINCT peers (a stripe
        needs k+m holders), so each match is capped at an even share
        instead of letting one storage-rich candidate swallow the whole
        request.  The cap only applies while the queue holds enough other
        candidates to plausibly reach the spread — with a shallower queue
        it falls back to greedy matching, so 2–3-client deployments see
        exactly the pre-erasure behavior.
        """
        if storage_required > defaults.MAX_BACKUP_STORAGE_REQUEST_SIZE:
            raise ValueError("storage request exceeds protocol cap")
        min_peers = max(int(min_peers), 1)
        async with self._lock:
            share_cap = None
            if min_peers > 1:
                others = {c for c, _r, _e in self._queue
                          if c != bytes(client_id)}
                if len(others) >= min_peers:
                    share_cap = -(-storage_required // min_peers)
            remaining = storage_required
            while remaining > 0:
                entry = self._pop_valid()
                if entry is None:
                    break
                candidate, cand_remaining, cand_expires = entry
                if candidate == bytes(client_id):
                    continue  # self-match discarded
                if self.db.audit_failing_reporters(
                        candidate, defaults.AUDIT_REPORT_WINDOW_S) \
                        >= defaults.AUDIT_SERVER_BLOCK_FAILURES:
                    # Independently reported as failing storage audits:
                    # drop its queued request rather than hand it new data.
                    continue
                match = min(remaining, cand_remaining)
                if share_cap is not None:
                    match = min(match, share_cap)
                # Record the negotiation FIRST, then push: a client must
                # never learn of a match the server does not persist (a
                # notified candidate would start treating the requester as a
                # negotiated peer while get_client_negotiated_peers denies
                # it).  A failed candidate push rolls the record back; the
                # reference instead records after notify
                # (backup_request.rs:95-139) and carries that window.
                # Known residual window: a server CRASH between the save and
                # the notify leaves a phantom record neither client knows
                # about.  That is harmless on the send path (the peer simply
                # never dials) and tolerated on restore: the phantom peer
                # refuses the dial as an unknown peer, and the client
                # proceeds anyway when the data from the remaining peers
                # covers the snapshot (engine._restored_coverage_gap).
                self.db.save_storage_negotiated(bytes(client_id), candidate,
                                                match)
                self.db.save_storage_negotiated(candidate, bytes(client_id),
                                                match)
                ok_cand = await self.connections.notify(
                    candidate, wire.BackupMatched(
                        destination_id=bytes(client_id),
                        storage_available=match))
                if not ok_cand:
                    # Candidate unreachable: roll back, drop its queued
                    # request, and try the next one
                    # (backup_request.rs:166-173).
                    self.db.delete_storage_negotiated(
                        bytes(client_id), candidate, match)
                    self.db.delete_storage_negotiated(
                        candidate, bytes(client_id), match)
                    continue
                _MATCHMAKINGS.inc()
                ok_self = await self.connections.notify(
                    bytes(client_id), wire.BackupMatched(
                        destination_id=candidate, storage_available=match))
                if not ok_self:
                    # The requester is unreachable but the candidate has
                    # already been told: keep the record (both sides stay
                    # consistent; the requester discovers the peer on its
                    # next restore/reconnect), re-enqueue the candidate's
                    # remainder, and stop matching for the dead requester.
                    cand_remaining -= match
                    if cand_remaining > 0:
                        self._queue.append((candidate, cand_remaining,
                                            cand_expires))
                    return
                remaining -= match
                cand_remaining -= match
                if cand_remaining > 0:
                    self._queue.append((candidate, cand_remaining,
                                        cand_expires))
            if remaining > 0:
                self._queue.append((bytes(client_id), remaining,
                                    time.time() + self.expiry_s))
            _QUEUE_DEPTH.set(len(self._queue))

    def pending(self) -> int:
        depth = len(self._queue)
        _QUEUE_DEPTH.set(depth)  # point-in-time refresh for scrapers
        return depth


@web.middleware
async def _obs_middleware(request, handler):
    """Per-request observability: count and time by canonical route
    (bounded label cardinality — the route table, not raw paths) and
    adopt the client's trace id from the POST JSON so the server-side
    span journals under the same id as the caller's.  The latency lands
    in ``bkw_server_request_seconds{route}``; the swarm scorecard and
    bench config 12 read their p99 from its buckets."""
    resource = request.match_info.route.resource
    path = resource.canonical if resource is not None else request.path
    _REQUESTS.inc(path=path)
    trace_id = None
    if request.method == "POST" and request.can_read_body:
        try:
            # request.text() caches: handlers re-read the same body
            trace_id = json.loads(await request.text()).get("trace_id")
        except (ValueError, UnicodeDecodeError):
            pass
    t0 = time.monotonic()
    try:
        with obs_trace.bind(trace_id), obs_trace.span(f"server{path}"):
            return await handler(request)
    finally:
        _REQUEST_SECONDS.observe(time.monotonic() - t0, route=path)


class CoordinationServer:
    """The stateless request tier.

    Handlers keep no cross-request state beyond the auth/session maps
    and the live WS registry; persistent state is behind ``self.db`` (a
    :class:`~.serverstore.ServerStore`) and queueing behind
    ``self.queue``.  Durable writes go through ``self.db.aio`` — in the
    default write-behind store the await resolves only after the group
    commit, so the durability-promising responses (registration, login
    bookkeeping, snapshot registration, audit/repair verdicts,
    negotiation records) are acknowledged only once committed, without
    ever running a sqlite commit on the event loop.

    ``legacy=True`` assembles the pre-PR-10 single-lock shape over a
    direct-commit store — the bench baseline.  ``store=`` injects any
    other :class:`~.serverstore.ServerStore` implementation.
    """

    def __init__(self, db_path=":memory:", store: Optional[ServerStore] = None,
                 legacy: bool = False, shards: Optional[int] = None):
        if store is None:
            store = (ServerDB(db_path) if legacy
                     else SqliteServerStore(db_path))
        self.db = store
        self.legacy = bool(legacy)
        self.auth = AuthManager()
        self.connections = Connections()
        if legacy:
            self.queue = StorageQueue(self.db, self.connections)
        else:
            self.queue = ShardedMatchmaker(self.db, self.connections,
                                           shards=shards)
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None
        self._started = time.time()

    # --- helpers -----------------------------------------------------------

    _STATUS_EXC = {400: web.HTTPBadRequest, 401: web.HTTPUnauthorized,
                   404: web.HTTPNotFound, 409: web.HTTPConflict,
                   500: web.HTTPInternalServerError}

    @staticmethod
    def _err(kind: str, detail: str = "",
             status: Optional[int] = None) -> web.HTTPException:
        """Typed error response: one of the 8 wire.ErrorKind payloads at
        its mapped HTTP status (handlers/mod.rs:50-91)."""
        status = status or wire.ERROR_HTTP_STATUS[kind]
        exc = CoordinationServer._STATUS_EXC[status]
        return exc(text=wire.Error(kind=kind, detail=detail).to_json(),
                   content_type="application/json")

    def _session(self, msg) -> bytes:
        client = self.auth.get_session(msg.session_token)
        if client is None:
            raise self._err(wire.ErrorKind.UNAUTHORIZED)
        return client

    @staticmethod
    async def _parse(request, cls):
        try:
            msg = wire.JsonMessage.from_json(await request.text())
        except (ValueError, KeyError) as e:
            raise CoordinationServer._err(wire.ErrorKind.BAD_REQUEST, str(e))
        if not isinstance(msg, cls):
            raise CoordinationServer._err(
                wire.ErrorKind.BAD_REQUEST, f"expected {cls.__name__}")
        return msg

    @staticmethod
    def _ok(msg: wire.JsonMessage = None) -> web.Response:
        return web.Response(text=(msg or wire.Ok()).to_json(),
                            content_type="application/json")

    # --- handlers (server/src/handlers/) -----------------------------------

    async def register_begin(self, request):
        msg = await self._parse(request, wire.ClientRegistrationRequest)
        return self._ok(wire.ServerChallenge(
            nonce=self.auth.challenge_begin(msg.pubkey)))

    async def register_complete(self, request):
        msg = await self._parse(request, wire.ClientRegistrationAuth)
        nonce = self.auth.take_challenge(msg.pubkey)
        if nonce is None:
            # expired/unknown challenge: the client should restart the
            # flow (ChallengeNotFound -> Retry, handlers/mod.rs:73)
            raise self._err(wire.ErrorKind.RETRY)
        if not verify_signature(msg.pubkey, nonce, msg.challenge_response):
            raise self._err(wire.ErrorKind.BAD_REQUEST, "bad signature")
        if await self.db.aio.client_exists(msg.pubkey):
            # 409 CONFLICT with a BadRequest payload (ClientExists,
            # handlers/mod.rs:66,79)
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "client already exists", status=409)
        await self.db.aio.register_client(msg.pubkey)
        return self._ok()

    async def login_begin(self, request):
        msg = await self._parse(request, wire.ClientLoginRequest)
        if not await self.db.aio.client_exists(msg.pubkey):
            raise self._err(wire.ErrorKind.CLIENT_NOT_FOUND)
        return self._ok(wire.ServerChallenge(
            nonce=self.auth.challenge_begin(msg.pubkey)))

    async def login_complete(self, request):
        msg = await self._parse(request, wire.ClientLoginAuth)
        nonce = self.auth.take_challenge(msg.pubkey)
        if nonce is None:
            raise self._err(wire.ErrorKind.RETRY)
        if not verify_signature(msg.pubkey, nonce, msg.challenge_response):
            raise self._err(wire.ErrorKind.BAD_REQUEST, "bad signature")
        await self.db.aio.client_update_logged_in(msg.pubkey)
        return self._ok(wire.LoginToken(token=self.auth.session_start(msg.pubkey)))

    async def backup_request(self, request):
        msg = await self._parse(request, wire.BackupRequest)
        client = self._session(msg)
        try:
            await self.queue.fulfill(client, msg.storage_required,
                                     min_peers=msg.min_peers)
        except ValueError as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        return self._ok()

    async def backup_done(self, request):
        msg = await self._parse(request, wire.BackupDone)
        client = self._session(msg)
        await self.db.aio.save_snapshot(client, msg.snapshot_hash)
        return self._ok()

    async def backup_restore(self, request):
        msg = await self._parse(request, wire.BackupRestoreRequest)
        client = self._session(msg)
        snapshot = await self.db.aio.get_latest_client_snapshot(client)
        if snapshot is None:
            # NoBackupsAvailable -> 404 NoBackups (handlers/backup.rs:30-38)
            raise self._err(wire.ErrorKind.NO_BACKUPS)
        peers = await self.db.aio.get_client_negotiated_peers(client)
        # advertise the deployment's stripe geometry so a from-scratch
        # restore client knows how many peer streams can go dark before
        # coverage is actually at risk (the shard containers themselves
        # are self-describing; this is advisory)
        return self._ok(wire.BackupRestoreInfo(
            snapshot_hash=snapshot, peers=[p.hex() for p in peers],
            rs_k=defaults.RS_K, rs_m=defaults.RS_M))

    async def p2p_begin(self, request):
        msg = await self._parse(request, wire.BeginP2PConnectionRequest)
        client = self._session(msg)
        delivered = await self.connections.notify(
            msg.destination_client_id, wire.IncomingP2PConnection(
                source_client_id=client, session_nonce=msg.session_nonce))
        if not delivered:
            raise self._err(wire.ErrorKind.DESTINATION_UNREACHABLE)
        return self._ok()

    async def p2p_confirm(self, request):
        msg = await self._parse(request, wire.ConfirmP2PConnectionRequest)
        client = self._session(msg)
        delivered = await self.connections.notify(
            msg.source_client_id, wire.FinalizeP2PConnection(
                destination_client_id=client,
                destination_ip_address=msg.destination_ip_address))
        if not delivered:
            raise self._err(wire.ErrorKind.DESTINATION_UNREACHABLE)
        return self._ok()

    async def audit_report(self, request):
        """Record one client's audit verdict on a peer; on failure, nudge
        every other client storing on that peer to audit it soon (the
        server never sees data, only verdicts — SURVEY.md §1 holds)."""
        msg = await self._parse(request, wire.AuditReport)
        client = self._session(msg)
        peer = bytes(msg.peer_id)
        await self.db.aio.save_audit_report(client, peer, bool(msg.passed),
                                            msg.detail or "")
        if not msg.passed:
            for source in await self.db.aio.get_clients_storing_on(peer):
                if source not in (client, peer):
                    await self.connections.notify(
                        source, wire.AuditDue(peer_id=peer))
        return self._ok()

    async def repair_report(self, request):
        """Record a completed peer-loss repair and reclaim the negotiation
        edges between the reporter and the lost peer, so the reporter's
        restore peer list drops the dead peer immediately.  Only the
        reporter's own edges are touched — other clients keep their own
        view of the peer until their own audits/repairs decide."""
        msg = await self._parse(request, wire.RepairReport)
        client = self._session(msg)
        peer = bytes(msg.peer_id)
        if peer == client:
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "cannot repair away from self")
        await self.db.aio.save_repair_report(client, peer, msg.packfiles_lost,
                                             msg.bytes_lost,
                                             msg.bytes_replaced)
        await self.db.aio.reclaim_negotiation(client, peer)
        return self._ok()

    # --- observability exposition (obs/expo.py) -----------------------------

    async def metrics(self, _request):
        self.queue.pending()  # refresh the queue-depth gauge
        _CONNECTED.set(self.connections.count())
        return obs_expo.metrics_response()

    async def healthz(self, _request):
        """Liveness plus the durability invariant summary.  The summary
        aggregates every InvariantMonitor publishing into this process's
        registry — all zeros / ``ok`` for a standalone server (the
        server never sees client placement state), and the live
        cross-client durability picture when clients are colocated (the
        scenario harness, tests, bench).  A violated invariant turns
        the whole document 503 (obs/expo.py)."""
        durability = obs_invariants.summary_from_registry()
        return obs_expo.health_response(
            schema_version=await self.db.aio.schema_version(),
            queue_depth=self.queue.pending(),
            connected_clients=self.connections.count(),
            uptime_s=round(time.time() - self._started, 3),
            durability=durability,
            status=durability["status"])

    async def ws(self, request):
        token = request.headers.get("Authorization")
        try:
            token_bytes = bytes.fromhex(token) if token else None
        except ValueError:
            raise self._err(wire.ErrorKind.UNAUTHORIZED, "malformed token")
        client = self.auth.get_session(token_bytes)
        if client is None:
            raise self._err(wire.ErrorKind.UNAUTHORIZED)
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        self.connections.register(client, ws)
        try:
            async for msg in ws:
                if msg.type in (WSMsgType.ERROR, WSMsgType.CLOSE):
                    break
        finally:
            self.connections.unregister(client, ws)
        return ws

    # --- lifecycle ---------------------------------------------------------

    def app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 20,
                              middlewares=[_obs_middleware])
        app.add_routes([
            web.get("/metrics", self.metrics),
            web.get("/healthz", self.healthz),
            web.post("/register/begin", self.register_begin),
            web.post("/register/complete", self.register_complete),
            web.post("/login/begin", self.login_begin),
            web.post("/login/complete", self.login_complete),
            web.post("/backups/request", self.backup_request),
            web.post("/backups/done", self.backup_done),
            web.post("/backups/restore", self.backup_restore),
            web.post("/p2p/connection/begin", self.p2p_begin),
            web.post("/p2p/connection/confirm", self.p2p_confirm),
            web.post("/audit/report", self.audit_report),
            web.post("/repair/report", self.repair_report),
            web.get("/ws", self.ws),
        ])
        return app

    async def start(self, host="127.0.0.1", port=0,
                    ssl_context=None) -> int:
        """Serve; with ``ssl_context`` the control plane is HTTPS/WSS (the
        reference is TLS-by-default with a USE_TLS off-switch for local
        testing, requests.rs:246-258, docs/src/client.md:22)."""
        self._runner = web.AppRunner(self.app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port, ssl_context=ssl_context)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        # drain + retire the writer thread; the store stays readable
        # (tests inspect server.db after stop)
        self.db.close()
