"""Coordination server: identity, matchmaking, rendezvous, snapshot registry.

Re-designs the reference server (``server/src/``) on aiohttp.  The control
plane never touches backup data (SURVEY.md §1): it does

* **challenge-response auth** on Ed25519 client keys — 30 s challenge TTL,
  24 h session TTL (``client_auth_manager.rs:17-20,49-101``),
* **storage-request matchmaking** — an expiring queue; ``fulfill`` pops
  candidates, matches ``min(remaining, candidate)``, notifies both clients
  over their push channels, records the negotiation in both directions, and
  re-enqueues remainders (``backup_request.rs:73-185``),
* **P2P rendezvous relay** — forwards connection requests/confirmations
  between clients (``handlers/p2p_connection_request.rs``),
* **snapshot registry** — latest snapshot hash per client plus the peer
  list needed for restore (``db.rs:129-187``, ``handlers/backup.rs``).

Persistent state lives in SQLite (the reference uses Postgres via sqlx;
an embedded store keeps the framework self-contained — the schema mirrors
``server/schema/schema.sql``).
"""

from __future__ import annotations

import asyncio
import os
import sqlite3
import time
from typing import Dict, Optional

import json

from aiohttp import WSMsgType, web

from .. import defaults, wire
from ..crypto import verify_signature
from ..obs import expo as obs_expo
from ..obs import invariants as obs_invariants
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

_REQUESTS = obs_metrics.counter(
    "bkw_server_requests_total", "Coordination-server requests by route",
    ("path",))
_QUEUE_DEPTH = obs_metrics.gauge(
    "bkw_matchmaking_queue_depth",
    "Storage requests waiting in the matchmaking queue")
_CONNECTED = obs_metrics.gauge(
    "bkw_server_connected_clients", "Clients on the WS push channel")

# Families the clients of this process produce into; declared here too
# (get-or-create merges them) so a standalone server's /metrics always
# advertises the core catalog even before any client code is imported.
obs_metrics.histogram("bkw_transfer_send_seconds",
                      "Seconds spent in ws.send + ack per transfer")
obs_metrics.counter("bkw_audit_total", "Audit verdicts by outcome",
                    ("outcome",))
obs_metrics.counter("bkw_repair_rounds_total", "Peer-loss repair rounds run")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS clients (
    pubkey BLOB PRIMARY KEY,
    registered REAL NOT NULL,
    last_login REAL
);
CREATE TABLE IF NOT EXISTS peer_backups (
    source BLOB NOT NULL,
    destination BLOB NOT NULL,
    size_negotiated INTEGER NOT NULL,
    timestamp REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS snapshots (
    client_pubkey BLOB NOT NULL,
    snapshot_hash BLOB NOT NULL,
    timestamp REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS snapshots_by_client
    ON snapshots (client_pubkey, timestamp);
CREATE TABLE IF NOT EXISTS audit_reports (
    reporter BLOB NOT NULL,
    peer BLOB NOT NULL,
    passed INTEGER NOT NULL,
    detail TEXT NOT NULL DEFAULT '',
    timestamp REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS audit_reports_by_peer
    ON audit_reports (peer, timestamp);
CREATE TABLE IF NOT EXISTS repair_reports (
    reporter BLOB NOT NULL,
    peer BLOB NOT NULL,
    packfiles_lost INTEGER NOT NULL,
    bytes_lost INTEGER NOT NULL,
    bytes_replaced INTEGER NOT NULL,
    timestamp REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS metadata (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL
);
"""

#: Bump when the schema changes shape; pre-versioning databases (PR 1 and
#: earlier, which had no ``metadata`` table) count as version 1.
SCHEMA_VERSION = 2

#: THE migration seam: ``{from_version: [SQL statements]}`` applied in
#: sequence by :meth:`ServerDB._migrate` to reach ``from_version + 1``.
#: Statements must be idempotent (IF NOT EXISTS / OR IGNORE) because a
#: crash between a migration and the version stamp replays it on the next
#: boot.  A Postgres twin of ServerDB would run the same ladder.
_MIGRATIONS = {
    # v1 (PR 1) -> v2: repair_reports + the metadata table itself.  Both
    # already appear in _SCHEMA's CREATE IF NOT EXISTS, so this rung is
    # empty — it exists to document the pattern for the next real change.
    1: [],
}


class ServerDB:
    """server/src/db.rs equivalent (embedded SQLite).

    The reference runs the coordination schema on Postgres
    (``server/src/db.rs:12-40``); here it is embedded.  Concurrency
    envelope, documented deliberately: WAL mode gives concurrent readers
    with a single writer, and every write the coordination plane makes
    (client registration, storage-request rows, negotiation records) is a
    sub-millisecond single-row statement at human backup cadence — orders
    of magnitude under SQLite's write ceiling.  The seam for a
    server-farm deployment is this class: it is the only component that
    touches the database, so a Postgres-backed twin can replace it
    without touching handlers.
    """

    def __init__(self, path):
        self._db = sqlite3.connect(path, check_same_thread=False)
        if path != ":memory:":
            self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.executescript(_SCHEMA)
        self._db.commit()
        self._migrate()

    def _migrate(self) -> None:
        """Boot-time schema version check (VERDICT r5 missing #3).

        * fresh or pre-versioning database -> run the ladder from v1 and
          stamp :data:`SCHEMA_VERSION` (the _SCHEMA script is idempotent,
          so replaying it on a v1 database upgrades it in place);
        * versioned database older than the code -> apply each rung of
          :data:`_MIGRATIONS` in order, stamping after each one;
        * database NEWER than the code -> refuse to start: old code
          writing rows a newer schema reinterprets is silent corruption.
        """
        row = self._db.execute(
            "SELECT value FROM metadata WHERE key = 'schema_version'"
        ).fetchone()
        version = int(row[0]) if row is not None else 1
        if version > SCHEMA_VERSION:
            raise RuntimeError(
                f"database schema v{version} is newer than this server"
                f" (v{SCHEMA_VERSION}); upgrade the server binary")
        while version < SCHEMA_VERSION:
            for stmt in _MIGRATIONS.get(version, ()):
                self._db.execute(stmt)
            version += 1
            self._db.execute(
                "INSERT INTO metadata (key, value) VALUES"
                " ('schema_version', ?) ON CONFLICT(key)"
                " DO UPDATE SET value = excluded.value", (str(version),))
            self._db.commit()
        if row is None:
            self._db.execute(
                "INSERT OR IGNORE INTO metadata (key, value) VALUES"
                " ('schema_version', ?)", (str(SCHEMA_VERSION),))
            self._db.commit()

    def schema_version(self) -> int:
        row = self._db.execute(
            "SELECT value FROM metadata WHERE key = 'schema_version'"
        ).fetchone()
        return int(row[0])

    def register_client(self, pubkey: bytes) -> None:
        self._db.execute(
            "INSERT OR IGNORE INTO clients (pubkey, registered) VALUES (?, ?)",
            (pubkey, time.time()))
        self._db.commit()

    def client_exists(self, pubkey: bytes) -> bool:
        return self._db.execute("SELECT 1 FROM clients WHERE pubkey = ?",
                                (pubkey,)).fetchone() is not None

    def client_update_logged_in(self, pubkey: bytes) -> None:
        self._db.execute("UPDATE clients SET last_login = ? WHERE pubkey = ?",
                         (time.time(), pubkey))
        self._db.commit()

    def save_storage_negotiated(self, source: bytes, destination: bytes,
                                size: int) -> None:
        self._db.execute(
            "INSERT INTO peer_backups (source, destination, size_negotiated,"
            " timestamp) VALUES (?, ?, ?, ?)",
            (source, destination, size, time.time()))
        self._db.commit()

    def delete_storage_negotiated(self, source: bytes, destination: bytes,
                                  size: int) -> None:
        """Roll back one just-recorded negotiation (failed-push compensation
        in StorageQueue.fulfill)."""
        self._db.execute(
            "DELETE FROM peer_backups WHERE rowid = ("
            " SELECT rowid FROM peer_backups WHERE source = ?"
            " AND destination = ? AND size_negotiated = ?"
            " ORDER BY timestamp DESC LIMIT 1)",
            (source, destination, size))
        self._db.commit()

    def save_snapshot(self, pubkey: bytes, snapshot_hash: bytes) -> None:
        self._db.execute(
            "INSERT INTO snapshots (client_pubkey, snapshot_hash, timestamp)"
            " VALUES (?, ?, ?)", (pubkey, snapshot_hash, time.time()))
        self._db.commit()

    def get_latest_client_snapshot(self, pubkey: bytes) -> Optional[bytes]:
        row = self._db.execute(
            "SELECT snapshot_hash FROM snapshots WHERE client_pubkey = ?"
            " ORDER BY timestamp DESC LIMIT 1", (pubkey,)).fetchone()
        return None if row is None else bytes(row[0])

    def get_client_negotiated_peers(self, pubkey: bytes) -> list:
        rows = self._db.execute(
            "SELECT DISTINCT destination FROM peer_backups WHERE source = ?",
            (pubkey,)).fetchall()
        return [bytes(r[0]) for r in rows]

    def get_clients_storing_on(self, pubkey: bytes) -> list:
        """Sources with data on ``pubkey`` (the reverse negotiation edge)."""
        rows = self._db.execute(
            "SELECT DISTINCT source FROM peer_backups WHERE destination = ?",
            (pubkey,)).fetchall()
        return [bytes(r[0]) for r in rows]

    def save_audit_report(self, reporter: bytes, peer: bytes, passed: bool,
                          detail: str) -> None:
        self._db.execute(
            "INSERT INTO audit_reports (reporter, peer, passed, detail,"
            " timestamp) VALUES (?, ?, ?, ?, ?)",
            (reporter, peer, int(passed), detail, time.time()))
        self._db.commit()

    def save_repair_report(self, reporter: bytes, peer: bytes,
                           packfiles_lost: int, bytes_lost: int,
                           bytes_replaced: int) -> None:
        self._db.execute(
            "INSERT INTO repair_reports (reporter, peer, packfiles_lost,"
            " bytes_lost, bytes_replaced, timestamp) VALUES (?, ?, ?, ?, ?, ?)",
            (reporter, peer, int(packfiles_lost), int(bytes_lost),
             int(bytes_replaced), time.time()))
        self._db.commit()

    def reclaim_negotiation(self, client: bytes, peer: bytes) -> int:
        """Retire every negotiation edge between ``client`` and a lost
        ``peer`` (both directions): the allowance is unusable, and restore
        peer lists must stop naming the dead peer.  Returns rows removed."""
        cur = self._db.execute(
            "DELETE FROM peer_backups WHERE (source = ? AND destination = ?)"
            " OR (source = ? AND destination = ?)",
            (client, peer, peer, client))
        self._db.commit()
        return cur.rowcount

    def audit_failing_reporters(self, peer: bytes,
                                window_s: float) -> int:
        """Distinct reporters whose LATEST report on ``peer`` within the
        window is a failure.  A later pass from the same reporter clears
        its vote, so a recovered peer re-enters matchmaking without any
        server-side state surgery."""
        rows = self._db.execute(
            "SELECT reporter, passed FROM audit_reports"
            " WHERE peer = ? AND timestamp >= ? ORDER BY timestamp",
            (peer, time.time() - window_s)).fetchall()
        latest: Dict[bytes, int] = {}
        for reporter, passed in rows:
            latest[bytes(reporter)] = passed
        return sum(1 for passed in latest.values() if not passed)


class AuthManager:
    """Challenges (30 s) and session tokens (24 h) with expiry
    (client_auth_manager.rs)."""

    def __init__(self):
        self._challenges: Dict[bytes, tuple] = {}  # pubkey -> (nonce, expiry)
        self._sessions: Dict[bytes, tuple] = {}  # token -> (pubkey, expiry)

    def challenge_begin(self, pubkey: bytes) -> bytes:
        nonce = os.urandom(wire.CHALLENGE_NONCE_LEN)
        self._challenges[pubkey] = (
            nonce, time.time() + defaults.AUTH_CHALLENGE_TTL_S)
        return nonce

    def take_challenge(self, pubkey: bytes) -> Optional[bytes]:
        """Pop a live challenge nonce; None when absent/expired (the
        reference distinguishes ChallengeNotFound -> Retry from a bad
        signature -> BadRequest, handlers/mod.rs:52-76)."""
        entry = self._challenges.pop(pubkey, None)
        if entry is None or entry[1] < time.time():
            return None
        return entry[0]

    def session_start(self, pubkey: bytes) -> bytes:
        token = os.urandom(wire.SESSION_TOKEN_LEN)
        self._sessions[token] = (pubkey, time.time() + defaults.SESSION_TTL_S)
        return token

    def get_session(self, token: Optional[bytes]) -> Optional[bytes]:
        if token is None:
            return None
        entry = self._sessions.get(bytes(token))
        if entry is None or entry[1] < time.time():
            self._sessions.pop(bytes(token), None)
            return None
        return entry[0]


class Connections:
    """client-id -> WS push sink registry (server/src/ws.rs:73-109)."""

    def __init__(self):
        self._socks: Dict[bytes, web.WebSocketResponse] = {}

    def register(self, client_id: bytes, ws: web.WebSocketResponse) -> None:
        self._socks[bytes(client_id)] = ws
        _CONNECTED.set(len(self._socks))

    def unregister(self, client_id: bytes, ws: web.WebSocketResponse) -> None:
        if self._socks.get(bytes(client_id)) is ws:
            self._socks.pop(bytes(client_id), None)
        _CONNECTED.set(len(self._socks))

    def count(self) -> int:
        return len(self._socks)

    def is_online(self, client_id: bytes) -> bool:
        return bytes(client_id) in self._socks

    async def notify(self, client_id: bytes, msg: wire.JsonMessage) -> bool:
        ws = self._socks.get(bytes(client_id))
        if ws is None or ws.closed:
            return False
        try:
            await ws.send_str(msg.to_json())
            return True
        except (ConnectionError, RuntimeError):
            self._socks.pop(bytes(client_id), None)
            return False


class StorageQueue:
    """The matchmaking economy (backup_request.rs): an expiring queue of
    (client, bytes-wanted) fulfilled by pairing clients with each other."""

    def __init__(self, db: ServerDB, connections: Connections,
                 expiry_s: float = defaults.BACKUP_REQUEST_EXPIRY_S):
        self.db = db
        self.connections = connections
        self.expiry_s = expiry_s
        self._queue: list = []  # (client_id, remaining, expires_at)
        self._lock = asyncio.Lock()

    def _pop_valid(self) -> Optional[tuple]:
        now = time.time()
        while self._queue:
            client, remaining, expires = self._queue.pop(0)
            if expires >= now and self.connections.is_online(client):
                return client, remaining, expires
        return None

    async def fulfill(self, client_id: bytes, storage_required: int,
                      min_peers: int = 1) -> None:
        """Match against queued requests; both sides get BackupMatched for
        min(remaining, candidate); remainders re-enqueue
        (backup_request.rs:73-185).

        ``min_peers > 1`` is the erasure-stripe hint: the requester wants
        its grant spread over at least that many DISTINCT peers (a stripe
        needs k+m holders), so each match is capped at an even share
        instead of letting one storage-rich candidate swallow the whole
        request.  The cap only applies while the queue holds enough other
        candidates to plausibly reach the spread — with a shallower queue
        it falls back to greedy matching, so 2–3-client deployments see
        exactly the pre-erasure behavior.
        """
        if storage_required > defaults.MAX_BACKUP_STORAGE_REQUEST_SIZE:
            raise ValueError("storage request exceeds protocol cap")
        min_peers = max(int(min_peers), 1)
        async with self._lock:
            share_cap = None
            if min_peers > 1:
                others = {c for c, _r, _e in self._queue
                          if c != bytes(client_id)}
                if len(others) >= min_peers:
                    share_cap = -(-storage_required // min_peers)
            remaining = storage_required
            while remaining > 0:
                entry = self._pop_valid()
                if entry is None:
                    break
                candidate, cand_remaining, cand_expires = entry
                if candidate == bytes(client_id):
                    continue  # self-match discarded
                if self.db.audit_failing_reporters(
                        candidate, defaults.AUDIT_REPORT_WINDOW_S) \
                        >= defaults.AUDIT_SERVER_BLOCK_FAILURES:
                    # Independently reported as failing storage audits:
                    # drop its queued request rather than hand it new data.
                    continue
                match = min(remaining, cand_remaining)
                if share_cap is not None:
                    match = min(match, share_cap)
                # Record the negotiation FIRST, then push: a client must
                # never learn of a match the server does not persist (a
                # notified candidate would start treating the requester as a
                # negotiated peer while get_client_negotiated_peers denies
                # it).  A failed candidate push rolls the record back; the
                # reference instead records after notify
                # (backup_request.rs:95-139) and carries that window.
                # Known residual window: a server CRASH between the save and
                # the notify leaves a phantom record neither client knows
                # about.  That is harmless on the send path (the peer simply
                # never dials) and tolerated on restore: the phantom peer
                # refuses the dial as an unknown peer, and the client
                # proceeds anyway when the data from the remaining peers
                # covers the snapshot (engine._restored_coverage_gap).
                self.db.save_storage_negotiated(bytes(client_id), candidate,
                                                match)
                self.db.save_storage_negotiated(candidate, bytes(client_id),
                                                match)
                ok_cand = await self.connections.notify(
                    candidate, wire.BackupMatched(
                        destination_id=bytes(client_id),
                        storage_available=match))
                if not ok_cand:
                    # Candidate unreachable: roll back, drop its queued
                    # request, and try the next one
                    # (backup_request.rs:166-173).
                    self.db.delete_storage_negotiated(
                        bytes(client_id), candidate, match)
                    self.db.delete_storage_negotiated(
                        candidate, bytes(client_id), match)
                    continue
                ok_self = await self.connections.notify(
                    bytes(client_id), wire.BackupMatched(
                        destination_id=candidate, storage_available=match))
                if not ok_self:
                    # The requester is unreachable but the candidate has
                    # already been told: keep the record (both sides stay
                    # consistent; the requester discovers the peer on its
                    # next restore/reconnect), re-enqueue the candidate's
                    # remainder, and stop matching for the dead requester.
                    cand_remaining -= match
                    if cand_remaining > 0:
                        self._queue.append((candidate, cand_remaining,
                                            cand_expires))
                    return
                remaining -= match
                cand_remaining -= match
                if cand_remaining > 0:
                    self._queue.append((candidate, cand_remaining,
                                        cand_expires))
            if remaining > 0:
                self._queue.append((bytes(client_id), remaining,
                                    time.time() + self.expiry_s))
            _QUEUE_DEPTH.set(len(self._queue))

    def pending(self) -> int:
        depth = len(self._queue)
        _QUEUE_DEPTH.set(depth)  # point-in-time refresh for scrapers
        return depth


@web.middleware
async def _obs_middleware(request, handler):
    """Per-request observability: count by canonical route (bounded label
    cardinality) and adopt the client's trace id from the POST JSON so
    the server-side span journals under the same id as the caller's."""
    resource = request.match_info.route.resource
    path = resource.canonical if resource is not None else request.path
    _REQUESTS.inc(path=path)
    trace_id = None
    if request.method == "POST" and request.can_read_body:
        try:
            # request.text() caches: handlers re-read the same body
            trace_id = json.loads(await request.text()).get("trace_id")
        except (ValueError, UnicodeDecodeError):
            pass
    with obs_trace.bind(trace_id), obs_trace.span(f"server{path}"):
        return await handler(request)


class CoordinationServer:
    def __init__(self, db_path=":memory:"):
        self.db = ServerDB(db_path)
        self.auth = AuthManager()
        self.connections = Connections()
        self.queue = StorageQueue(self.db, self.connections)
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None
        self._started = time.time()

    # --- helpers -----------------------------------------------------------

    _STATUS_EXC = {400: web.HTTPBadRequest, 401: web.HTTPUnauthorized,
                   404: web.HTTPNotFound, 409: web.HTTPConflict,
                   500: web.HTTPInternalServerError}

    @staticmethod
    def _err(kind: str, detail: str = "",
             status: Optional[int] = None) -> web.HTTPException:
        """Typed error response: one of the 8 wire.ErrorKind payloads at
        its mapped HTTP status (handlers/mod.rs:50-91)."""
        status = status or wire.ERROR_HTTP_STATUS[kind]
        exc = CoordinationServer._STATUS_EXC[status]
        return exc(text=wire.Error(kind=kind, detail=detail).to_json(),
                   content_type="application/json")

    def _session(self, msg) -> bytes:
        client = self.auth.get_session(msg.session_token)
        if client is None:
            raise self._err(wire.ErrorKind.UNAUTHORIZED)
        return client

    @staticmethod
    async def _parse(request, cls):
        try:
            msg = wire.JsonMessage.from_json(await request.text())
        except (ValueError, KeyError) as e:
            raise CoordinationServer._err(wire.ErrorKind.BAD_REQUEST, str(e))
        if not isinstance(msg, cls):
            raise CoordinationServer._err(
                wire.ErrorKind.BAD_REQUEST, f"expected {cls.__name__}")
        return msg

    @staticmethod
    def _ok(msg: wire.JsonMessage = None) -> web.Response:
        return web.Response(text=(msg or wire.Ok()).to_json(),
                            content_type="application/json")

    # --- handlers (server/src/handlers/) -----------------------------------

    async def register_begin(self, request):
        msg = await self._parse(request, wire.ClientRegistrationRequest)
        return self._ok(wire.ServerChallenge(
            nonce=self.auth.challenge_begin(msg.pubkey)))

    async def register_complete(self, request):
        msg = await self._parse(request, wire.ClientRegistrationAuth)
        nonce = self.auth.take_challenge(msg.pubkey)
        if nonce is None:
            # expired/unknown challenge: the client should restart the
            # flow (ChallengeNotFound -> Retry, handlers/mod.rs:73)
            raise self._err(wire.ErrorKind.RETRY)
        if not verify_signature(msg.pubkey, nonce, msg.challenge_response):
            raise self._err(wire.ErrorKind.BAD_REQUEST, "bad signature")
        if self.db.client_exists(msg.pubkey):
            # 409 CONFLICT with a BadRequest payload (ClientExists,
            # handlers/mod.rs:66,79)
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "client already exists", status=409)
        self.db.register_client(msg.pubkey)
        return self._ok()

    async def login_begin(self, request):
        msg = await self._parse(request, wire.ClientLoginRequest)
        if not self.db.client_exists(msg.pubkey):
            raise self._err(wire.ErrorKind.CLIENT_NOT_FOUND)
        return self._ok(wire.ServerChallenge(
            nonce=self.auth.challenge_begin(msg.pubkey)))

    async def login_complete(self, request):
        msg = await self._parse(request, wire.ClientLoginAuth)
        nonce = self.auth.take_challenge(msg.pubkey)
        if nonce is None:
            raise self._err(wire.ErrorKind.RETRY)
        if not verify_signature(msg.pubkey, nonce, msg.challenge_response):
            raise self._err(wire.ErrorKind.BAD_REQUEST, "bad signature")
        self.db.client_update_logged_in(msg.pubkey)
        return self._ok(wire.LoginToken(token=self.auth.session_start(msg.pubkey)))

    async def backup_request(self, request):
        msg = await self._parse(request, wire.BackupRequest)
        client = self._session(msg)
        try:
            await self.queue.fulfill(client, msg.storage_required,
                                     min_peers=msg.min_peers)
        except ValueError as e:
            raise self._err(wire.ErrorKind.BAD_REQUEST, str(e))
        return self._ok()

    async def backup_done(self, request):
        msg = await self._parse(request, wire.BackupDone)
        client = self._session(msg)
        self.db.save_snapshot(client, msg.snapshot_hash)
        return self._ok()

    async def backup_restore(self, request):
        msg = await self._parse(request, wire.BackupRestoreRequest)
        client = self._session(msg)
        snapshot = self.db.get_latest_client_snapshot(client)
        if snapshot is None:
            # NoBackupsAvailable -> 404 NoBackups (handlers/backup.rs:30-38)
            raise self._err(wire.ErrorKind.NO_BACKUPS)
        peers = self.db.get_client_negotiated_peers(client)
        # advertise the deployment's stripe geometry so a from-scratch
        # restore client knows how many peer streams can go dark before
        # coverage is actually at risk (the shard containers themselves
        # are self-describing; this is advisory)
        return self._ok(wire.BackupRestoreInfo(
            snapshot_hash=snapshot, peers=[p.hex() for p in peers],
            rs_k=defaults.RS_K, rs_m=defaults.RS_M))

    async def p2p_begin(self, request):
        msg = await self._parse(request, wire.BeginP2PConnectionRequest)
        client = self._session(msg)
        delivered = await self.connections.notify(
            msg.destination_client_id, wire.IncomingP2PConnection(
                source_client_id=client, session_nonce=msg.session_nonce))
        if not delivered:
            raise self._err(wire.ErrorKind.DESTINATION_UNREACHABLE)
        return self._ok()

    async def p2p_confirm(self, request):
        msg = await self._parse(request, wire.ConfirmP2PConnectionRequest)
        client = self._session(msg)
        delivered = await self.connections.notify(
            msg.source_client_id, wire.FinalizeP2PConnection(
                destination_client_id=client,
                destination_ip_address=msg.destination_ip_address))
        if not delivered:
            raise self._err(wire.ErrorKind.DESTINATION_UNREACHABLE)
        return self._ok()

    async def audit_report(self, request):
        """Record one client's audit verdict on a peer; on failure, nudge
        every other client storing on that peer to audit it soon (the
        server never sees data, only verdicts — SURVEY.md §1 holds)."""
        msg = await self._parse(request, wire.AuditReport)
        client = self._session(msg)
        peer = bytes(msg.peer_id)
        self.db.save_audit_report(client, peer, bool(msg.passed),
                                  msg.detail or "")
        if not msg.passed:
            for source in self.db.get_clients_storing_on(peer):
                if source not in (client, peer):
                    await self.connections.notify(
                        source, wire.AuditDue(peer_id=peer))
        return self._ok()

    async def repair_report(self, request):
        """Record a completed peer-loss repair and reclaim the negotiation
        edges between the reporter and the lost peer, so the reporter's
        restore peer list drops the dead peer immediately.  Only the
        reporter's own edges are touched — other clients keep their own
        view of the peer until their own audits/repairs decide."""
        msg = await self._parse(request, wire.RepairReport)
        client = self._session(msg)
        peer = bytes(msg.peer_id)
        if peer == client:
            raise self._err(wire.ErrorKind.BAD_REQUEST,
                            "cannot repair away from self")
        self.db.save_repair_report(client, peer, msg.packfiles_lost,
                                   msg.bytes_lost, msg.bytes_replaced)
        self.db.reclaim_negotiation(client, peer)
        return self._ok()

    # --- observability exposition (obs/expo.py) -----------------------------

    async def metrics(self, _request):
        self.queue.pending()  # refresh the queue-depth gauge
        _CONNECTED.set(self.connections.count())
        return obs_expo.metrics_response()

    async def healthz(self, _request):
        """Liveness plus the durability invariant summary.  The summary
        aggregates every InvariantMonitor publishing into this process's
        registry — all zeros / ``ok`` for a standalone server (the
        server never sees client placement state), and the live
        cross-client durability picture when clients are colocated (the
        scenario harness, tests, bench).  A violated invariant turns
        the whole document 503 (obs/expo.py)."""
        durability = obs_invariants.summary_from_registry()
        return obs_expo.health_response(
            schema_version=self.db.schema_version(),
            queue_depth=self.queue.pending(),
            connected_clients=self.connections.count(),
            uptime_s=round(time.time() - self._started, 3),
            durability=durability,
            status=durability["status"])

    async def ws(self, request):
        token = request.headers.get("Authorization")
        try:
            token_bytes = bytes.fromhex(token) if token else None
        except ValueError:
            raise self._err(wire.ErrorKind.UNAUTHORIZED, "malformed token")
        client = self.auth.get_session(token_bytes)
        if client is None:
            raise self._err(wire.ErrorKind.UNAUTHORIZED)
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        self.connections.register(client, ws)
        try:
            async for msg in ws:
                if msg.type in (WSMsgType.ERROR, WSMsgType.CLOSE):
                    break
        finally:
            self.connections.unregister(client, ws)
        return ws

    # --- lifecycle ---------------------------------------------------------

    def app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 20,
                              middlewares=[_obs_middleware])
        app.add_routes([
            web.get("/metrics", self.metrics),
            web.get("/healthz", self.healthz),
            web.post("/register/begin", self.register_begin),
            web.post("/register/complete", self.register_complete),
            web.post("/login/begin", self.login_begin),
            web.post("/login/complete", self.login_complete),
            web.post("/backups/request", self.backup_request),
            web.post("/backups/done", self.backup_done),
            web.post("/backups/restore", self.backup_restore),
            web.post("/p2p/connection/begin", self.p2p_begin),
            web.post("/p2p/connection/confirm", self.p2p_confirm),
            web.post("/audit/report", self.audit_report),
            web.post("/repair/report", self.repair_report),
            web.get("/ws", self.ws),
        ])
        return app

    async def start(self, host="127.0.0.1", port=0,
                    ssl_context=None) -> int:
        """Serve; with ``ssl_context`` the control plane is HTTPS/WSS (the
        reference is TLS-by-default with a USE_TLS off-switch for local
        testing, requests.rs:246-258, docs/src/client.md:22)."""
        self._runner = web.AppRunner(self.app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port, ssl_context=ssl_context)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
