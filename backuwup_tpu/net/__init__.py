"""Networking: coordination server, client control plane, P2P data plane."""
