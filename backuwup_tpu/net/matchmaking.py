"""Sharded in-memory matchmaking (the PR-10 scale-out of StorageQueue).

The original ``StorageQueue`` serializes the whole economy behind ONE
``asyncio.Lock`` held across the entire fulfill — including the
negotiation db writes and both WebSocket pushes — and expires entries by
rescanning a python list.  :class:`ShardedMatchmaker` keeps the exact
matchmaking semantics (see below) but restructures the state for
contention:

* **N pubkey-keyed shards** — a queued request lives in its owner's home
  shard (``shard = int.from_bytes(pubkey[:8]) % N``).  Each shard has
  its own lock, FIFO deque, and entry table.
* **per-shard locks, never held across an await** — a lock guards only
  the O(1)/O(log n) pops and pushes; the db writes and client pushes of
  a match run lock-free, so concurrent fulfills from different clients
  overlap their I/O instead of queueing behind one critical section.
* **O(log n) expiry via deadline heaps** — each shard keeps a
  ``(expires_at, seq)`` min-heap beside the FIFO; reaping pops only
  expired heads (heap pops, no rescans).  ``reap_ops`` counts heap
  operations so the test can assert the bound.
* **cross-shard work stealing** — fulfill starts at the requester's home
  shard and walks the ring, so a deep queue on one shard still fulfills
  requesters homed anywhere.

Preserved semantics (tests/test_control_plane.py, test_audit.py,
test_erasure.py pin these on the legacy queue; the sharded tests mirror
them):

* FIFO within a shard; expired and offline entries are dropped at pop;
* a popped self-match is discarded, not re-enqueued;
* candidates audit-blocked by ≥ ``AUDIT_SERVER_BLOCK_FAILURES`` distinct
  failing reporters are dropped;
* the negotiation is recorded FIRST, then pushed: a candidate-push
  failure rolls both records back and drops the candidate; a
  requester-push failure keeps the records, re-enqueues the candidate's
  remainder, and stops matching for the dead requester;
* ``min_peers > 1`` caps each match at an even share while enough
  distinct other clients are queued to plausibly reach the spread.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import defaults, wire
from ..obs import metrics as obs_metrics
from ..utils import clock as clockmod

_QUEUE_DEPTH = obs_metrics.gauge(
    "bkw_matchmaking_queue_depth",
    "Storage requests waiting in the matchmaking queue")
_MATCHMAKINGS = obs_metrics.counter(
    "bkw_matchmakings_total",
    "Matchmaking pairings recorded (negotiation persisted, candidate"
    " notified)")
_EXPIRED = obs_metrics.counter(
    "bkw_matchmaking_expired_total",
    "Queued storage requests dropped by deadline-heap expiry")


class _Shard:
    """One matchmaking shard: FIFO + deadline heap over an entry table.

    ``entries`` maps a monotonically increasing ``seq`` to a live
    ``[client_id, remaining, expires_at]`` record; the FIFO and the heap
    hold seqs (possibly stale — a seq missing from ``entries`` was
    consumed or reaped and is skipped at pop, each skip O(1)).
    """

    def __init__(self, index: int):
        self.index = index
        self.lock = asyncio.Lock()
        self.entries: Dict[int, list] = {}
        self.fifo: deque = deque()
        self.heap: List[Tuple[float, int]] = []
        self.reap_ops = 0  # deadline-heap pops (the O(log n) evidence)

    def add(self, seq: int, client: bytes, remaining: int,
            expires_at: float) -> None:
        self.entries[seq] = [client, remaining, expires_at]
        self.fifo.append(seq)
        heapq.heappush(self.heap, (expires_at, seq))

    def reap(self, now: float) -> int:
        """Drop every expired entry by popping the deadline heap — no
        scan of live entries.  Returns the number dropped."""
        dropped = 0
        while self.heap and self.heap[0][0] < now:
            _expires, seq = heapq.heappop(self.heap)
            self.reap_ops += 1
            if seq in self.entries:
                del self.entries[seq]
                dropped += 1
        if dropped:
            _EXPIRED.inc(dropped)
        return dropped

    def pop(self, now: float, connections) -> Optional[list]:
        """Oldest live entry whose client is online; offline entries are
        dropped (legacy ``_pop_valid`` semantics)."""
        self.reap(now)
        while self.fifo:
            seq = self.fifo.popleft()
            entry = self.entries.pop(seq, None)
            if entry is None:
                continue  # stale seq: consumed or reaped
            if connections.is_online(entry[0]):
                return entry
        return None

    def depth(self) -> int:
        return len(self.entries)


class ShardedMatchmaker:
    """Drop-in for ``StorageQueue`` in the stateless request tier; the
    durable negotiation writes go through ``store.aio`` so the event
    loop never waits on a commit it didn't have to."""

    def __init__(self, store, connections,
                 expiry_s: Optional[float] = None,
                 shards: Optional[int] = None,
                 clock=None):
        self.db = store
        self.connections = connections
        self.clock = clockmod.resolve(clock)
        self.expiry_s = (defaults.BACKUP_REQUEST_EXPIRY_S
                         if expiry_s is None else expiry_s)
        n = defaults.MATCHMAKING_SHARDS if not shards else int(shards)
        self.shards = [_Shard(i) for i in range(max(n, 1))]
        self._seq = itertools.count(1)
        #: Federation hook (docs/server.md §Federation): an async
        #: ``(requester, want, share_cap) -> Optional[(candidate, match)]``
        #: consulted only after every LOCAL shard came up empty — the
        #: remote continuation of the home-shard-last steal walk.  The
        #: serving node records the negotiation (both edges) and pushes
        #: to the candidate before answering, so by the time this
        #: returns, only the requester-side push remains.  None = no
        #: federation (single-node deployments) or no remote candidate.
        self.remote_steal = None

    # --- shard routing ------------------------------------------------------

    def shard_of(self, client_id: bytes) -> _Shard:
        key = int.from_bytes(bytes(client_id)[:8] or b"\0", "big")
        return self.shards[key % len(self.shards)]

    def _enqueue(self, client_id: bytes, remaining: int,
                 expires_at: float) -> None:
        self.shard_of(client_id).add(next(self._seq), bytes(client_id),
                                     remaining, expires_at)

    def _distinct_others(self, client_id: bytes) -> int:
        me = bytes(client_id)
        return len({e[0] for s in self.shards for e in s.entries.values()
                    if e[0] != me})

    async def _pop_candidate(self, requester: bytes) -> Optional[list]:
        """Steal work around the ring starting at the shard AFTER the
        requester's home and visiting home last: the requester's own
        queued remainders live in its home shard, and popping them first
        would discard them as self-matches far more often than the
        legacy global FIFO ever did (measured: it halves the match rate
        under uniform load).  The shard lock covers only the pop
        itself."""
        now = self.clock.now()
        home = self.shard_of(requester).index
        n = len(self.shards)
        for i in range(1, n + 1):
            shard = self.shards[(home + i) % n]
            async with shard.lock:
                while True:
                    entry = shard.pop(now, self.connections)
                    if entry is None:
                        break
                    if entry[0] == bytes(requester):
                        continue  # self-match discarded
                    return entry
        return None

    # --- the economy --------------------------------------------------------

    async def fulfill(self, client_id: bytes, storage_required: int,
                      min_peers: int = 1) -> None:
        """Match against queued requests; both sides get BackupMatched
        for min(remaining, candidate); remainders re-enqueue.  Semantics
        mirror ``StorageQueue.fulfill`` (see the module docstring); the
        structural difference is that no lock is held across the store
        writes or the pushes, so fulfills for different clients overlap.

        Two concurrent fulfills can no longer observe each other's
        half-made matches through a shared critical section — but they
        never could observe anything useful there either: every pop
        removes the entry before any await, so each queued request still
        has exactly one consumer.
        """
        if storage_required > defaults.MAX_BACKUP_STORAGE_REQUEST_SIZE:
            raise ValueError("storage request exceeds protocol cap")
        me = bytes(client_id)
        min_peers = max(int(min_peers), 1)
        share_cap = None
        if min_peers > 1 and self._distinct_others(me) >= min_peers:
            share_cap = -(-storage_required // min_peers)
        remaining = storage_required
        while remaining > 0:
            entry = await self._pop_candidate(me)
            if entry is None:
                # Every local shard is empty: go remote (federation's
                # continuation of the home-last walk).  The serving node
                # has already recorded the negotiation and notified the
                # candidate, so only the requester-side push remains —
                # and a failed requester push keeps the records and
                # stops, exactly the legacy requester-dead semantics.
                if self.remote_steal is None:
                    break
                stolen = await self.remote_steal(me, remaining, share_cap)
                if stolen is None:
                    break
                r_candidate, r_match = stolen
                ok_self = await self.connections.notify(
                    me, wire.BackupMatched(destination_id=r_candidate,
                                           storage_available=r_match))
                if not ok_self:
                    self._refresh_depth()
                    return
                remaining -= r_match
                continue
            candidate, cand_remaining, cand_expires = entry
            if await self.db.aio.audit_failing_reporters(
                    candidate, defaults.AUDIT_REPORT_WINDOW_S) \
                    >= defaults.AUDIT_SERVER_BLOCK_FAILURES:
                # independently reported as failing storage audits: drop
                # its queued request rather than hand it new data
                continue
            match = min(remaining, cand_remaining)
            if share_cap is not None:
                match = min(match, share_cap)
            # Record FIRST, then push (the legacy invariant): a client
            # must never learn of a match the server does not persist.
            # The awaits resolve only after the write-behind group
            # commit, so the durability barrier holds per match.
            await self.db.aio.save_storage_negotiated(me, candidate, match)
            await self.db.aio.save_storage_negotiated(candidate, me, match)
            ok_cand = await self.connections.notify(
                candidate, wire.BackupMatched(
                    destination_id=me, storage_available=match))
            if not ok_cand:
                # candidate unreachable: roll back, drop its queued
                # request, and try the next one
                await self.db.aio.delete_storage_negotiated(
                    me, candidate, match)
                await self.db.aio.delete_storage_negotiated(
                    candidate, me, match)
                continue
            _MATCHMAKINGS.inc()
            ok_self = await self.connections.notify(
                me, wire.BackupMatched(
                    destination_id=candidate, storage_available=match))
            if not ok_self:
                # the requester is unreachable but the candidate has
                # already been told: keep the record, re-enqueue the
                # candidate's remainder, stop matching for the dead
                # requester
                cand_remaining -= match
                if cand_remaining > 0:
                    shard = self.shard_of(candidate)
                    async with shard.lock:
                        shard.add(next(self._seq), candidate,
                                  cand_remaining, cand_expires)
                self._refresh_depth()
                return
            remaining -= match
            cand_remaining -= match
            if cand_remaining > 0:
                shard = self.shard_of(candidate)
                async with shard.lock:
                    shard.add(next(self._seq), candidate, cand_remaining,
                              cand_expires)
        if remaining > 0:
            shard = self.shard_of(me)
            async with shard.lock:
                shard.add(next(self._seq), me, remaining,
                          self.clock.now() + self.expiry_s)
        self._refresh_depth()

    async def serve_steal(self, requester: bytes, want: int,
                          share_cap: Optional[int] = None
                          ) -> Optional[Tuple[bytes, int]]:
        """Serve one cross-node steal (the /fed/steal RPC body): pop a
        local candidate for a REMOTE requester, record the negotiation,
        and push to the (locally connected) candidate.

        This is one iteration of :meth:`fulfill` with the requester-side
        push left to the requester's own node — the candidate-side
        invariants are identical: audit-blocked candidates dropped,
        record-first-then-push, a failed candidate push rolls both edges
        back and tries the next candidate, remainders re-enqueue, and
        ``_MATCHMAKINGS`` counts here (the serving side) only, so a
        pairing is counted exactly once across the federation.

        Returns ``(candidate_pubkey, matched_bytes)`` or None when no
        eligible local candidate exists.
        """
        me = bytes(requester)
        want = int(want)
        while True:
            entry = await self._pop_candidate(me)
            if entry is None:
                return None
            candidate, cand_remaining, cand_expires = entry
            if await self.db.aio.audit_failing_reporters(
                    candidate, defaults.AUDIT_REPORT_WINDOW_S) \
                    >= defaults.AUDIT_SERVER_BLOCK_FAILURES:
                continue
            match = min(want, cand_remaining)
            if share_cap is not None:
                match = min(match, int(share_cap))
            # Both edges recorded by the serving node (the store routes
            # each by pubkey, so placement is identical to a local
            # fulfill) — keeping record-then-push atomic on one node
            # instead of splitting the rollback across the RPC.
            await self.db.aio.save_storage_negotiated(me, candidate, match)
            await self.db.aio.save_storage_negotiated(candidate, me, match)
            ok_cand = await self.connections.notify(
                candidate, wire.BackupMatched(
                    destination_id=me, storage_available=match))
            if not ok_cand:
                await self.db.aio.delete_storage_negotiated(
                    me, candidate, match)
                await self.db.aio.delete_storage_negotiated(
                    candidate, me, match)
                continue
            _MATCHMAKINGS.inc()
            cand_remaining -= match
            if cand_remaining > 0:
                shard = self.shard_of(candidate)
                async with shard.lock:
                    shard.add(next(self._seq), candidate, cand_remaining,
                              cand_expires)
            self._refresh_depth()
            return candidate, match

    # --- introspection ------------------------------------------------------

    def _refresh_depth(self) -> int:
        depth = sum(s.depth() for s in self.shards)
        _QUEUE_DEPTH.set(depth)
        return depth

    def pending(self) -> int:
        """Live queued requests (expired entries reaped first).  Safe to
        call from sync code: every lock-guarded critical section in this
        class is await-free, so no coroutine can be mid-mutation while
        sync code runs on the loop."""
        now = self.clock.now()
        for shard in self.shards:
            shard.reap(now)
        return self._refresh_depth()

    def reap_ops(self) -> int:
        """Total deadline-heap pops across shards (test instrumentation
        for the O(log n) expiry bound)."""
        return sum(s.reap_ops for s in self.shards)
