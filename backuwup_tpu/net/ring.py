"""Consistent-hash ring router for the federated coordination plane.

No reference equivalent — the reference server is one process over one
Postgres.  Here the coordination plane goes horizontal: N nodes each run
the stateless request tier (net/server.py) over a shared pubkey-keyed
store, and this module decides *which* node owns a pubkey.

Design (docs/server.md §Federation):

* Each node contributes ``vnodes`` points on a 64-bit ring, at
  ``blake2b(f"{node_id}:{i}")`` — deterministic, so every node (and
  every client shipped the node list) computes the identical ring with
  no coordination traffic.
* ``owner(key)`` hashes the key onto the ring and walks clockwise to
  the first point (bisect over the sorted point list, O(log n·v)).
* Bounded movement: removing a node deletes only its own points, so
  exactly the keys it owned move (to their ring successors); adding a
  node claims ~1/N of the keyspace and moves nothing else.  The ring
  ownership-stability tests in tests/test_federation.py pin both.
* ``steal_order(node)`` federates the in-process steal semantics of
  ``ShardedMatchmaker._pop_candidate`` (home shard LAST): by the time a
  node goes remote it has already walked all of its local shards, so
  the remote order is simply the other nodes in ring-successor order
  starting after ``node`` — deterministic, and adjacent nodes (which
  absorb each other's keys on failure) are tried first.

``partition_of`` maps pubkeys to store partitions with the same prefix
convention as ``ShardedMatchmaker.shard_of`` — partition count is a file
-layout constant, NOT the ring (nodes come and go; partitions don't).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence

from .. import defaults

__all__ = ["HashRing", "partition_of", "partition_key", "successors"]


def _point(label: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(label.encode(), digest_size=8).digest(), "big")


def _key_point(key: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(bytes(key), digest_size=8).digest(), "big")


def partition_of(pubkey: bytes, partitions: int) -> int:
    """Store partition index for ``pubkey`` (same convention as
    ``ShardedMatchmaker.shard_of``: big-endian 8-byte prefix, modulo)."""
    prefix = bytes(pubkey)[:8] or b"\x00"
    return int.from_bytes(prefix, "big") % max(1, int(partitions))


def partition_key(partition: int) -> bytes:
    """Deterministic ring key for a store partition *index*.

    Replication homes whole partitions (a file-layout unit), not
    individual pubkeys, so each partition needs one stable ring position
    every node computes identically.  Hashing the label keeps partition
    placement independent of the pubkey distribution."""
    return hashlib.blake2b(b"bkw-partition:%d" % int(partition),
                           digest_size=16).digest()


def successors(ring: "HashRing", partition: int,
               count: Optional[int] = None) -> List[str]:
    """The replication chain for ``partition``: ring-successor nodes
    after its owner, most-senior first, capped at ``count``
    (``defaults.REPL_SUCCESSORS``).  Empty when the ring has one node
    (standalone mode: no one to ship to)."""
    owner = ring.owner(partition_key(partition))
    if owner is None:
        return []
    limit = defaults.REPL_SUCCESSORS if count is None else int(count)
    return ring.steal_order(owner)[:max(0, limit)]


class HashRing:
    """Deterministic consistent-hash ring: pubkey -> owning node id."""

    def __init__(self, nodes: Sequence[str] = (),
                 vnodes: Optional[int] = None):
        self.vnodes = int(vnodes or defaults.FEDERATION_RING_VNODES)
        self._points: List[int] = []        # sorted ring positions
        self._owners: Dict[int, str] = {}   # position -> node id
        for node in nodes:
            self.add(node)

    def add(self, node_id: str) -> None:
        if node_id in self.nodes():
            return
        for i in range(self.vnodes):
            pt = _point(f"{node_id}:{i}")
            # blake2b collisions across distinct labels are not a
            # realistic event; first writer keeps the point.
            if pt in self._owners:
                continue
            self._owners[pt] = node_id
            bisect.insort(self._points, pt)

    def remove(self, node_id: str) -> None:
        mine = [pt for pt, n in self._owners.items() if n == node_id]
        for pt in mine:
            del self._owners[pt]
            idx = bisect.bisect_left(self._points, pt)
            del self._points[idx]

    def nodes(self) -> List[str]:
        """All node ids, in ring order of their first point."""
        seen: List[str] = []
        for pt in self._points:
            n = self._owners[pt]
            if n not in seen:
                seen.append(n)
        return seen

    def __len__(self) -> int:
        return len(self.nodes())

    def owner(self, key: bytes) -> Optional[str]:
        if not self._points:
            return None
        idx = bisect.bisect_right(self._points, _key_point(key))
        if idx == len(self._points):
            idx = 0
        return self._owners[self._points[idx]]

    def steal_order(self, node_id: str) -> List[str]:
        """Other nodes in ring-successor order starting after
        ``node_id`` — the federated continuation of the in-process
        home-shard-last walk (``node_id`` itself is excluded: its local
        shards were already drained before going remote)."""
        order = self.nodes()
        if node_id not in order:
            return order
        at = order.index(node_id)
        return order[at + 1:] + order[:at]
