"""P2P data plane: signed, replay-protected, acked peer-to-peer transfer.

Re-designs ``client/src/net_p2p/``: all backup bytes move client<->client
over WebSocket, end-to-end authenticated:

* Every message is an :class:`~backuwup_tpu.wire.EncapsulatedMsg` — an
  Ed25519-signed :class:`~backuwup_tpu.wire.P2PBody` carrying a replay
  header (random 16-byte session nonce + strictly-sequential sequence
  number, ``p2p_message.rs:21-24``, ``receive.rs:95-105``).
* Connections rendezvous through the coordination server: the initiator
  registers a nonce (60 s expiry, ``p2p_connection_manager.rs``), the
  acceptor binds a random port and confirms its address, the initiator
  dials and sends the signed seq-0 request (``handle_connections.rs``).
* Per-file acks with timeouts (``transport.rs:127-128``); packfiles are
  deleted by the sender only after the ack (``send.rs:277-289``).
* Hosts store received packfiles XOR-obfuscated with a local 4-byte key so
  a casual host can't read foreign (already encrypted) packfiles
  (``received_files_writer.rs:76-78``); quota = negotiated − received with
  a 16 MiB grace (``:101-108``).
"""

from __future__ import annotations

import asyncio
import os
import time
from pathlib import Path
from typing import Callable, Dict, Optional

import numpy as np

try:
    import websockets
except ModuleNotFoundError:  # containers without the wheel: aiohttp shim
    from ..utils import ws_compat as websockets

from .. import defaults, wire
from ..crypto import KeyManager, verify_signature
from ..obs import trace as obs_trace
from ..store import Store
from ..utils import faults, retry

PURPOSE_TRANSPORT = wire.RequestType.TRANSPORT
PURPOSE_RESTORE = wire.RequestType.RESTORE_ALL
PURPOSE_AUDIT = wire.RequestType.AUDIT


class P2PError(Exception):
    pass


def obfuscate(data: bytes, key: bytes) -> bytes:
    """XOR with a repeating 4-byte key (net_p2p/mod.rs:38-47); involutive."""
    if len(key) != 4:
        raise ValueError("obfuscation key must be 4 bytes")
    arr = np.frombuffer(bytes(data), dtype=np.uint8)
    pad = -len(arr) % 4
    if pad:
        arr = np.concatenate([arr, np.zeros(pad, np.uint8)])
    k = np.frombuffer(bytes(key) * (len(arr) // 4), dtype=np.uint8)
    out = (arr ^ k).tobytes()
    return out[:len(data)]


class ConnectionRequests:
    """Outgoing-request registry: anti-unsolicited-connection bookkeeping
    with expiry (p2p_connection_manager.rs:17-66)."""

    def __init__(self, ttl_s: float = defaults.P2P_REQUEST_TTL_S):
        self.ttl_s = ttl_s
        self._pending: Dict[bytes, tuple] = {}  # peer -> (nonce, purpose, exp)

    def add(self, peer_id: bytes, purpose: wire.RequestType) -> bytes:
        nonce = os.urandom(wire.TRANSPORT_NONCE_LEN)
        self._pending[bytes(peer_id)] = (nonce, purpose,
                                         time.time() + self.ttl_s)
        return nonce

    def finalize(self, peer_id: bytes) -> tuple:
        entry = self._pending.pop(bytes(peer_id), None)
        if entry is None or entry[2] < time.time():
            raise P2PError("no pending connection request for peer")
        return entry[0], entry[1]


def _sign_body(keys: KeyManager, body: wire.P2PBody) -> bytes:
    encoded = body.encode_bytes()
    # the caller's trace id rides outside the signed body (advisory
    # correlation metadata — see wire.EncapsulatedMsg)
    return wire.EncapsulatedMsg(
        body=encoded, signature=keys.sign(encoded),
        trace_id=obs_trace.current_trace_id()).encode_bytes()


def _verify_msg(raw: bytes, peer_id: bytes) -> wire.P2PBody:
    if len(raw) > defaults.MAX_P2P_MESSAGE_SIZE:
        raise P2PError("p2p message exceeds size cap")
    msg = wire.EncapsulatedMsg.decode_bytes(raw)
    if not verify_signature(peer_id, msg.body, msg.signature):
        raise P2PError("bad message signature")
    body = wire.P2PBody.decode_bytes(msg.body)
    # ride the sender's trace id alongside the body (frozen dataclass:
    # a side-channel attribute, never part of equality or encoding)
    object.__setattr__(body, "trace_id",
                       obs_trace.clean_trace_id(msg.trace_id))
    return body


class Transport:
    """Send side: ordered, signed, acked file transfer (transport.rs)."""

    def __init__(self, ws, keys: KeyManager, peer_id: bytes,
                 session_nonce: bytes, first_seq: int = 1):
        self.ws = ws
        self.keys = keys
        self.peer_id = bytes(peer_id)
        self.session_nonce = bytes(session_nonce)
        self.seq = first_seq
        self._acks: Dict[int, asyncio.Event] = {}
        self._ack_task: Optional[asyncio.Task] = None
        self._recv_queue: asyncio.Queue = asyncio.Queue()

    def start(self) -> None:
        if self._ack_task is None:
            self._ack_task = asyncio.create_task(self._listen())

    async def _listen(self) -> None:
        """Verify + route incoming frames: acks release waiting senders,
        data frames queue for the receive loop (duplex socket)."""
        try:
            async for raw in self.ws:
                try:
                    body = _verify_msg(raw, self.peer_id)
                except P2PError:
                    continue
                if body.header.session_nonce != self.session_nonce:
                    continue
                if body.kind == wire.P2PBodyKind.ACK:
                    ev = self._acks.get(body.acked_sequence)
                    if ev is not None:
                        ev.set()
                else:
                    await self._recv_queue.put(body)
        except websockets.ConnectionClosed:
            pass
        finally:
            # put_nowait (queue is unbounded): the await form would fail
            # with "Event loop is closed" when the task is GC'd at
            # interpreter/loop teardown
            try:
                self._recv_queue.put_nowait(None)
            except RuntimeError:
                pass

    async def send_data(self, data: bytes, file_info: wire.FileInfoKind,
                        file_id: bytes) -> None:
        """Send one file; waits for the signed ack (transport.rs:111-132)."""
        seq = self.seq
        self.seq += 1
        body = wire.P2PBody(
            kind=wire.P2PBodyKind.FILE,
            header=wire.P2PHeader(sequence_number=seq,
                                  session_nonce=self.session_nonce),
            file_info=file_info, file_id=bytes(file_id), data=bytes(data))
        ev = asyncio.Event()
        self._acks[seq] = ev
        raw = _sign_body(self.keys, body)
        plane = faults.PLANE
        if plane is not None:  # chaos hook; inert in production (PLANE=None)
            action = await plane.on_send(self.peer_id)
            if action == faults.ACT_DROP:
                await self.close()
                self._acks.pop(seq, None)
                raise P2PError(f"injected connection drop at seq {seq}")
            if action == faults.ACT_CORRUPT:
                raw = plane.corrupt(raw, self.peer_id)
        try:
            await asyncio.wait_for(self.ws.send(raw),
                                   defaults.PACKFILE_SEND_TIMEOUT_S)
            await asyncio.wait_for(ev.wait(), defaults.ACK_TIMEOUT_S)
        except (asyncio.TimeoutError, websockets.ConnectionClosed) as e:
            raise P2PError(f"send/ack failed for seq {seq}: {e}") from e
        finally:
            self._acks.pop(seq, None)

    async def send_body(self, body: wire.P2PBody) -> None:
        """Fire one signed non-FILE body (audit challenge/proof exchange —
        correlation is by echoed sequence number, not per-frame acks)."""
        try:
            await asyncio.wait_for(self.ws.send(_sign_body(self.keys, body)),
                                   defaults.PACKFILE_SEND_TIMEOUT_S)
        except (asyncio.TimeoutError, websockets.ConnectionClosed) as e:
            raise P2PError(f"send failed: {e}") from e

    async def recv_body(self, timeout: float) -> wire.P2PBody:
        """Next verified non-ACK body from the peer (None sentinel on close
        becomes an error: callers always expect a concrete body)."""
        try:
            body = await asyncio.wait_for(self._recv_queue.get(), timeout)
        except asyncio.TimeoutError as e:
            raise P2PError("timed out waiting for peer body") from e
        if body is None:
            raise P2PError("connection closed while waiting for peer body")
        return body

    async def close(self) -> None:
        if self._ack_task is not None:
            self._ack_task.cancel()
        try:
            await self.ws.close()
        except Exception:
            pass


class Receiver:
    """Receive side: strict-sequence validation + signed acks (receive.rs).

    ``sink(file_info, file_id, data)`` persists one file; the loop ends when
    the peer closes the socket.
    """

    def __init__(self, transport: Transport, sink: Callable,
                 first_seq: int = 1):
        self.t = transport
        self.sink = sink
        self.expected_seq = first_seq

    async def run(self) -> int:
        """Returns the number of files received."""
        count = 0
        while True:
            body = await self.t._recv_queue.get()
            if body is None:
                return count
            if body.kind != wire.P2PBodyKind.FILE:
                continue
            if body.header.sequence_number != self.expected_seq:
                raise P2PError(
                    f"sequence break: got {body.header.sequence_number}, "
                    f"expected {self.expected_seq} (replay protection)")
            # adopt the sender's trace id so this store joins its pack/
            # transfer spans in the journal (the acceptance chain)
            with obs_trace.bind(getattr(body, "trace_id", None)), \
                    obs_trace.span("receiver.store"):
                await self.sink(body.file_info, body.file_id, body.data)
            plane = faults.PLANE
            if plane is not None \
                    and plane.withhold_ack_now(self.t.peer_id):
                # injected crash-between-write-and-ack: the file is
                # persisted but the sender never learns; do NOT advance
                # expected_seq — a real crash would lose that state too
                continue
            ack = wire.P2PBody(
                kind=wire.P2PBodyKind.ACK,
                header=wire.P2PHeader(sequence_number=self.expected_seq,
                                      session_nonce=self.t.session_nonce),
                acked_sequence=self.expected_seq)
            await self.t.ws.send(_sign_body(self.t.keys, ack))
            self.expected_seq += 1
            count += 1


class ReceivedFilesWriter:
    """Store a peer's packfiles/indexes, obfuscated + quota-enforced
    (received_files_writer.rs)."""

    def __init__(self, store: Store, peer_id: bytes):
        self.store = store
        self.peer_id = bytes(peer_id)
        self.dir = store.received_dir(peer_id)
        key = store.get_obfuscation_key()
        if key is None:
            raise P2PError("obfuscation key not initialized")
        self.key = key

    def _quota_left(self) -> int:
        peer = self.store.get_peer(self.peer_id)
        negotiated = peer.bytes_negotiated if peer else 0
        received = peer.bytes_received if peer else 0
        return negotiated - received + defaults.PEER_OVERUSE_GRACE

    async def sink(self, file_info: wire.FileInfoKind, file_id: bytes,
                   data: bytes) -> None:
        if file_info == wire.FileInfoKind.INDEX:
            sub = "index"
        elif file_info == wire.FileInfoKind.SHARD:
            sub = "shard"  # file_id is the 13-byte shard id
        else:
            sub = "pack"
        d = self.dir / sub
        path = d / bytes(file_id).hex()
        loop = asyncio.get_running_loop()

        def persist() -> bool:
            """Blocking disk work off the event loop (the prover may be
            mid-backup itself: a slow disk here must not stall its own
            transfer plane).  Returns True if the file was new."""
            d.mkdir(parents=True, exist_ok=True)
            if path.exists():
                # Idempotent re-send: if the sender's ack was lost (crash
                # or drop between our write and their receive) it will
                # retry the identical file on a fresh session.  Same id +
                # same bytes => ack without re-counting quota; anything
                # else is still the collision refusal
                # (received_files_writer.rs:54-56).  XOR obfuscation is
                # deterministic, so comparing stored bytes against the
                # re-obfuscated payload is exact.
                if path.read_bytes() == obfuscate(data, self.key):
                    return False
                raise P2PError(f"refusing to overwrite {path.name}"
                               " with different bytes")
            if len(data) > self._quota_left():
                raise P2PError("peer exceeded negotiated storage quota")
            path.write_bytes(obfuscate(data, self.key))
            return True

        if await loop.run_in_executor(None, persist):
            self.store.add_peer_received(self.peer_id, len(data))

    def iter_stored(self):
        """Yield (file_info, file_id, de-obfuscated bytes) of everything this
        peer stored with us — the restore-serving source (restore_send.rs)."""
        for sub, kind in (("pack", wire.FileInfoKind.PACKFILE),
                          ("shard", wire.FileInfoKind.SHARD),
                          ("index", wire.FileInfoKind.INDEX)):
            d = self.dir / sub
            if not d.is_dir():
                continue
            for f in sorted(d.iterdir()):
                yield kind, bytes.fromhex(f.name), obfuscate(f.read_bytes(),
                                                             self.key)


class RestoreFilesWriter:
    """Save own packfiles/shards coming back from a peer during restore
    (restore_files_writer.rs).  ``base`` overrides the destination tree —
    sourceless shard repair stages its survivor fetches in a scratch dir
    instead of the restore dir."""

    def __init__(self, store: Store, base: Optional[object] = None):
        self.dir = Path(base) if base is not None else store.restore_dir()
        self.files = 0

    async def sink(self, file_info: wire.FileInfoKind, file_id: bytes,
                   data: bytes) -> None:
        if file_info == wire.FileInfoKind.INDEX:
            d = self.dir / "index"
            name = f"{int.from_bytes(bytes(file_id)[:8], 'little'):06d}"
        elif file_info == wire.FileInfoKind.SHARD:
            # shard/<packfile hex>/<index>: one directory per stripe so
            # assembly (erasure/stripe.py assemble_tree) can walk it
            pid, idx = bytes(file_id)[:-1], bytes(file_id)[-1]
            d = self.dir / "shard" / pid.hex()
            name = f"{idx:03d}"
        else:
            d = self.dir / "pack" / bytes(file_id).hex()[:2]
            name = bytes(file_id).hex()
        def persist() -> None:
            d.mkdir(parents=True, exist_ok=True)
            (d / name).write_bytes(data)

        # restore pulls run one Receiver per peer concurrently; the write
        # happens off the loop so one slow disk flush never stalls the
        # other peers' frames
        await asyncio.get_running_loop().run_in_executor(None, persist)
        self.files += 1


class P2PNode:
    """Ties rendezvous + transport together for one client."""

    def __init__(self, keys: KeyManager, store: Store, server_client,
                 bind_host: str = "127.0.0.1"):
        self.keys = keys
        self.store = store
        self.server = server_client
        self.bind_host = bind_host
        self.requests = ConnectionRequests()
        self._finalize_waiters: Dict[bytes, asyncio.Queue] = {}
        self.on_transport_request: Optional[Callable] = None
        self.on_restore_request: Optional[Callable] = None
        self.on_audit_request: Optional[Callable] = None
        server_client.on_incoming_p2p = self._handle_incoming
        server_client.on_finalize_p2p = self._handle_finalize

    # --- outgoing (accept_and_connect, handle_connections.rs:94-139) -------

    async def connect(self, peer_id: bytes, purpose: wire.RequestType,
                      timeout: float = 15.0) -> Transport:
        peer_id = bytes(peer_id)
        plane = faults.PLANE
        if plane is not None and (plane.is_dead(peer_id)
                                  or plane.is_dead(self.keys.client_id)):
            # fail fast, exactly like a dial to a vanished host
            raise P2PError("injected: peer is dead")
        nonce = self.requests.add(peer_id, purpose)
        q = self._finalize_waiters.setdefault(peer_id, asyncio.Queue())
        await self.server.p2p_connection_begin(peer_id, nonce)
        try:
            addr = await asyncio.wait_for(q.get(), timeout)
        except asyncio.TimeoutError:
            raise P2PError("peer did not confirm p2p connection")
        nonce, purpose = self.requests.finalize(peer_id)

        # dial retries (handle_connections.rs:145-165) through the unified
        # retry policy: 3 dials with jittered exponential backoff
        async def _dial():
            return await websockets.connect(
                f"ws://{addr}", max_size=defaults.MAX_P2P_MESSAGE_SIZE)

        try:
            ws = await retry.retry_async(_dial, retry.DIAL,
                                         retry_on=(OSError,))
        except OSError as e:
            raise P2PError(f"could not dial peer at {addr}: {e}") from e
        init = wire.P2PBody(
            kind=wire.P2PBodyKind.REQUEST,
            header=wire.P2PHeader(sequence_number=0, session_nonce=nonce),
            request_type=purpose)
        await ws.send(_sign_body(self.keys, init))
        t = Transport(ws, self.keys, peer_id, nonce)
        t.start()
        return t

    async def _handle_finalize(self, msg: wire.FinalizeP2PConnection) -> None:
        q = self._finalize_waiters.setdefault(
            bytes(msg.destination_client_id), asyncio.Queue())
        await q.put(msg.destination_ip_address)

    # --- incoming (accept_and_listen, handle_connections.rs:30-90) ---------

    async def _handle_incoming(self, msg: wire.IncomingP2PConnection) -> None:
        source = bytes(msg.source_client_id)
        plane = faults.PLANE
        if plane is not None and plane.is_dead(self.keys.client_id):
            return  # injected death: a dead host answers no rendezvous
        if self.store.get_peer(source) is None:
            return  # unknown peer: refuse (handle_connections.rs:31-45)
        expected_nonce = msg.session_nonce
        accepted: asyncio.Queue = asyncio.Queue()

        async def handler(ws):
            try:
                raw = await asyncio.wait_for(ws.recv(), 10)
                body = _verify_msg(raw, source)
                if (body.kind != wire.P2PBodyKind.REQUEST
                        or body.header.sequence_number != 0
                        or body.header.session_nonce != expected_nonce):
                    await ws.close()
                    return
            except (P2PError, asyncio.TimeoutError,
                    websockets.ConnectionClosed):
                return
            t = Transport(ws, self.keys, source, expected_nonce)
            t.start()
            done = asyncio.Event()
            await accepted.put((body.request_type, t, done))
            await done.wait()  # keep the ws handler alive while serving

        # random high port (net_p2p/mod.rs:26-35); the outer try/finally
        # guarantees the listener is closed even if this handler task is
        # cancelled mid-await (client shutdown)
        server = await websockets.serve(
            handler, self.bind_host, 0,
            max_size=defaults.MAX_P2P_MESSAGE_SIZE)
        try:
            port = server.sockets[0].getsockname()[1]
            await self.server.p2p_connection_confirm(
                source, f"{self.bind_host}:{port}")
            try:
                request_type, transport, done = await asyncio.wait_for(
                    accepted.get(), 30)
            except asyncio.TimeoutError:
                return
            try:
                if request_type == wire.RequestType.TRANSPORT:
                    if self.on_transport_request is not None:
                        await self.on_transport_request(source, transport)
                elif request_type == wire.RequestType.RESTORE_ALL:
                    if self.on_restore_request is not None:
                        await self.on_restore_request(source, transport)
                elif request_type == wire.RequestType.AUDIT:
                    if self.on_audit_request is not None:
                        await self.on_audit_request(source, transport)
            finally:
                done.set()
                await transport.close()
        finally:
            server.close()

    # --- restore serving (restore_send.rs) ---------------------------------

    async def serve_restore(self, peer_id: bytes, transport: Transport) -> int:
        """Stream everything ``peer_id`` stored with us back to them, with
        a per-peer rate limit (restore_send.rs:22-94)."""
        last = self.store.last_event_time(f"restore_served:{bytes(peer_id).hex()}")
        if last is not None and time.time() - last < defaults.RESTORE_REQUEST_THROTTLE_S:
            raise P2PError("restore request throttled")
        self.store.add_event(f"restore_served:{bytes(peer_id).hex()}", {})
        writer = ReceivedFilesWriter(self.store, peer_id)
        sent = 0
        for kind, file_id, data in writer.iter_stored():
            await transport.send_data(data, kind, file_id)
            sent += 1
        return sent

    # --- audit serving (prover side of the storage attestation) ------------

    async def serve_audit(self, peer_id: bytes, transport: Transport,
                          backend) -> int:
        """Answer one storage-audit challenge batch from ``peer_id``.

        The verifier opens an AUDIT-purpose connection, sends a single
        CHALLENGE body, and expects one PROOF body echoing its sequence
        number.  Per-peer rate limiting mirrors ``serve_restore`` so a
        hostile verifier cannot turn us into a free hashing oracle.
        """
        from ..audit.prover import compute_proofs  # local: avoids cycle

        peer_hex = bytes(peer_id).hex()
        last = self.store.last_event_time(f"audit_served:{peer_hex}")
        if last is not None and \
                time.time() - last < defaults.AUDIT_SERVE_MIN_INTERVAL_S:
            raise P2PError("audit request throttled")
        self.store.add_event(f"audit_served:{peer_hex}", {})
        body = await transport.recv_body(defaults.AUDIT_PROOF_TIMEOUT_S)
        if body.kind != wire.P2PBodyKind.CHALLENGE:
            raise P2PError("expected a CHALLENGE body on an audit connection")
        if len(body.challenges) > defaults.AUDIT_MAX_CHALLENGES_PER_MSG:
            raise P2PError("too many challenges in one message")
        # join the verifier's audit trace (challenge -> proof in one id)
        with obs_trace.bind(getattr(body, "trace_id", None)), \
                obs_trace.span("audit.serve"):
            proofs = compute_proofs(self.store, backend, peer_id,
                                    body.challenges)
        reply = wire.P2PBody(
            kind=wire.P2PBodyKind.PROOF,
            header=wire.P2PHeader(
                sequence_number=body.header.sequence_number,
                session_nonce=transport.session_nonce),
            proofs=tuple(proofs))
        await transport.send_body(reply)
        return len(proofs)
