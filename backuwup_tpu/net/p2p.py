"""P2P data plane: signed, replay-protected, acked peer-to-peer transfer.

Re-designs ``client/src/net_p2p/``: all backup bytes move client<->client
over WebSocket, end-to-end authenticated:

* Every message is an :class:`~backuwup_tpu.wire.EncapsulatedMsg` — an
  Ed25519-signed :class:`~backuwup_tpu.wire.P2PBody` carrying a replay
  header (random 16-byte session nonce + strictly-sequential sequence
  number, ``p2p_message.rs:21-24``, ``receive.rs:95-105``).
* Connections rendezvous through the coordination server: the initiator
  registers a nonce (60 s expiry, ``p2p_connection_manager.rs``), the
  acceptor binds a random port and confirms its address, the initiator
  dials and sends the signed seq-0 request (``handle_connections.rs``).
* Per-file acks with timeouts (``transport.rs:127-128``); packfiles are
  deleted by the sender only after the ack (``send.rs:277-289``).
* Hosts store received packfiles XOR-obfuscated with a local 4-byte key so
  a casual host can't read foreign (already encrypted) packfiles
  (``received_files_writer.rs:76-78``); quota = negotiated − received with
  a 16 MiB grace (``:101-108``).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from pathlib import Path
from typing import Callable, Dict, Optional, Tuple

import numpy as np

try:
    import websockets
except ModuleNotFoundError:  # containers without the wheel: aiohttp shim
    from ..utils import ws_compat as websockets

from .. import defaults, wire
from ..crypto import KeyManager, verify_signature
from ..obs import journal as obs_journal
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..ops.blake3_cpu import blake3_many
from ..store import Store
from ..utils import durable, faults, retry

_P2P_BYTES = obs_metrics.counter(
    "bkw_p2p_bytes_sent_total",
    "Signed frame bytes shipped through the transport send chokepoint")
_SEQ_BREAKS = obs_metrics.counter(
    "bkw_p2p_sequence_breaks_total",
    "Receiver sequence-validation failures (replay protection tripped)")
_PARTS = obs_metrics.counter(
    "bkw_transfer_parts_total", "FILE_PART frames acked end-to-end")
_RESUMES = obs_metrics.counter(
    "bkw_transfer_resumes_total",
    "RESUME_OFFER outcomes on chunked sends (resumed / restarted_*)",
    ("outcome",))
_STALLS = obs_metrics.counter(
    "bkw_transfer_stalls_total",
    "Adaptive-deadline expiries (transfer aborted toward resume)")
_PARTIALS_EXPIRED = obs_metrics.counter(
    "bkw_partials_expired_total",
    "Abandoned partial transfers expired by the receiver-side TTL janitor")
_RECLAIM_REQUESTS = obs_metrics.counter(
    "bkw_reclaim_requests_total",
    "RECLAIM requests served (holder side), by outcome", ("outcome",))
_RECLAIM_BYTES_FREED = obs_metrics.counter(
    "bkw_reclaim_bytes_freed_total",
    "Bytes a holder deleted (and credited back) while serving RECLAIMs")

# Crash-matrix seam around the receiver's partial-stage commit
_CP_PARTIAL_PRE = faults.register_crash_site("partial.sink.pre")
_CP_PARTIAL_POST = faults.register_crash_site("partial.sink.post")

PURPOSE_TRANSPORT = wire.RequestType.TRANSPORT
PURPOSE_RESTORE = wire.RequestType.RESTORE_ALL
PURPOSE_AUDIT = wire.RequestType.AUDIT


class P2PError(Exception):
    pass


def obfuscate(data: bytes, key: bytes) -> bytes:
    """XOR with a repeating 4-byte key (net_p2p/mod.rs:38-47); involutive."""
    if len(key) != 4:
        raise ValueError("obfuscation key must be 4 bytes")
    arr = np.frombuffer(bytes(data), dtype=np.uint8)
    pad = -len(arr) % 4
    if pad:
        arr = np.concatenate([arr, np.zeros(pad, np.uint8)])
    k = np.frombuffer(bytes(key) * (len(arr) // 4), dtype=np.uint8)
    out = (arr ^ k).tobytes()
    return out[:len(data)]


def adaptive_deadline(size: int, throughput_bps: float = 0.0) -> float:
    """Per-transfer ack deadline scaled to payload size (docs/transfer.md).

    Replaces the fixed ``ACK_TIMEOUT_S`` for sized payloads: the budget is
    the ack floor plus the seconds the payload needs at the slower of the
    assumed minimum link rate and the peer's measured EWMA throughput
    derated by the safety fraction — so a large file on a slow-but-alive
    link is not declared dead, while a genuine stall still trips fast.
    """
    floor = float(defaults.TRANSFER_MIN_THROUGHPUT_BPS)
    if throughput_bps > 0.0:
        floor = max(floor, throughput_bps * defaults.TRANSFER_DEADLINE_SAFETY)
    return min(defaults.ACK_TIMEOUT_S + size / max(floor, 1.0),
               defaults.TRANSFER_DEADLINE_CAP_S)


class SendProgress:
    """Wire-progress of one ``send_file`` attempt, for resume accounting:
    ``started`` is the offset the attempt resumed from, ``offset`` the
    high-water byte that has hit the wire (updated before each part's ack,
    so a cut mid-ack still counts its shipped bytes)."""

    def __init__(self) -> None:
        self.started = 0
        self.offset = 0


def validate_resume_offer(offer: wire.P2PBody, data: bytes, digest: bytes,
                          file_id: bytes) -> Tuple[int, str]:
    """Decide where a chunked send restarts given the receiver's offer.

    Returns ``(start_offset, outcome)``.  A verified prefix resumes
    (``resumed``); a digest mismatch means the receiver holds a partial of
    a *different* file version (``restarted_stale``) and a bad prefix
    digest means its partial is corrupt (``restarted_corrupt``) — both
    restart from zero, and the receiver discards its partial when part 0
    arrives.  Never trusts the offer: the whole-file digest is recomputed
    sender-side and the final assembled file is verified receiver-side.
    """
    if offer.kind != wire.P2PBodyKind.RESUME_OFFER:
        raise P2PError("expected a RESUME_OFFER body")
    if bytes(offer.file_id) != bytes(file_id):
        raise P2PError("RESUME_OFFER for a different file id")
    off = int(offer.offset)
    if off <= 0 or off > len(data):
        return 0, "cold"
    if bytes(offer.file_digest) != bytes(digest):
        return 0, "restarted_stale"
    if bytes(offer.prefix_digest) != blake3_many([data[:off]])[0]:
        return 0, "restarted_corrupt"
    return off, "resumed"


class ConnectionRequests:
    """Outgoing-request registry: anti-unsolicited-connection bookkeeping
    with expiry (p2p_connection_manager.rs:17-66)."""

    def __init__(self, ttl_s: float = defaults.P2P_REQUEST_TTL_S):
        self.ttl_s = ttl_s
        self._pending: Dict[bytes, tuple] = {}  # peer -> (nonce, purpose, exp)

    def add(self, peer_id: bytes, purpose: wire.RequestType) -> bytes:
        nonce = os.urandom(wire.TRANSPORT_NONCE_LEN)
        self._pending[bytes(peer_id)] = (nonce, purpose,
                                         time.time() + self.ttl_s)
        return nonce

    def finalize(self, peer_id: bytes) -> tuple:
        entry = self._pending.pop(bytes(peer_id), None)
        if entry is None or entry[2] < time.time():
            raise P2PError("no pending connection request for peer")
        return entry[0], entry[1]


def _sign_body(keys: KeyManager, body: wire.P2PBody) -> bytes:
    encoded = body.encode_bytes()
    # the caller's trace id rides outside the signed body (advisory
    # correlation metadata — see wire.EncapsulatedMsg)
    return wire.EncapsulatedMsg(
        body=encoded, signature=keys.sign(encoded),
        trace_id=obs_trace.current_trace_id()).encode_bytes()


def _verify_msg(raw: bytes, peer_id: bytes) -> wire.P2PBody:
    if len(raw) > defaults.MAX_P2P_MESSAGE_SIZE:
        raise P2PError("p2p message exceeds size cap")
    msg = wire.EncapsulatedMsg.decode_bytes(raw)
    if not verify_signature(peer_id, msg.body, msg.signature):
        raise P2PError("bad message signature")
    body = wire.P2PBody.decode_bytes(msg.body)
    # ride the sender's trace id alongside the body (frozen dataclass:
    # a side-channel attribute, never part of equality or encoding)
    object.__setattr__(body, "trace_id",
                       obs_trace.clean_trace_id(msg.trace_id))
    return body


class Transport:
    """Send side: ordered, signed, acked file transfer (transport.rs)."""

    def __init__(self, ws, keys: KeyManager, peer_id: bytes,
                 session_nonce: bytes, first_seq: int = 1):
        self.ws = ws
        self.keys = keys
        self.peer_id = bytes(peer_id)
        self.session_nonce = bytes(session_nonce)
        self.seq = first_seq
        self._acks: Dict[int, asyncio.Event] = {}
        self._listen_done = False
        self._ack_task: Optional[asyncio.Task] = None
        self._recv_queue: asyncio.Queue = asyncio.Queue()

    def start(self) -> None:
        if self._ack_task is None:
            self._ack_task = asyncio.create_task(self._listen())

    async def _listen(self) -> None:
        """Verify + route incoming frames: acks release waiting senders,
        data frames queue for the receive loop (duplex socket)."""
        try:
            async for raw in self.ws:
                try:
                    body = _verify_msg(raw, self.peer_id)
                except P2PError:
                    continue
                if body.header.session_nonce != self.session_nonce:
                    continue
                if body.kind == wire.P2PBodyKind.ACK:
                    ev = self._acks.pop(body.acked_sequence, None)
                    if ev is not None:
                        ev.set()
                else:
                    await self._recv_queue.put(body)
        except websockets.ConnectionClosed:
            pass
        finally:
            # Wake every pending ack waiter: once this loop exits no ack
            # can ever arrive, and a silent exit would strand concurrent
            # senders for their full adaptive deadline (they'd count a
            # stall for what is really a closed transport — e.g. a
            # sibling admission tick dropping a peer it judged full).
            # _listen_done distinguishes this sweep from a real ack:
            # the waiter raises P2PError immediately into the
            # abort-and-resume path instead of counting a stall.
            self._listen_done = True
            for ev in self._acks.values():
                ev.set()
            # put_nowait (queue is unbounded): the await form would fail
            # with "Event loop is closed" when the task is GC'd at
            # interpreter/loop teardown
            try:
                self._recv_queue.put_nowait(None)
            except RuntimeError:
                pass

    async def _ship(self, raw: bytes, seq: Optional[int] = None,
                    timeout: Optional[float] = None) -> None:
        """The single outbound chokepoint: EVERY signed frame leaves
        through here, so the fault plane's drop/corrupt/latency sites see
        control frames (audit, resume negotiation) exactly as they see
        FILE frames — no chaos-immune traffic."""
        plane = faults.PLANE
        if plane is not None:  # chaos hook; inert in production (PLANE=None)
            action = await plane.on_send(self.peer_id)
            if action == faults.ACT_DROP:
                await self.close()
                if seq is not None:
                    self._acks.pop(seq, None)
                raise P2PError("injected connection drop"
                               + (f" at seq {seq}" if seq is not None else ""))
            if action == faults.ACT_CORRUPT:
                raw = plane.corrupt(raw, self.peer_id)
        _P2P_BYTES.inc(len(raw))
        try:
            await asyncio.wait_for(
                self.ws.send(raw),
                defaults.PACKFILE_SEND_TIMEOUT_S if timeout is None
                else timeout)
        except (asyncio.TimeoutError, websockets.ConnectionClosed) as e:
            raise P2PError(f"send failed: {e}") from e

    async def _send_acked(self, body: wire.P2PBody, seq: int,
                          deadline: float) -> None:
        """Ship one seq-carrying frame and wait for its signed ack under
        the adaptive deadline; a deadline expiry is counted as a stall
        (the caller aborts-and-resumes rather than restarting)."""
        ev = asyncio.Event()
        self._acks[seq] = ev
        raw = _sign_body(self.keys, body)
        try:
            await self._ship(raw, seq=seq,
                             timeout=max(defaults.PACKFILE_SEND_TIMEOUT_S,
                                         deadline))
            try:
                await asyncio.wait_for(ev.wait(), deadline)
            except asyncio.TimeoutError as e:
                _STALLS.inc()
                raise P2PError(
                    f"ack stalled for seq {seq}"
                    f" after {deadline:.1f}s") from e
            if self._listen_done and seq in self._acks:
                # woken by _listen's close-time sweep, not by an ack
                # (a real ack pops the seq before setting the event):
                # fail fast (no stall count — the link is gone, not slow)
                # so run_resumable can redial and resume immediately
                raise P2PError(
                    f"transport closed while awaiting ack for seq {seq}")
        finally:
            self._acks.pop(seq, None)

    async def send_data(self, data: bytes, file_info: wire.FileInfoKind,
                        file_id: bytes, throughput_bps: float = 0.0) -> None:
        """Send one file as a single FILE frame; waits for the signed ack
        (transport.rs:111-132).  The ack deadline scales with payload size
        so a large file on a slow link is distinguishable from a dead
        peer even on this legacy non-chunked path."""
        seq = self.seq
        self.seq += 1
        body = wire.P2PBody(
            kind=wire.P2PBodyKind.FILE,
            header=wire.P2PHeader(sequence_number=seq,
                                  session_nonce=self.session_nonce),
            file_info=file_info, file_id=bytes(file_id), data=bytes(data))
        await self._send_acked(
            body, seq, adaptive_deadline(len(data), throughput_bps))

    async def send_file(self, data: bytes, file_info: wire.FileInfoKind,
                        file_id: bytes, *, resume: bool = True,
                        throughput_bps: float = 0.0,
                        progress: Optional[SendProgress] = None) -> None:
        """Send one file, chunked into resumable FILE_PART frames when it
        exceeds ``TRANSFER_CHUNK_BYTES`` (else the legacy FILE frame).

        A chunked send first asks the receiver how much of ``file_id`` it
        already holds (RESUME_QUERY/RESUME_OFFER) and continues from the
        verified offset; the receiver checks the assembled file against
        the whole-file digest before the final part's ack.
        """
        data = bytes(data)
        chunk = int(defaults.TRANSFER_CHUNK_BYTES)
        if chunk <= 0 or len(data) <= chunk:
            if progress is not None:
                progress.offset = len(data)  # all-or-nothing frame
            await self.send_data(data, file_info, file_id,
                                 throughput_bps=throughput_bps)
            return
        loop = asyncio.get_running_loop()
        digest = await loop.run_in_executor(
            None, lambda: blake3_many([data])[0])
        start = 0
        if resume:
            start = await self._negotiate_resume(data, file_info, file_id,
                                                 digest, throughput_bps)
        if progress is not None:
            progress.started = start
            progress.offset = start
        off = start
        while off < len(data):
            part = data[off:off + chunk]
            plane = faults.PLANE
            if plane is not None:
                if plane.on_send_part(self.peer_id, off,
                                      len(part)) == faults.ACT_DROP:
                    await self.close()
                    raise P2PError(
                        f"injected mid-transfer cut at offset {off}")
            seq = self.seq
            self.seq += 1
            body = wire.P2PBody(
                kind=wire.P2PBodyKind.FILE_PART,
                header=wire.P2PHeader(sequence_number=seq,
                                      session_nonce=self.session_nonce),
                file_info=file_info, file_id=bytes(file_id), data=part,
                offset=off, total_size=len(data), file_digest=digest)
            if progress is not None:
                progress.offset = off + len(part)  # on the wire before ack
            await self._send_acked(
                body, seq, adaptive_deadline(len(part), throughput_bps))
            _PARTS.inc()
            off += len(part)

    async def _negotiate_resume(self, data: bytes,
                                file_info: wire.FileInfoKind,
                                file_id: bytes, digest: bytes,
                                throughput_bps: float) -> int:
        """RESUME_QUERY -> RESUME_OFFER round trip; returns the verified
        offset to continue from (0 = cold or restart)."""
        seq = self.seq
        self.seq += 1
        query = wire.P2PBody(
            kind=wire.P2PBodyKind.RESUME_QUERY,
            header=wire.P2PHeader(sequence_number=seq,
                                  session_nonce=self.session_nonce),
            file_info=file_info, file_id=bytes(file_id))
        await self._ship(_sign_body(self.keys, query))
        offer = await self.recv_body(adaptive_deadline(0, throughput_bps))
        loop = asyncio.get_running_loop()
        start, outcome = await loop.run_in_executor(
            None, lambda: validate_resume_offer(offer, data, digest,
                                                file_id))
        if int(offer.offset) > 0:
            _RESUMES.inc(outcome=outcome)
            obs_journal.emit("transfer_resume_offer",
                             peer=self.peer_id.hex()[:16], outcome=outcome,
                             offered=int(offer.offset), start=start)
        return start

    async def send_body(self, body: wire.P2PBody) -> None:
        """Fire one signed non-FILE body (audit challenge/proof exchange,
        resume offers — correlation is by echoed sequence number, not
        per-frame acks).  Routed through the fault chokepoint like every
        other outbound frame."""
        await self._ship(_sign_body(self.keys, body))

    async def recv_body(self, timeout: float) -> wire.P2PBody:
        """Next verified non-ACK body from the peer (None sentinel on close
        becomes an error: callers always expect a concrete body)."""
        try:
            body = await asyncio.wait_for(self._recv_queue.get(), timeout)
        except asyncio.TimeoutError as e:
            raise P2PError("timed out waiting for peer body") from e
        if body is None:
            raise P2PError("connection closed while waiting for peer body")
        return body

    async def close(self) -> None:
        if self._ack_task is not None:
            self._ack_task.cancel()
        try:
            await self.ws.close()
        except Exception:
            pass


class Receiver:
    """Receive side: strict-sequence validation + signed acks (receive.rs).

    ``sink(file_info, file_id, data)`` persists one whole file;
    ``part_sink(file_info, file_id, data, offset, total, digest)`` stages
    one FILE_PART (returning True when the file completed) and
    ``resume_query(file_info, file_id)`` answers RESUME_QUERY with
    ``(offset, digest, prefix_digest)`` — both default to None for legacy
    callers, which then reject chunked traffic.  The loop ends when the
    peer closes the socket.
    """

    def __init__(self, transport: Transport, sink: Callable,
                 first_seq: int = 1, part_sink: Optional[Callable] = None,
                 resume_query: Optional[Callable] = None):
        self.t = transport
        self.sink = sink
        self.part_sink = part_sink
        self.resume_query = resume_query
        self.expected_seq = first_seq

    async def run(self) -> int:
        """Returns the number of files received (completed, not parts)."""
        count = 0
        while True:
            body = await self.t._recv_queue.get()
            if body is None:
                return count
            if body.kind not in (wire.P2PBodyKind.FILE,
                                 wire.P2PBodyKind.FILE_PART,
                                 wire.P2PBodyKind.RESUME_QUERY):
                continue
            if body.header.sequence_number != self.expected_seq:
                # replay protection tripped: surface it (counter +
                # journal) and close the transport cleanly before
                # erroring out of the serve loop — a poisoned session
                # must not linger half-open
                _SEQ_BREAKS.inc()
                obs_journal.emit(
                    "p2p_sequence_break",
                    peer=self.t.peer_id.hex()[:16],
                    got=int(body.header.sequence_number),
                    expected=int(self.expected_seq))
                await self.t.close()
                raise P2PError(
                    f"sequence break: got {body.header.sequence_number}, "
                    f"expected {self.expected_seq} (replay protection)")
            if body.kind == wire.P2PBodyKind.RESUME_QUERY:
                await self._answer_resume_query(body)
                self.expected_seq += 1
                continue
            # adopt the sender's trace id so this store joins its pack/
            # transfer spans in the journal (the acceptance chain)
            with obs_trace.bind(getattr(body, "trace_id", None)), \
                    obs_trace.span("receiver.store"):
                if body.kind == wire.P2PBodyKind.FILE_PART:
                    if self.part_sink is None:
                        raise P2PError(
                            "peer sent FILE_PART but this receiver does"
                            " not support chunked transfer")
                    completed = await self.part_sink(
                        body.file_info, body.file_id, body.data,
                        body.offset, body.total_size, body.file_digest)
                else:
                    await self.sink(body.file_info, body.file_id, body.data)
                    completed = True
            plane = faults.PLANE
            if plane is not None \
                    and plane.withhold_ack_now(self.t.peer_id):
                # injected crash-between-write-and-ack: the file is
                # persisted but the sender never learns; do NOT advance
                # expected_seq — a real crash would lose that state too
                continue
            ack = wire.P2PBody(
                kind=wire.P2PBodyKind.ACK,
                header=wire.P2PHeader(sequence_number=self.expected_seq,
                                      session_nonce=self.t.session_nonce),
                acked_sequence=self.expected_seq)
            await self.t.ws.send(_sign_body(self.t.keys, ack))
            self.expected_seq += 1
            if completed:
                count += 1

    async def _answer_resume_query(self, body: wire.P2PBody) -> None:
        """RESUME_OFFER echoing the query's sequence number (the PROOF
        pattern: correlation by echoed seq, no ack)."""
        offset, digest, prefix = 0, b"", b""
        if self.resume_query is not None:
            offset, digest, prefix = await self.resume_query(
                body.file_info, body.file_id)
        reply = wire.P2PBody(
            kind=wire.P2PBodyKind.RESUME_OFFER,
            header=wire.P2PHeader(
                sequence_number=body.header.sequence_number,
                session_nonce=self.t.session_nonce),
            file_id=bytes(body.file_id), offset=int(offset),
            file_digest=bytes(digest), prefix_digest=bytes(prefix))
        await self.t.send_body(reply)


class PartialStore:
    """Receiver-side staging for chunked transfers (docs/transfer.md).

    One in-flight file is a ``<file_id hex>.bin`` byte prefix plus a
    ``.json`` meta record (total size, whole-file digest, file kind)
    under the writer's ``partial/`` subtree.  All methods are synchronous
    disk work — callers run them in an executor.  Invariants:

    * parts append strictly contiguously; a gap is a protocol error;
    * part 0 always truncates: a sender that restarted from zero (stale
      or corrupt partial) implicitly discards the old bytes;
    * the assembled file must match the whole-file BLAKE3 before it is
      handed to the real sink — a corrupted partial is discarded, never
      acked, never resumed.
    """

    def __init__(self, base: Path):
        self.base = Path(base)

    def _paths(self, file_id: bytes) -> Tuple[Path, Path]:
        stem = bytes(file_id).hex()
        return self.base / f"{stem}.bin", self.base / f"{stem}.json"

    def query(self, file_id: bytes) -> Tuple[int, bytes, bytes]:
        """(held bytes, whole-file digest, prefix digest) for RESUME_OFFER;
        (0, b"", b"") when nothing usable is held."""
        bin_p, meta_p = self._paths(file_id)
        if not bin_p.exists() or not meta_p.exists():
            return 0, b"", b""
        try:
            meta = json.loads(meta_p.read_text())
            digest = bytes.fromhex(meta["digest"])
            held = bin_p.read_bytes()
        except (KeyError, ValueError, OSError):
            self.discard(file_id)
            return 0, b"", b""
        if not held:
            return 0, b"", b""
        return len(held), digest, blake3_many([held])[0]

    def append(self, file_info: wire.FileInfoKind, file_id: bytes,
               offset: int, total: int, digest: bytes,
               data: bytes) -> Optional[bytes]:
        """Stage one part; returns the assembled, digest-verified bytes
        when the file completed, else None."""
        bin_p, meta_p = self._paths(file_id)
        offset, total = int(offset), int(total)
        if offset == 0:
            self.base.mkdir(parents=True, exist_ok=True)
            # tmp+replace+fsync: a crash mid-meta-write must never leave a
            # truncated .json that query() would half-trust on resume
            durable.write_replace(meta_p, json.dumps(
                {"total": total, "digest": bytes(digest).hex(),
                 "file_info": int(file_info)}, sort_keys=True).encode())
            bin_p.write_bytes(bytes(data))
        else:
            if not bin_p.exists() or not meta_p.exists():
                raise P2PError("FILE_PART continues an unknown partial")
            meta = json.loads(meta_p.read_text())
            if meta.get("digest") != bytes(digest).hex() \
                    or int(meta.get("total", -1)) != total:
                self.discard(file_id)
                raise P2PError("FILE_PART metadata mismatch;"
                               " partial discarded")
            held = bin_p.stat().st_size
            if offset != held:
                raise P2PError(f"non-contiguous FILE_PART: offset {offset},"
                               f" held {held}")
            with bin_p.open("ab") as f:
                f.write(bytes(data))
        held = bin_p.stat().st_size
        if held < total:
            return None
        raw = bin_p.read_bytes()
        if held > total or blake3_many([raw])[0] != bytes(digest):
            self.discard(file_id)
            raise P2PError("assembled file digest mismatch;"
                           " partial discarded")
        self.discard(file_id)
        return raw

    def discard(self, file_id: bytes) -> None:
        for p in self._paths(file_id):
            try:
                p.unlink()
            except OSError:
                pass

    def expire(self, ttl_s: Optional[float] = None,
               now: Optional[float] = None) -> int:
        """TTL janitor: delete abandoned partials (bin/json pairs — and
        stray meta ``.tmp`` files from a crashed writer) whose newest
        member is older than ``ttl_s``.  Returns the number of partial
        *files* (distinct ids) expired; each bumps
        ``bkw_partials_expired_total``.  A sender that never returns must
        not leak receiver quota forever."""
        ttl = defaults.PARTIAL_STORE_TTL_S if ttl_s is None else float(ttl_s)
        now = time.time() if now is None else float(now)
        if not self.base.is_dir():
            return 0
        newest: Dict[str, float] = {}
        members: Dict[str, list] = {}
        for p in self.base.iterdir():
            if not p.is_file():
                continue
            stem = p.name.split(".", 1)[0]
            try:
                mtime = p.stat().st_mtime
            except OSError:
                continue
            newest[stem] = max(newest.get(stem, 0.0), mtime)
            members.setdefault(stem, []).append(p)
        expired = 0
        for stem, latest in newest.items():
            if now - latest <= ttl:
                continue
            for p in members[stem]:
                try:
                    p.unlink()
                except OSError:
                    pass
            expired += 1
        if expired:
            _PARTIALS_EXPIRED.inc(expired)
        return expired


class _ResumableSinkMixin:
    """Chunked-transfer entry points riding on a writer's ``partials``
    (a :class:`PartialStore`) and whole-file ``sink``; wired into
    :class:`Receiver` as ``part_sink``/``resume_query``."""

    def _check_part_admission(self, file_info: wire.FileInfoKind,
                              file_id: bytes, total: int) -> None:
        """Veto hook before part 0 burns disk (quota, etc.)."""

    async def sink_part(self, file_info: wire.FileInfoKind, file_id: bytes,
                        data: bytes, offset: int, total: int,
                        digest: bytes) -> bool:
        loop = asyncio.get_running_loop()

        def stage():
            if int(offset) == 0:
                self._check_part_admission(file_info, file_id, int(total))
            faults.crashpoint(_CP_PARTIAL_PRE)
            out = self.partials.append(file_info, file_id, offset, total,
                                       digest, data)
            faults.crashpoint(_CP_PARTIAL_POST)
            return out

        raw = await loop.run_in_executor(None, stage)
        if raw is None:
            return False
        await self.sink(file_info, file_id, raw)
        return True

    async def resume_offer(self, file_info: wire.FileInfoKind,
                           file_id: bytes) -> Tuple[int, bytes, bytes]:
        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.partials.query(file_id))


class ReceivedFilesWriter(_ResumableSinkMixin):
    """Store a peer's packfiles/indexes, obfuscated + quota-enforced
    (received_files_writer.rs)."""

    def __init__(self, store: Store, peer_id: bytes):
        self.store = store
        self.peer_id = bytes(peer_id)
        self.dir = store.received_dir(peer_id)
        self.partials = PartialStore(self.dir / "partial")
        key = store.get_obfuscation_key()
        if key is None:
            raise P2PError("obfuscation key not initialized")
        self.key = key

    def _quota_left(self) -> int:
        peer = self.store.get_peer(self.peer_id)
        negotiated = peer.bytes_negotiated if peer else 0
        received = peer.bytes_received if peer else 0
        return negotiated - received + defaults.PEER_OVERUSE_GRACE

    def _dest(self, file_info: wire.FileInfoKind, file_id: bytes) -> Path:
        if file_info == wire.FileInfoKind.INDEX:
            sub = "index"
        elif file_info == wire.FileInfoKind.SHARD:
            sub = "shard"  # file_id is the 13-byte shard id
        else:
            sub = "pack"
        return self.dir / sub / bytes(file_id).hex()

    def _check_part_admission(self, file_info: wire.FileInfoKind,
                              file_id: bytes, total: int) -> None:
        # refuse a chunked transfer up front when the whole file could
        # never fit the quota — don't burn disk on a doomed partial
        # (idempotent re-sends of an already-stored file are exempt:
        # the final sink acks those without re-counting)
        if not self._dest(file_info, file_id).exists() \
                and total > self._quota_left():
            raise P2PError("peer exceeded negotiated storage quota")

    async def sink(self, file_info: wire.FileInfoKind, file_id: bytes,
                   data: bytes) -> None:
        path = self._dest(file_info, file_id)
        d = path.parent
        loop = asyncio.get_running_loop()

        def persist() -> bool:
            """Blocking disk work off the event loop (the prover may be
            mid-backup itself: a slow disk here must not stall its own
            transfer plane).  Returns True if the file was new."""
            d.mkdir(parents=True, exist_ok=True)
            if path.exists():
                # Idempotent re-send: if the sender's ack was lost (crash
                # or drop between our write and their receive) it will
                # retry the identical file on a fresh session.  Same id +
                # same bytes => ack without re-counting quota; anything
                # else is still the collision refusal
                # (received_files_writer.rs:54-56).  XOR obfuscation is
                # deterministic, so comparing stored bytes against the
                # re-obfuscated payload is exact.
                if path.read_bytes() == obfuscate(data, self.key):
                    return False
                raise P2PError(f"refusing to overwrite {path.name}"
                               " with different bytes")
            if len(data) > self._quota_left():
                raise P2PError("peer exceeded negotiated storage quota")
            path.write_bytes(obfuscate(data, self.key))
            return True

        if await loop.run_in_executor(None, persist):
            self.store.add_peer_received(self.peer_id, len(data))

    def iter_stored(self):
        """Yield (file_info, file_id, de-obfuscated bytes) of everything this
        peer stored with us — the restore-serving source (restore_send.rs)."""
        for sub, kind in (("pack", wire.FileInfoKind.PACKFILE),
                          ("shard", wire.FileInfoKind.SHARD),
                          ("index", wire.FileInfoKind.INDEX)):
            d = self.dir / sub
            if not d.is_dir():
                continue
            for f in sorted(d.iterdir()):
                yield kind, bytes.fromhex(f.name), obfuscate(f.read_bytes(),
                                                             self.key)


class RestoreFilesWriter(_ResumableSinkMixin):
    """Save own packfiles/shards coming back from a peer during restore
    (restore_files_writer.rs).  ``base`` overrides the destination tree —
    sourceless shard repair stages its survivor fetches in a scratch dir
    instead of the restore dir."""

    def __init__(self, store: Store, base: Optional[object] = None):
        self.dir = Path(base) if base is not None else store.restore_dir()
        self.partials = PartialStore(self.dir / "partial")
        self.files = 0

    async def sink(self, file_info: wire.FileInfoKind, file_id: bytes,
                   data: bytes) -> None:
        if file_info == wire.FileInfoKind.INDEX:
            d = self.dir / "index"
            name = f"{int.from_bytes(bytes(file_id)[:8], 'little'):06d}"
        elif file_info == wire.FileInfoKind.SHARD:
            # shard/<packfile hex>/<index>: one directory per stripe so
            # assembly (erasure/stripe.py assemble_tree) can walk it
            pid, idx = bytes(file_id)[:-1], bytes(file_id)[-1]
            d = self.dir / "shard" / pid.hex()
            name = f"{idx:03d}"
        else:
            d = self.dir / "pack" / bytes(file_id).hex()[:2]
            name = bytes(file_id).hex()
        def persist() -> None:
            d.mkdir(parents=True, exist_ok=True)
            (d / name).write_bytes(data)

        # restore pulls run one Receiver per peer concurrently; the write
        # happens off the loop so one slow disk flush never stalls the
        # other peers' frames
        await asyncio.get_running_loop().run_in_executor(None, persist)
        self.files += 1


class P2PNode:
    """Ties rendezvous + transport together for one client."""

    def __init__(self, keys: KeyManager, store: Store, server_client,
                 bind_host: str = "127.0.0.1"):
        self.keys = keys
        self.store = store
        self.server = server_client
        self.bind_host = bind_host
        self.requests = ConnectionRequests()
        self._finalize_waiters: Dict[bytes, asyncio.Queue] = {}
        self.on_transport_request: Optional[Callable] = None
        self.on_restore_request: Optional[Callable] = None
        self.on_restore_fetch_request: Optional[Callable] = None
        self.on_audit_request: Optional[Callable] = None
        self.on_reclaim_request: Optional[Callable] = None
        server_client.on_incoming_p2p = self._handle_incoming
        server_client.on_finalize_p2p = self._handle_finalize

    # --- outgoing (accept_and_connect, handle_connections.rs:94-139) -------

    async def connect(self, peer_id: bytes, purpose: wire.RequestType,
                      timeout: float = 15.0) -> Transport:
        peer_id = bytes(peer_id)
        plane = faults.PLANE
        if plane is not None and (plane.is_dead(peer_id)
                                  or plane.is_dead(self.keys.client_id)):
            # fail fast, exactly like a dial to a vanished host; recorded
            # so the breach explainer sees kill evidence (obs/diagnose.py)
            dead = peer_id if plane.is_dead(peer_id) else self.keys.client_id
            faults._record_injection(f"dial.dead:{dead.hex()[:8]}")
            raise P2PError("injected: peer is dead")
        if plane is not None and plane.flaky_reconnect(peer_id):
            # the residential-NAT reconnect lottery: this dial attempt is
            # simply refused; the caller's resume loop retries
            raise P2PError("injected: flaky reconnect refused dial")
        nonce = self.requests.add(peer_id, purpose)
        q = self._finalize_waiters.setdefault(peer_id, asyncio.Queue())
        await self.server.p2p_connection_begin(peer_id, nonce)
        try:
            addr = await asyncio.wait_for(q.get(), timeout)
        except asyncio.TimeoutError:
            raise P2PError("peer did not confirm p2p connection")
        nonce, purpose = self.requests.finalize(peer_id)

        # dial retries (handle_connections.rs:145-165) through the unified
        # retry policy: 3 dials with jittered exponential backoff
        async def _dial():
            return await websockets.connect(
                f"ws://{addr}", max_size=defaults.MAX_P2P_MESSAGE_SIZE)

        try:
            ws = await retry.retry_async(_dial, retry.DIAL,
                                         retry_on=(OSError,))
        except OSError as e:
            raise P2PError(f"could not dial peer at {addr}: {e}") from e
        init = wire.P2PBody(
            kind=wire.P2PBodyKind.REQUEST,
            header=wire.P2PHeader(sequence_number=0, session_nonce=nonce),
            request_type=purpose)
        await ws.send(_sign_body(self.keys, init))
        t = Transport(ws, self.keys, peer_id, nonce)
        t.start()
        return t

    async def _handle_finalize(self, msg: wire.FinalizeP2PConnection) -> None:
        q = self._finalize_waiters.setdefault(
            bytes(msg.destination_client_id), asyncio.Queue())
        await q.put(msg.destination_ip_address)

    # --- incoming (accept_and_listen, handle_connections.rs:30-90) ---------

    async def _handle_incoming(self, msg: wire.IncomingP2PConnection) -> None:
        source = bytes(msg.source_client_id)
        plane = faults.PLANE
        if plane is not None and plane.is_dead(self.keys.client_id):
            return  # injected death: a dead host answers no rendezvous
        if self.store.get_peer(source) is None:
            return  # unknown peer: refuse (handle_connections.rs:31-45)
        expected_nonce = msg.session_nonce
        accepted: asyncio.Queue = asyncio.Queue()

        async def handler(ws):
            try:
                raw = await asyncio.wait_for(ws.recv(), 10)
                body = _verify_msg(raw, source)
                if (body.kind != wire.P2PBodyKind.REQUEST
                        or body.header.sequence_number != 0
                        or body.header.session_nonce != expected_nonce):
                    await ws.close()
                    return
            except (P2PError, asyncio.TimeoutError,
                    websockets.ConnectionClosed):
                return
            t = Transport(ws, self.keys, source, expected_nonce)
            t.start()
            done = asyncio.Event()
            await accepted.put((body.request_type, t, done))
            await done.wait()  # keep the ws handler alive while serving

        # random high port (net_p2p/mod.rs:26-35); the outer try/finally
        # guarantees the listener is closed even if this handler task is
        # cancelled mid-await (client shutdown)
        server = await websockets.serve(
            handler, self.bind_host, 0,
            max_size=defaults.MAX_P2P_MESSAGE_SIZE)
        try:
            port = server.sockets[0].getsockname()[1]
            await self.server.p2p_connection_confirm(
                source, f"{self.bind_host}:{port}")
            try:
                request_type, transport, done = await asyncio.wait_for(
                    accepted.get(), 30)
            except asyncio.TimeoutError:
                return
            try:
                if request_type == wire.RequestType.TRANSPORT:
                    if self.on_transport_request is not None:
                        await self.on_transport_request(source, transport)
                elif request_type == wire.RequestType.RESTORE_ALL:
                    if self.on_restore_request is not None:
                        await self.on_restore_request(source, transport)
                elif request_type == wire.RequestType.RESTORE_FETCH:
                    if self.on_restore_fetch_request is not None:
                        await self.on_restore_fetch_request(source, transport)
                elif request_type == wire.RequestType.AUDIT:
                    if self.on_audit_request is not None:
                        await self.on_audit_request(source, transport)
                elif request_type == wire.RequestType.RECLAIM:
                    if self.on_reclaim_request is not None:
                        await self.on_reclaim_request(source, transport)
            finally:
                done.set()
                await transport.close()
        finally:
            server.close()

    # --- restore serving (restore_send.rs) ---------------------------------

    async def serve_restore(self, peer_id: bytes, transport: Transport) -> int:
        """Stream everything ``peer_id`` stored with us back to them, with
        a per-peer rate limit (restore_send.rs:22-94)."""
        last = self.store.last_event_time(f"restore_served:{bytes(peer_id).hex()}")
        if last is not None and time.time() - last < defaults.RESTORE_REQUEST_THROTTLE_S:
            raise P2PError("restore request throttled")
        self.store.add_event(f"restore_served:{bytes(peer_id).hex()}", {})
        writer = ReceivedFilesWriter(self.store, peer_id)
        sent = 0
        for kind, file_id, data in writer.iter_stored():
            # chunked when large: a restore over a flaky WAN link resumes
            # instead of restarting (the puller passes a part-capable sink)
            await transport.send_file(data, kind, file_id)
            sent += 1
        return sent

    # --- shard-granular pull restore (docs/transfer.md restore data plane) --

    async def request_fetch(self, transport: Transport, wants) -> None:
        """Puller side: name the stored items wanted back on a
        RESTORE_FETCH connection.  ``wants`` is an iterable of
        ``(FileInfoKind, file_id)`` pairs; an INDEX want with an empty id
        asks for every index file the serving peer holds for us (the
        puller has no placement record of where its index files landed).
        Correlation is by connection, not sequence, so seq 0 is fine."""
        body = wire.P2PBody(
            kind=wire.P2PBodyKind.FETCH_REQUEST,
            header=wire.P2PHeader(sequence_number=0,
                                  session_nonce=transport.session_nonce),
            wants=tuple((wire.FileInfoKind(k), bytes(i))
                        for k, i in wants))
        await transport.send_body(body)

    async def serve_restore_fetch(self, peer_id: bytes,
                                  transport: Transport) -> int:
        """Serve one FETCH_REQUEST: stream exactly the named items back
        (skipping ones we don't hold — the puller notices the gap and
        re-queues on another holder).  Much lighter throttle than
        ``serve_restore``: a multi-source restore legitimately fans one
        client across many holders and hedges may revisit us."""
        peer_hex = bytes(peer_id).hex()
        last = self.store.last_event_time(f"restore_fetch_served:{peer_hex}")
        if last is not None and \
                time.time() - last < defaults.RESTORE_FETCH_MIN_INTERVAL_S:
            raise P2PError("restore fetch throttled")
        self.store.add_event(f"restore_fetch_served:{peer_hex}", {})
        writer = ReceivedFilesWriter(self.store, peer_id)
        body = await transport.recv_body(defaults.AUDIT_PROOF_TIMEOUT_S)
        if body.kind != wire.P2PBodyKind.FETCH_REQUEST:
            raise P2PError(
                "expected a FETCH_REQUEST body on a restore-fetch"
                " connection")
        if len(body.wants) > defaults.RESTORE_FETCH_MAX_WANTS:
            raise P2PError("too many items in one fetch request")
        loop = asyncio.get_running_loop()

        def _read(path: Path) -> bytes:
            return obfuscate(path.read_bytes(), writer.key)

        sent = 0
        with obs_trace.bind(getattr(body, "trace_id", None)), \
                obs_trace.span("restore.serve_fetch"):
            for kind, fid in body.wants:
                if kind == wire.FileInfoKind.INDEX and not fid:
                    d = writer.dir / "index"
                    names = sorted(
                        f.name for f in d.iterdir()) if d.is_dir() else []
                    for name in names:
                        data = await loop.run_in_executor(
                            None, _read, d / name)
                        await transport.send_file(
                            data, wire.FileInfoKind.INDEX,
                            bytes.fromhex(name))
                        sent += 1
                    continue
                path = writer._dest(kind, fid)
                if not path.exists():
                    continue
                data = await loop.run_in_executor(None, _read, path)
                await transport.send_file(data, kind, bytes(fid))
                sent += 1
        return sent

    # --- reclaim serving (GC's make-before-break tail, docs/lifecycle.md) ---

    async def request_reclaim(self, transport: Transport, items,
                              timeout: Optional[float] = None) -> int:
        """Owner side: ask the connected holder to delete the named
        superseded items.  ``items`` iterates ``(FileInfoKind, file_id)``
        pairs; returns the bytes the holder reports freed.  Correlation
        is the CHALLENGE/PROOF idiom — the ack echoes our sequence."""
        seq = transport.seq
        transport.seq += 1
        body = wire.P2PBody(
            kind=wire.P2PBodyKind.RECLAIM_REQUEST,
            header=wire.P2PHeader(sequence_number=seq,
                                  session_nonce=transport.session_nonce),
            wants=tuple((wire.FileInfoKind(k), bytes(i))
                        for k, i in items))
        await transport.send_body(body)
        reply = await transport.recv_body(
            defaults.AUDIT_PROOF_TIMEOUT_S if timeout is None else timeout)
        if reply.kind != wire.P2PBodyKind.RECLAIM_ACK \
                or reply.header.sequence_number != seq:
            raise P2PError("expected a RECLAIM_ACK echoing our sequence")
        return int(reply.offset)

    async def serve_reclaim(self, peer_id: bytes,
                            transport: Transport) -> int:
        """Serve one RECLAIM_REQUEST: delete the named items the signed
        requester itself stored with us, credit the freed bytes back
        against its quota, and ack with the byte count.

        Deletion scope is bounded by identity: paths resolve strictly
        under ``received_dir(peer_id)`` via the same ``_dest`` mapping
        the receive path uses, so a peer can only ever reclaim its OWN
        placements.  Unknown ids are skipped, not errors — the owner
        retries from its persisted backlog and an already-deleted file
        simply contributes zero bytes (idempotent re-delivery)."""
        peer_hex = bytes(peer_id).hex()
        last = self.store.last_event_time(f"reclaim_served:{peer_hex}")
        if last is not None and \
                time.time() - last < defaults.RECLAIM_MIN_INTERVAL_S:
            _RECLAIM_REQUESTS.inc(outcome="throttled")
            raise P2PError("reclaim request throttled")
        self.store.add_event(f"reclaim_served:{peer_hex}", {})
        writer = ReceivedFilesWriter(self.store, peer_id)
        body = await transport.recv_body(defaults.AUDIT_PROOF_TIMEOUT_S)
        if body.kind != wire.P2PBodyKind.RECLAIM_REQUEST:
            _RECLAIM_REQUESTS.inc(outcome="bad_body")
            raise P2PError(
                "expected a RECLAIM_REQUEST body on a reclaim connection")
        if len(body.wants) > defaults.RECLAIM_MAX_ITEMS:
            _RECLAIM_REQUESTS.inc(outcome="too_many")
            raise P2PError("too many items in one reclaim request")
        loop = asyncio.get_running_loop()

        def _unlink() -> int:
            freed = 0
            for kind, fid in body.wants:
                path = writer._dest(kind, fid)
                try:
                    size = path.stat().st_size
                    path.unlink()
                    freed += size
                except OSError:
                    continue  # unknown or already gone: zero bytes
            return freed

        freed = await loop.run_in_executor(None, _unlink)
        if freed:
            # the deleted bytes stop counting against the peer's quota
            # (clamped: a replayed delete cannot mint free storage)
            self.store.credit_peer_received(peer_id, freed)
            _RECLAIM_BYTES_FREED.inc(freed)
        _RECLAIM_REQUESTS.inc(outcome="ok")
        reply = wire.P2PBody(
            kind=wire.P2PBodyKind.RECLAIM_ACK,
            header=wire.P2PHeader(
                sequence_number=body.header.sequence_number,
                session_nonce=transport.session_nonce),
            acked_sequence=body.header.sequence_number,
            offset=freed)
        await transport.send_body(reply)
        return freed

    # --- audit serving (prover side of the storage attestation) ------------

    async def serve_audit(self, peer_id: bytes, transport: Transport,
                          backend) -> int:
        """Answer one storage-audit challenge batch from ``peer_id``.

        The verifier opens an AUDIT-purpose connection, sends a single
        CHALLENGE body, and expects one PROOF body echoing its sequence
        number.  Per-peer rate limiting mirrors ``serve_restore`` so a
        hostile verifier cannot turn us into a free hashing oracle.
        """
        from ..audit.prover import compute_proofs  # local: avoids cycle

        peer_hex = bytes(peer_id).hex()
        last = self.store.last_event_time(f"audit_served:{peer_hex}")
        if last is not None and \
                time.time() - last < defaults.AUDIT_SERVE_MIN_INTERVAL_S:
            raise P2PError("audit request throttled")
        self.store.add_event(f"audit_served:{peer_hex}", {})
        body = await transport.recv_body(defaults.AUDIT_PROOF_TIMEOUT_S)
        if body.kind != wire.P2PBodyKind.CHALLENGE:
            raise P2PError("expected a CHALLENGE body on an audit connection")
        if len(body.challenges) > defaults.AUDIT_MAX_CHALLENGES_PER_MSG:
            raise P2PError("too many challenges in one message")
        # join the verifier's audit trace (challenge -> proof in one id)
        with obs_trace.bind(getattr(body, "trace_id", None)), \
                obs_trace.span("audit.serve"):
            proofs = compute_proofs(self.store, backend, peer_id,
                                    body.challenges)
        reply = wire.P2PBody(
            kind=wire.P2PBodyKind.PROOF,
            header=wire.P2PHeader(
                sequence_number=body.header.sequence_number,
                session_nonce=transport.session_nonce),
            proofs=tuple(proofs))
        await transport.send_body(reply)
        return len(proofs)
