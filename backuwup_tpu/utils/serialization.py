"""Deterministic little-endian binary codec for data-plane structures.

The reference serializes blobs/trees/p2p bodies with bincode
(``dir_packer.rs:321``, ``transport.rs:111-132``).  This is our equivalent:
fixed-width little-endian integers, ``u64``-length-prefixed byte strings,
no implicit padding — byte-for-byte deterministic so that tree blobs hash
reproducibly and signatures verify across hosts.
"""

from __future__ import annotations

import struct
from typing import Optional


class Writer:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u8(self, v: int) -> None:
        self._parts.append(struct.pack("<B", v))

    def u32(self, v: int) -> None:
        self._parts.append(struct.pack("<I", v))

    def u64(self, v: int) -> None:
        self._parts.append(struct.pack("<Q", v))

    def fixed(self, b: bytes) -> None:
        self._parts.append(bytes(b))

    def blob(self, b: bytes) -> None:
        self.u64(len(b))
        self._parts.append(bytes(b))

    def str(self, s: str) -> None:
        self.blob(s.encode("utf-8"))

    def opt_fixed(self, b: Optional[bytes], length: int) -> None:
        if b is None:
            self.u8(0)
        else:
            if len(b) != length:
                raise ValueError(f"opt_fixed expects {length} bytes")
            self.u8(1)
            self.fixed(b)

    def take(self) -> bytes:
        return b"".join(self._parts)


class CodecError(ValueError):
    pass


class Reader:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = memoryview(buf)
        self._pos = 0

    def _read(self, n: int) -> memoryview:
        if self._pos + n > len(self._buf):
            raise CodecError("unexpected end of buffer")
        out = self._buf[self._pos:self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self._read(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._read(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._read(8))[0]

    def fixed(self, n: int) -> bytes:
        return bytes(self._read(n))

    def blob(self, max_len: int = 1 << 34) -> bytes:
        n = self.u64()
        if n > max_len:
            raise CodecError(f"blob length {n} exceeds cap {max_len}")
        return bytes(self._read(n))

    def str(self) -> str:
        return self.blob(1 << 20).decode("utf-8")

    def opt_fixed(self, length: int) -> Optional[bytes]:
        flag = self.u8()
        if flag == 0:
            return None
        if flag != 1:
            raise CodecError(f"bad option tag {flag}")
        return self.fixed(length)

    def remaining(self) -> int:
        return len(self._buf) - self._pos

    def expect_end(self) -> None:
        if self._pos != len(self._buf):
            raise CodecError(f"{len(self._buf) - self._pos} trailing bytes")
