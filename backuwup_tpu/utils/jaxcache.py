"""Opt-in persistent XLA compilation cache.

The dedup-pipeline programs (CDC scan, batched BLAKE3) are large unrolled
graphs; first compilation is expensive (remote-compiled on the hardware
path).  A persistent cache makes every process after the first start warm.
"""

from __future__ import annotations

import os
from pathlib import Path

_DEFAULT = Path(os.environ.get("BACKUWUP_JAX_CACHE",
                               Path.home() / ".cache" / "backuwup_tpu_jax"))


def enable_compilation_cache(path: Path = _DEFAULT) -> None:
    import jax

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
