"""libcrypto fallbacks for the ``cryptography`` package, via ctypes.

Some deployment containers ship Python without the ``cryptography`` wheel
but always have OpenSSL's ``libcrypto`` on disk (hashlib/ssl link it).
This module exposes the exact primitive surface the codebase uses —
AES-256-GCM, ChaCha20 keystream, Ed25519 sign/verify, HKDF-SHA256 — with
call signatures mirroring ``cryptography``'s, so the import sites can gate:

    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ModuleNotFoundError:
        from ..utils.compat_crypto import AESGCM

All cipher work happens inside OpenSSL (EVP); nothing here rolls its own
crypto except the ~10-line RFC 5869 HKDF over :mod:`hmac`.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import hashlib
import hmac
import os

_EVP_CTRL_GCM_SET_IVLEN = 0x9
_EVP_CTRL_GCM_GET_TAG = 0x10
_EVP_CTRL_GCM_SET_TAG = 0x11
_EVP_PKEY_ED25519 = 1087  # NID_ED25519
_TAG_LEN = 16

_lib = None


def _libcrypto():
    global _lib
    if _lib is None:
        name = ctypes.util.find_library("crypto")
        candidates = [name] if name else []
        candidates += ["libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"]
        last = None
        for cand in candidates:
            if not cand:
                continue
            try:
                lib = ctypes.CDLL(cand)
                break
            except OSError as e:
                last = e
        else:
            raise ModuleNotFoundError(
                "neither the `cryptography` package nor libcrypto is "
                f"available: {last}")
        for fn in ("EVP_CIPHER_CTX_new", "EVP_aes_128_gcm", "EVP_aes_192_gcm",
                   "EVP_aes_256_gcm", "EVP_chacha20", "EVP_MD_CTX_new",
                   "EVP_PKEY_new_raw_private_key",
                   "EVP_PKEY_new_raw_public_key"):
            getattr(lib, fn).restype = ctypes.c_void_p
        lib.EVP_PKEY_new_raw_private_key.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
        lib.EVP_PKEY_new_raw_public_key.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
        for fn in ("EVP_CIPHER_CTX_free", "EVP_MD_CTX_free", "EVP_PKEY_free"):
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class InvalidTag(Exception):
    """AEAD authentication failure (cryptography.exceptions.InvalidTag)."""


def _check(ok, what: str):
    if not ok:
        raise ValueError(f"libcrypto: {what} failed")


class _EvpCipher:
    """One EVP_CIPHER_CTX pass (encrypt or decrypt direction)."""

    def __init__(self, cipher, key: bytes, iv: bytes, encrypt: bool,
                 gcm: bool = False):
        lib = _libcrypto()
        self._lib = lib
        self.ctx = lib.EVP_CIPHER_CTX_new()
        _check(self.ctx, "EVP_CIPHER_CTX_new")
        self.encrypt = encrypt
        init = lib.EVP_EncryptInit_ex if encrypt else lib.EVP_DecryptInit_ex
        ctx = ctypes.c_void_p(self.ctx)
        _check(init(ctx, ctypes.c_void_p(cipher), None, None, None), "init")
        if gcm and len(iv) != 12:  # GCM's default nonce length is 12
            _check(lib.EVP_CIPHER_CTX_ctrl(
                ctx, _EVP_CTRL_GCM_SET_IVLEN, len(iv), None), "set ivlen")
        _check(init(ctx, None, None, key, iv), "key/iv init")

    def ctrl(self, op: int, arg: int, buf) -> None:
        _check(self._lib.EVP_CIPHER_CTX_ctrl(
            ctypes.c_void_p(self.ctx), op, arg, buf), "ctrl")

    def update(self, data: bytes, aad: bool = False) -> bytes:
        out = None if aad else ctypes.create_string_buffer(len(data) + 16)
        outl = ctypes.c_int(0)
        fn = (self._lib.EVP_EncryptUpdate if self.encrypt
              else self._lib.EVP_DecryptUpdate)
        _check(fn(ctypes.c_void_p(self.ctx), out, ctypes.byref(outl),
                  data, len(data)), "update")
        return b"" if aad else out.raw[:outl.value]

    def final(self) -> bool:
        out = ctypes.create_string_buffer(16)
        outl = ctypes.c_int(0)
        fn = (self._lib.EVP_EncryptFinal_ex if self.encrypt
              else self._lib.EVP_DecryptFinal_ex)
        return bool(fn(ctypes.c_void_p(self.ctx), out, ctypes.byref(outl)))

    def __del__(self):
        if getattr(self, "ctx", None):
            self._lib.EVP_CIPHER_CTX_free(ctypes.c_void_p(self.ctx))
            self.ctx = None


class AESGCM:
    """Drop-in for ``cryptography``'s AESGCM (16-byte tag appended)."""

    def __init__(self, key: bytes):
        if len(key) not in (16, 24, 32):
            raise ValueError("AES key must be 128/192/256 bits")
        self._key = bytes(key)

    def _cipher(self):
        lib = _libcrypto()
        return {16: lib.EVP_aes_128_gcm, 24: lib.EVP_aes_192_gcm,
                32: lib.EVP_aes_256_gcm}[len(self._key)]()

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        c = _EvpCipher(self._cipher(), self._key, bytes(nonce), encrypt=True,
                       gcm=True)
        if aad:
            c.update(bytes(aad), aad=True)
        ct = c.update(bytes(data))
        _check(c.final(), "gcm final")
        tag = ctypes.create_string_buffer(_TAG_LEN)
        c.ctrl(_EVP_CTRL_GCM_GET_TAG, _TAG_LEN, tag)
        return ct + tag.raw[:_TAG_LEN]

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        data = bytes(data)
        if len(data) < _TAG_LEN:
            raise InvalidTag("ciphertext shorter than the GCM tag")
        ct, tag = data[:-_TAG_LEN], data[-_TAG_LEN:]
        c = _EvpCipher(self._cipher(), self._key, bytes(nonce), encrypt=False,
                       gcm=True)
        if aad:
            c.update(bytes(aad), aad=True)
        plain = c.update(ct)
        c.ctrl(_EVP_CTRL_GCM_SET_TAG, _TAG_LEN,
               ctypes.create_string_buffer(tag, _TAG_LEN))
        if not c.final():
            raise InvalidTag("GCM tag verification failed")
        return plain


class ChaCha20:
    """Algorithm marker mirroring ``ciphers.algorithms.ChaCha20``."""

    def __init__(self, key: bytes, nonce: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20 key must be 32 bytes")
        if len(nonce) != 16:
            raise ValueError("ChaCha20 nonce must be 16 bytes")
        self.key = bytes(key)
        self.nonce = bytes(nonce)


class _ChaChaEncryptor:
    def __init__(self, algorithm: ChaCha20):
        self._c = _EvpCipher(_libcrypto().EVP_chacha20(), algorithm.key,
                             algorithm.nonce, encrypt=True)

    def update(self, data: bytes) -> bytes:
        return self._c.update(bytes(data))


class Cipher:
    """Just enough of ``ciphers.Cipher`` for the ChaCha20 keystream use."""

    def __init__(self, algorithm, mode=None):
        if not isinstance(algorithm, ChaCha20):
            raise TypeError("compat Cipher only supports ChaCha20")
        self._algorithm = algorithm

    def encryptor(self) -> _ChaChaEncryptor:
        return _ChaChaEncryptor(self._algorithm)


# --- Ed25519 (EVP_PKEY one-shot DigestSign/DigestVerify) --------------------


class _Pkey:
    def __init__(self, ptr):
        self._lib = _libcrypto()
        self.ptr = ptr

    def __del__(self):
        if getattr(self, "ptr", None):
            self._lib.EVP_PKEY_free(ctypes.c_void_p(self.ptr))
            self.ptr = None


class Ed25519PublicKey:
    def __init__(self, pkey: _Pkey, raw: bytes):
        self._pkey = pkey
        self._raw = raw

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "Ed25519PublicKey":
        data = bytes(data)
        ptr = _libcrypto().EVP_PKEY_new_raw_public_key(
            _EVP_PKEY_ED25519, None, data, len(data))
        if not ptr:
            raise ValueError("invalid Ed25519 public key")
        return cls(_Pkey(ptr), data)

    def public_bytes(self, encoding=None, format=None) -> bytes:
        return self._raw

    def verify(self, signature: bytes, data: bytes) -> None:
        lib = _libcrypto()
        ctx = lib.EVP_MD_CTX_new()
        _check(ctx, "EVP_MD_CTX_new")
        try:
            _check(lib.EVP_DigestVerifyInit(
                ctypes.c_void_p(ctx), None, None, None,
                ctypes.c_void_p(self._pkey.ptr)), "verify init")
            ok = lib.EVP_DigestVerify(
                ctypes.c_void_p(ctx), bytes(signature), len(signature),
                bytes(data), len(data))
            if ok != 1:
                raise InvalidSignature("Ed25519 verification failed")
        finally:
            lib.EVP_MD_CTX_free(ctypes.c_void_p(ctx))


class Ed25519PrivateKey:
    def __init__(self, pkey: _Pkey):
        self._pkey = pkey

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls.from_private_bytes(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "Ed25519PrivateKey":
        data = bytes(data)
        ptr = _libcrypto().EVP_PKEY_new_raw_private_key(
            _EVP_PKEY_ED25519, None, data, len(data))
        if not ptr:
            raise ValueError("invalid Ed25519 private key")
        return cls(_Pkey(ptr))

    def public_key(self) -> Ed25519PublicKey:
        lib = _libcrypto()
        buf = ctypes.create_string_buffer(32)
        ln = ctypes.c_size_t(32)
        _check(lib.EVP_PKEY_get_raw_public_key(
            ctypes.c_void_p(self._pkey.ptr), buf, ctypes.byref(ln)),
            "get raw public key")
        return Ed25519PublicKey.from_public_bytes(buf.raw[:ln.value])

    def sign(self, data: bytes) -> bytes:
        lib = _libcrypto()
        ctx = lib.EVP_MD_CTX_new()
        _check(ctx, "EVP_MD_CTX_new")
        try:
            _check(lib.EVP_DigestSignInit(
                ctypes.c_void_p(ctx), None, None, None,
                ctypes.c_void_p(self._pkey.ptr)), "sign init")
            sig = ctypes.create_string_buffer(64)
            ln = ctypes.c_size_t(64)
            _check(lib.EVP_DigestSign(
                ctypes.c_void_p(ctx), sig, ctypes.byref(ln),
                bytes(data), len(data)), "sign")
            return sig.raw[:ln.value]
        finally:
            lib.EVP_MD_CTX_free(ctypes.c_void_p(ctx))


class InvalidSignature(Exception):
    """cryptography.exceptions.InvalidSignature analog."""


# --- HKDF-SHA256 (RFC 5869 over hmac/hashlib) -------------------------------


class _SHA256:
    digest_size = 32


class hashes:  # namespace mirror of cryptography.hazmat.primitives.hashes
    SHA256 = _SHA256


class _Raw:
    Raw = "raw"


class serialization:  # namespace mirror (only Raw/Raw is used)
    Encoding = _Raw
    PublicFormat = _Raw


class HKDF:
    def __init__(self, algorithm=None, length: int = 32, salt=None,
                 info: bytes = b""):
        self.length = length
        self.salt = salt or b"\x00" * 32
        self.info = bytes(info or b"")

    def derive(self, key_material: bytes) -> bytes:
        prk = hmac.new(self.salt, bytes(key_material), hashlib.sha256).digest()
        okm = b""
        block = b""
        counter = 1
        while len(okm) < self.length:
            block = hmac.new(prk, block + self.info + bytes([counter]),
                             hashlib.sha256).digest()
            okm += block
            counter += 1
        return okm[:self.length]
