"""Backward-compat facade over :mod:`backuwup_tpu.obs.trace`.

The original host-tracing module (SURVEY §5.1) grew into the unified
observability plane: spans now carry trace/span ids that propagate
across threads, tasks, and the wire, feed the ``bkw_span_seconds``
histogram, and journal their closes.  Everything here simply re-exports
the obs implementation so the dozens of ``from ..utils import tracing``
call sites (and external scripts) keep working unchanged:

* ``span``/``traced``/``report``/``reset``/``format_report`` — the flat
  ``{name: (calls, total_s)}`` aggregate table, still gated on
  ``BKW_TRACE=1`` / :func:`enable` exactly as before (the id/histogram/
  journal mechanics run regardless of the gate);
* ``jax_profiler`` — unchanged ``BKW_TRACE_DIR`` device-trace hook.

New code should import :mod:`backuwup_tpu.obs.trace` directly.
"""

from __future__ import annotations

from ..obs.trace import (  # noqa: F401  (re-exported API)
    bind,
    current,
    current_span_id,
    current_trace_id,
    enable,
    enabled,
    format_report,
    jax_profiler,
    new_span_id,
    new_trace_id,
    report,
    reset,
    span,
    traced,
)

__all__ = [
    "bind", "current", "current_span_id", "current_trace_id", "enable",
    "enabled", "format_report", "jax_profiler", "new_span_id",
    "new_trace_id", "report", "reset", "span", "traced",
]
