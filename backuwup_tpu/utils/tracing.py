"""Host tracing + JAX-profiler hooks (SURVEY §5.1: the reference has no
tracing at all — println! only — and the survey directs this build to add
real instrumentation).

* :func:`span` — a contextmanager/decorator accumulating wall-clock per
  named section into a process-wide registry (thread-safe, negligible
  overhead when disabled).
* :func:`report` — snapshot of {name: (calls, total_s)} for logs/UI.
* :func:`jax_profiler` — wraps ``jax.profiler.trace`` so a device trace
  can be captured around any section when ``BKW_TRACE_DIR`` is set
  (viewable in TensorBoard/Perfetto); a no-op otherwise, so production
  paths can keep the call sites unconditionally.

Enable span collection with ``BKW_TRACE=1`` (or ``enable(True)``).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
from typing import Dict, Iterator, Tuple

_lock = threading.Lock()
_spans: Dict[str, Tuple[int, float]] = {}
_enabled = os.environ.get("BKW_TRACE", "0") == "1"


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def span(name: str) -> Iterator[None]:
    """Accumulate wall time under ``name`` (no-op unless enabled)."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            calls, total = _spans.get(name, (0, 0.0))
            _spans[name] = (calls + 1, total + dt)


def traced(name: str = None):
    """Decorator form of :func:`span`."""

    def deco(fn):
        label = name or f"{fn.__module__}.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kw):
            with span(label):
                return fn(*args, **kw)

        return wrapper

    return deco


def report() -> Dict[str, Tuple[int, float]]:
    with _lock:
        return dict(_spans)


def reset() -> None:
    with _lock:
        _spans.clear()


def format_report() -> str:
    rows = sorted(report().items(), key=lambda kv: -kv[1][1])
    if not rows:
        return "no spans recorded (BKW_TRACE=1 to enable)"
    width = max(len(k) for k, _ in rows)
    out = []
    for name, (calls, total) in rows:
        out.append(f"{name:<{width}}  {calls:>6}x  {total * 1e3:>10.1f} ms")
    return "\n".join(out)


@contextlib.contextmanager
def jax_profiler(section: str = "trace") -> Iterator[None]:
    """Capture a device profile into ``$BKW_TRACE_DIR/<section>`` when the
    env var is set; no-op (zero overhead) otherwise."""
    trace_dir = os.environ.get("BKW_TRACE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, section)):
        yield
