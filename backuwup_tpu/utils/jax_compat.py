"""Version-portable accessors for JAX APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map``; this repo targets the graduated name but must also run
on the pinned 0.4.x toolchain where only the experimental path exists.
The keyword signature (``mesh=``, ``in_specs=``, ``out_specs=``) is
identical in both, so call sites just import ``shard_map`` from here.
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
        # The experimental version predates replication rules for
        # while_loop (the dedup probe's retry loop); the graduated API
        # checks those fine, so only the fallback relaxes the check.
        kwargs.setdefault("check_rep", False)
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)
