"""Durable-commit helpers: fsync-disciplined atomic file replacement.

ALICE (Pillai et al., OSDI'14) showed that "atomic" tmp-write +
``os.replace`` protocols quietly assume two things POSIX never promised:
that the tmp file's *contents* reach disk before the rename, and that
the rename itself (a directory-entry update) is persisted.  A crash
between either pair leaves a zero-length or stale file behind a fresh
name.  Every commit point in this codebase (packfile seal, blob-index
save, challenge-table save, journal rotation, partial-transfer meta)
funnels through the helpers here so the discipline lives in one place:

* :func:`fsync_file` — flush one file's data+metadata;
* :func:`fsync_dir` — persist a directory's entries (the rename);
* :func:`commit_replace` — fsync tmp, ``os.replace``, fsync parent:
  after it returns, the destination durably holds the new bytes;
* :func:`write_replace` — the whole write-tmp/commit dance for callers
  that start from a byte string.

``fsync`` can be disabled process-wide with ``BKW_FSYNC=0`` (pure-tmpfs
test runs where durability is moot); the *atomicity* of the replace is
kept either way.  Directory fsync failures are swallowed — some
filesystems (and seccomp profiles) refuse ``fsync`` on a directory fd,
and a best-effort barrier beats an unconditional crash.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Union

_PathLike = Union[str, "os.PathLike[str]"]

#: Process-wide switch; tests may flip it, ``BKW_FSYNC=0`` disables.
FSYNC_ENABLED = os.environ.get("BKW_FSYNC", "1").lower() not in (
    "0", "false", "no")


def fsync_file(path: _PathLike) -> None:
    """Flush ``path``'s contents to stable storage (no-op when fsync is
    disabled)."""
    if not FSYNC_ENABLED:
        return
    fd = os.open(os.fspath(path), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: _PathLike) -> None:
    """Persist directory ``path``'s entries — the half of a rename that
    lives in the parent, not the file.  Best-effort: filesystems that
    reject directory fsync are tolerated."""
    if not FSYNC_ENABLED:
        return
    try:
        fd = os.open(os.fspath(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def commit_replace(tmp: _PathLike, dst: _PathLike) -> None:
    """Durably commit ``tmp`` over ``dst``: fsync the tmp file, rename
    atomically, then fsync the parent directory so the rename survives a
    crash.  ``tmp`` and ``dst`` must share a parent (same-directory
    rename is the only atomic one)."""
    fsync_file(tmp)
    os.replace(tmp, dst)
    fsync_dir(Path(os.fspath(dst)).parent)


def write_replace(dst: _PathLike, data: bytes) -> None:
    """Durably publish ``data`` at ``dst`` via a sibling ``.tmp`` file
    and :func:`commit_replace`."""
    dst = Path(os.fspath(dst))
    tmp = dst.with_name(dst.name + ".tmp")
    tmp.write_bytes(data)
    commit_replace(tmp, dst)
