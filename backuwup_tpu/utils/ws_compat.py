"""Minimal ``websockets``-API shim over aiohttp (client + server).

Containers that lack the ``websockets`` wheel always have aiohttp here
(the coordination server and UI are built on it), so the p2p layer gates:

    try:
        import websockets
    except ModuleNotFoundError:
        from ..utils import ws_compat as websockets

Only the surface :mod:`backuwup_tpu.net.p2p` touches is provided:
``connect(url, max_size=)``, ``serve(handler, host, port, max_size=)``
(-> object with ``.sockets`` and a sync ``.close()``), connection objects
with ``send``/``recv``/``close``/async-iteration, and ``ConnectionClosed``.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import aiohttp
from aiohttp import WSMsgType, web


class ConnectionClosed(Exception):
    """Raised by send/recv once the peer socket is gone."""


class _WS:
    """Wraps an aiohttp client or server websocket in websockets' API."""

    def __init__(self, ws, session: Optional[aiohttp.ClientSession] = None):
        self._ws = ws
        self._session = session

    async def send(self, data) -> None:
        try:
            await self._ws.send_bytes(bytes(data))
        except (ConnectionError, RuntimeError, aiohttp.ClientError) as e:
            raise ConnectionClosed(str(e)) from e

    async def recv(self):
        msg = await self._ws.receive()
        if msg.type == WSMsgType.BINARY:
            return msg.data
        if msg.type == WSMsgType.TEXT:
            return msg.data
        raise ConnectionClosed(f"websocket ended: {msg.type.name}")

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self.recv()
        except ConnectionClosed:
            raise StopAsyncIteration from None

    async def close(self) -> None:
        try:
            await self._ws.close()
        except Exception:
            pass
        if self._session is not None:
            await self._session.close()
            self._session = None


async def connect(url: str, max_size: Optional[int] = None) -> _WS:
    session = aiohttp.ClientSession()
    try:
        ws = await session.ws_connect(
            url, max_msg_size=max_size or 4 * 2 ** 20, autoping=True)
    except aiohttp.ClientError as e:
        await session.close()
        # net/p2p dial-retry loops catch OSError, the type websockets raises
        raise OSError(f"websocket connect failed: {e}") from e
    except Exception:
        await session.close()
        raise
    return _WS(ws, session)


class _Server:
    """Mirrors websockets' server handle: .sockets + sync .close()."""

    def __init__(self, runner: web.ServerRunner, site: web.TCPSite):
        self._runner = runner
        self._site = site

    @property
    def sockets(self):
        return self._site._server.sockets

    def close(self) -> None:
        self._site._server.close()
        # cleanup() is async; websockets' close() is sync — detach it.
        loop = asyncio.get_event_loop()
        if loop.is_running():
            loop.create_task(self._runner.cleanup())


async def serve(handler, host: str, port: int,
                max_size: Optional[int] = None) -> _Server:
    async def http_handler(request: web.BaseRequest):
        ws = web.WebSocketResponse(max_msg_size=max_size or 4 * 2 ** 20)
        await ws.prepare(request)
        await handler(_WS(ws))
        return ws

    runner = web.ServerRunner(web.Server(http_handler))
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    return _Server(runner, site)
