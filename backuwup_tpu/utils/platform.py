"""Make an explicit ``JAX_PLATFORMS`` env var actually win.

Some rigs re-pin JAX to the hardware plugin via sitecustomize's
``jax.config.update("jax_platforms", ...)``, which beats the env var —
the LAST config update before backend initialization wins.  Every
process entry point that honors ``JAX_PLATFORMS`` (bench, the driver
contract, the test harness) funnels through this one helper so the
workaround can't drift between copies.
"""

from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    """Re-assert ``JAX_PLATFORMS`` over a sitecustomize config pin.

    Must run before anything touches a device: once a backend is
    initialized, ``jax.config.update("jax_platforms", ...)`` silently
    has no effect.  The initialized-probe reads a private attribute;
    if that breaks under a newer jax, FAIL OPEN and apply the update
    anyway (a post-init update is the documented silent no-op, while
    skipping it would silently re-enable the dead-accelerator-tunnel
    hang this helper exists to prevent).
    """
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        from jax._src import xla_bridge

        initialized = bool(xla_bridge._backends)
    except Exception:
        initialized = False
    if not initialized:
        jax.config.update("jax_platforms", want)
