"""zstd compression via a ctypes binding of the system libzstd.

The reference compresses every blob with zstd level 3 through the Rust
``zstd`` crate (``packfile/pack.rs:59-64``, ``packfile/mod.rs:31``).  This
binds the same C library directly; if libzstd is unavailable the caller can
fall back to zlib (``CompressionKind.ZLIB`` exists in the wire model for
exactly that).
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

_lib: Optional[ctypes.CDLL] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("zstd") or "libzstd.so.1"
    try:
        lib = ctypes.CDLL(name)
    except OSError:
        return None
    lib.ZSTD_compressBound.restype = ctypes.c_size_t
    lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
    lib.ZSTD_compress.restype = ctypes.c_size_t
    lib.ZSTD_compress.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                  ctypes.c_void_p, ctypes.c_size_t,
                                  ctypes.c_int]
    lib.ZSTD_decompress.restype = ctypes.c_size_t
    lib.ZSTD_decompress.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                    ctypes.c_void_p, ctypes.c_size_t]
    lib.ZSTD_isError.restype = ctypes.c_uint
    lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
    lib.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
    lib.ZSTD_getFrameContentSize.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def compress(data: bytes, level: int = 3) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("libzstd not available")
    data = bytes(data)
    bound = lib.ZSTD_compressBound(len(data))
    out = ctypes.create_string_buffer(bound)
    n = lib.ZSTD_compress(out, bound, data, len(data), level)
    if lib.ZSTD_isError(n):
        raise RuntimeError("ZSTD_compress failed")
    return out.raw[:n]


def decompress(data: bytes, max_size: int = 1 << 31) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("libzstd not available")
    data = bytes(data)
    size = lib.ZSTD_getFrameContentSize(data, len(data))
    if size in (2**64 - 1, 2**64 - 2) or size > max_size:  # error/unknown
        raise ValueError("zstd frame has unknown or oversized content size")
    out = ctypes.create_string_buffer(max(1, size))
    n = lib.ZSTD_decompress(out, size, data, len(data))
    if lib.ZSTD_isError(n) or n != size:
        raise RuntimeError("ZSTD_decompress failed")
    return out.raw[:n]
