"""The clock seam: one injection point for every time-dependent path.

The simulation plane (``backuwup_tpu/sim``) runs the *real* retry,
matchmaking, peer-stats, and durability-sweep code on virtual time — a
simulated week of million-client churn in tier-1 minutes.  That only
works if the real code never reaches for the wall clock directly: every
``time.time()`` / ``time.monotonic()`` / ``asyncio.sleep()`` in a
sim-covered module routes through a :class:`Clock` handed in at
construction (bkwlint BKW006 enforces this statically).

The contract is three methods:

* ``now()`` — wall-clock epoch seconds.  Comparable to *persisted*
  timestamps (``last_seen``, ``sent_at``, audit-ledger ``next_due``), so
  anything that judges stored state against the present uses it.
* ``monotonic()`` — never steps backward.  Anything measuring an
  *interval* (violation-seconds accrual, rate math) uses it so an NTP
  step can neither inflate nor hide elapsed time.  ``SimClock`` keeps
  ``now == monotonic`` — virtual time only moves forward.
* ``await sleep(delay)`` — parks the caller until ``delay`` seconds of
  *clock* time pass.  Under asyncio that is ``asyncio.sleep``; under the
  sim driver it parks the task on the virtual deadline heap.

:data:`SYSTEM` is the process-wide real-time instance and the default
everywhere, so production call sites change shape only by gaining an
optional ``clock=`` parameter.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional


class SystemClock:
    """Real time: the production implementation of the clock seam.

    This class is the seam's terminal — the one place in the sim-covered
    modules where the actual wall clock is read (BKW006 baselines these
    three call sites, nothing else).
    """

    def now(self) -> float:
        return time.time()

    def monotonic(self) -> float:
        return time.monotonic()

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(delay)


#: The process-wide real-time clock; ``resolve(None)`` returns it.
SYSTEM = SystemClock()


def resolve(clock: Optional[object]) -> object:
    """``clock or SYSTEM`` with an explicit name, so constructors read as
    declaring the seam rather than defaulting an argument."""
    return SYSTEM if clock is None else clock
