"""Unified retry policy: jittered exponential backoff with caps.

One policy type for every transient-failure loop in the client — the p2p
dial path, the server-WS reconnect, the storage-request throttle, the
send-loop pacing, and the audit ledger's re-audit schedule.  Before this
module each of those carried its own ad-hoc constant and a bare
``asyncio.sleep``; now the shape of every retry (base, cap, growth,
jitter, attempt budget) is declared in one place (``defaults.py``) and the
loops share the same three small mechanisms:

* :class:`Backoff` — stateful attempt counter with ``await sleep()`` for
  loops that block between attempts (dial retries, WS reconnect).
* :class:`RetryTimer` — wall-clock variant for polling loops that must
  not block (the send loop re-requests storage only when ``due(now)``).
* :func:`retry_async` — run-awaitable-until-it-sticks wrapper for the
  simple "try N times" call sites.

Jitter is *full-range multiplicative*: the delay is drawn uniformly from
``[d*(1-j), d*(1+j)]`` so a fleet of clients retrying against one server
decorrelates (the thundering-herd argument of Exponential Backoff And
Jitter, AWS Architecture Blog 2015).  Policies that feed persisted,
test-asserted schedules (the audit ledger) set ``jitter=0``.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from .. import defaults
from . import clock as clockmod
from ..obs import journal as obs_journal
from ..obs import metrics as obs_metrics

_ATTEMPTS = obs_metrics.counter(
    "bkw_retry_attempts_total", "Retry/backoff firings by named policy",
    ("policy",))


def _record_attempt(policy: "RetryPolicy", attempt: int) -> None:
    label = policy.name or "anonymous"
    _ATTEMPTS.inc(policy=label)
    obs_journal.emit("retry", policy=label, attempt=attempt)


@dataclass(frozen=True)
class RetryPolicy:
    """Shape of one retry schedule; immutable so call sites can share it."""

    base_s: float
    cap_s: float
    multiplier: float = 2.0
    jitter: float = defaults.RETRY_JITTER  # +/- fraction of the raw delay
    max_attempts: Optional[int] = None  # retries allowed; None = unbounded
    name: str = ""  # metric/journal label; last so positional sites hold

    def delay_s(self, attempt: int,
                rand: Optional[Callable[[], float]] = None) -> float:
        """Delay before retry number ``attempt`` (1-based).

        ``rand`` is an injectable uniform-[0,1) source so tests (and the
        deterministic fault plane) can pin the jitter draw.
        """
        raw = min(self.base_s * self.multiplier ** max(0, attempt - 1),
                  self.cap_s)
        if self.jitter <= 0:
            return raw
        u = (rand or random.random)()
        return raw * (1.0 - self.jitter + 2.0 * self.jitter * u)


class Backoff:
    """Stateful attempt counter over a policy, for blocking retry loops.

    ``reset()`` after a success so the next failure starts from the base
    delay again (a reconnect loop must not inherit the backoff of an
    outage it already survived).
    """

    def __init__(self, policy: RetryPolicy,
                 rand: Optional[Callable[[], float]] = None,
                 clock=None):
        self.policy = policy
        self._rand = rand
        self.clock = clockmod.resolve(clock)
        self.attempt = 0

    def reset(self) -> None:
        self.attempt = 0

    def next_delay(self) -> Optional[float]:
        """Delay for the next retry, or None when attempts are exhausted."""
        self.attempt += 1
        if self.policy.max_attempts is not None \
                and self.attempt > self.policy.max_attempts:
            return None
        _record_attempt(self.policy, self.attempt)
        return self.policy.delay_s(self.attempt, self._rand)

    async def sleep(self) -> bool:
        """Sleep for the next delay; False when the budget is exhausted."""
        delay = self.next_delay()
        if delay is None:
            return False
        await self.clock.sleep(delay)
        return True


class RetryTimer:
    """Wall-clock backoff for polling loops that must not block.

    The send loop polls its buffer every tick; the storage request inside
    it may only fire when the previous one's backoff window has elapsed.
    ``due(now)`` answers that, ``fire(now)`` marks an attempt and arms the
    next window, ``reset()`` clears the schedule after a success.  A fresh
    timer is due immediately.
    """

    def __init__(self, policy: RetryPolicy,
                 rand: Optional[Callable[[], float]] = None,
                 clock=None):
        self.policy = policy
        self._rand = rand
        self.clock = clockmod.resolve(clock)
        self.attempt = 0
        self._next_at = 0.0

    def due(self, now: Optional[float] = None) -> bool:
        now = self.clock.now() if now is None else now
        return now >= self._next_at

    def fire(self, now: Optional[float] = None) -> None:
        now = self.clock.now() if now is None else now
        self.attempt += 1
        _record_attempt(self.policy, self.attempt)
        self._next_at = now + self.policy.delay_s(self.attempt, self._rand)

    def reset(self) -> None:
        self.attempt = 0
        self._next_at = 0.0


async def retry_async(fn, policy: RetryPolicy, *,
                      retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                      rand: Optional[Callable[[], float]] = None,
                      on_retry: Optional[Callable] = None,
                      clock=None):
    """``await fn()`` with retries per ``policy``; re-raises the last error
    once the attempt budget is spent.  ``on_retry(attempt, exc)`` observes
    each failure (logging hook); ``clock`` routes the backoff sleeps
    through the clock seam (``utils.clock``) for virtual-time callers."""
    backoff = Backoff(policy, rand, clock=clock)
    while True:
        try:
            return await fn()
        except retry_on as e:
            if not await backoff.sleep():
                raise
            if on_retry is not None:
                on_retry(backoff.attempt, e)


# --- the client's shared policies (tunables live in defaults.py) ------------

#: p2p dial retries (handle_connections.rs:145-165 hardcoded 3 tries/0.5 s).
DIAL = RetryPolicy(base_s=defaults.DIAL_RETRY_BASE_S,
                   cap_s=defaults.DIAL_RETRY_CAP_S,
                   max_attempts=defaults.DIAL_RETRY_ATTEMPTS,
                   name="dial")

#: server push-channel reconnect (net_server/mod.rs:26-55 hardcoded 0.2 s).
WS_RECONNECT = RetryPolicy(base_s=defaults.WS_RECONNECT_BASE_S,
                           cap_s=defaults.WS_RECONNECT_CAP_S,
                           name="ws_reconnect")

#: storage-request re-issue while no peer has room (send.rs:296-309).
STORAGE_REQUEST = RetryPolicy(base_s=defaults.STORAGE_REQUEST_RETRY_S,
                              cap_s=defaults.STORAGE_REQUEST_RETRY_CAP_S,
                              name="storage_request")

#: send-loop pacing while waiting for the packer to produce.
SEND_IDLE = RetryPolicy(base_s=defaults.SEND_IDLE_BASE_S,
                        cap_s=defaults.SEND_IDLE_CAP_S,
                        name="send_idle")

#: send-loop pacing while waiting for a usable peer.
PEER_WAIT = RetryPolicy(base_s=defaults.PEER_WAIT_BASE_S,
                        cap_s=defaults.PEER_WAIT_CAP_S,
                        name="peer_wait")

#: audit ledger re-audit schedule after a miss/failure.  jitter=0: the
#: ledger persists absolute ``next_due`` times that tests (and operators
#: reading the ledger) must be able to predict exactly.
AUDIT = RetryPolicy(base_s=defaults.AUDIT_RETRY_BASE_S,
                    cap_s=defaults.AUDIT_BACKOFF_CAP_S,
                    jitter=0.0, name="audit")
