"""Deterministic, seedable fault-injection plane for the p2p data plane.

The chaos harness of the framework: tests (or an operator via the
``BKW_FAULTS`` env var) install a :class:`FaultPlane` and the hooks at the
Transport/Node seam in :mod:`backuwup_tpu.net.p2p` start injecting

* **drop_send** — the connection dies mid-``send_data`` (socket closed,
  sender sees a ``P2PError``),
* **corrupt_frame** — one byte of the signed frame is flipped in flight
  (the receiver's signature check drops it; the sender times out on the
  ack),
* **withhold_ack** — the receiver persists the file but the ack never
  leaves (the crash-between-write-and-ack window; exercises the
  idempotent re-send path),
* **latency** — an extra await before the frame goes out,
* **peer death** — a peer id is marked dead: it answers no rendezvous,
  accepts no dial, and every in-flight transport to it fails on the next
  send.  :meth:`FaultPlane.kill_after` arms death after N successful
  sends — "the peer vanished mid-backup".
* **mid-transfer cuts** — :meth:`FaultPlane.arm_cut` arms exact byte
  offsets per peer; the chunked sender dies on the FILE_PART covering an
  armed offset (``cut_part`` is the rate-based version).  The resume
  protocol (docs/transfer.md) must continue from the persisted offset.
* **flaky reconnect** — ``reconnect_fail`` makes a fraction of p2p dials
  fail outright, the residential-NAT reconnect lottery.
* **crash points** — named :func:`crashpoint` sites at every multi-step
  commit seam (pack-seal, blob-index save, challenge-table save,
  placement insert, stripe finish, repair re-home, partial sink).  When
  armed (``arm_crash`` exact, or the seeded ``crash`` rate) the site
  raises :class:`CrashInjected` — deliberately a ``BaseException`` so no
  blanket ``except Exception`` recovery path can absorb the "process
  died here" signal — or, with ``crash_hard`` set (subprocess mode),
  hard-exits via ``os._exit`` with :data:`CRASH_EXIT_CODE`, the closest
  in-tree approximation of ``kill -9`` at that instruction.  Sites
  self-register through :func:`register_crash_site` at import, so the
  crash-matrix harness can enumerate :func:`crash_sites` without a
  hand-kept list.

Two properties the acceptance bar demands, by construction:

* **Inert when disabled.**  The module-global :data:`PLANE` is ``None``
  unless explicitly installed; every hook site is a single
  ``faults.PLANE is not None`` check, so the production path pays one
  attribute load and no frames, allocations, or RNG draws.
* **Deterministic under a seed.**  Every decision site draws from its own
  ``random.Random`` seeded by ``(plane seed, site name)``, so the answer
  stream of one site is a pure function of the seed and that site's query
  count — independent of how asyncio interleaves *other* sites.  Tests
  that need exact placement use :meth:`arm` (fire on the Nth query) which
  bypasses probability entirely.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import random
from typing import Dict, Optional, Set

from ..obs import journal as obs_journal
from ..obs import metrics as obs_metrics

#: send_data asks this before shipping a FILE frame
ACT_DROP = "drop"
ACT_CORRUPT = "corrupt"

#: Process exit status used by hard crash injection (``crash_hard``) so a
#: supervising test can tell an injected crash from a real fault.
CRASH_EXIT_CODE = 70


class CrashInjected(BaseException):
    """The process "died" at a named crash point.

    Derives from ``BaseException`` on purpose: the commit seams sit under
    broad ``except Exception`` guards (challenge-table save, send jobs)
    that must NOT be able to swallow an injected crash — a real power cut
    would not have run those handlers either.
    """

    def __init__(self, site: str):
        super().__init__(site)
        self.site = site


#: Every crash-point name ever registered in this process, in module
#: import order of the seams.  The crash-matrix harness enumerates this.
CRASH_SITES: Set[str] = set()


def register_crash_site(site: str) -> str:
    """Declare a crash point at module import; returns ``site`` so call
    sites can bind it to a constant: ``_CP = faults.register_crash_site(
    "pack.seal.pre")``."""
    CRASH_SITES.add(site)
    return site


def crash_sites() -> tuple:
    """Sorted tuple of every registered crash point (matrix input)."""
    return tuple(sorted(CRASH_SITES))

_INJECTIONS = obs_metrics.counter(
    "bkw_fault_injections_total", "Fault-plane firings by hook site",
    ("site",))


def _record_injection(site: str) -> None:
    # metric label is the hook prefix (site minus the ':<peer hex>' tail)
    # so cardinality stays bounded; the journal keeps the full site
    _INJECTIONS.inc(site=site.split(":", 1)[0])
    obs_journal.emit("fault", site=site)


def _site_seed(seed: int, site: str) -> int:
    digest = hashlib.blake2s(f"{seed}:{site}".encode()).digest()[:8]
    return int.from_bytes(digest, "little")


class FaultPlane:
    """One installed chaos configuration.

    ``rates`` are per-query probabilities in [0, 1]; ``arm`` pins exact
    query indices per site for deterministic tests.  Site names follow
    ``<hook>:<peer hex>`` so each peer direction has an independent
    stream.
    """

    def __init__(self, seed: int = 0, *, drop_send: float = 0.0,
                 corrupt_frame: float = 0.0, withhold_ack: float = 0.0,
                 latency: float = 0.0, latency_s: float = 0.05,
                 cut_part: float = 0.0, reconnect_fail: float = 0.0,
                 crash: float = 0.0, crash_hard: bool = False):
        self.seed = int(seed)
        self.drop_send = float(drop_send)
        self.corrupt_frame = float(corrupt_frame)
        self.withhold_ack = float(withhold_ack)
        self.latency = float(latency)
        self.latency_s = float(latency_s)
        self.cut_part = float(cut_part)
        self.reconnect_fail = float(reconnect_fail)
        self.crash = float(crash)
        self.crash_hard = bool(crash_hard)
        self.dead: Set[bytes] = set()
        self._cuts: Dict[bytes, Set[int]] = {}
        self._kill_after: Dict[bytes, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._queries: Dict[str, int] = {}
        self._armed: Dict[str, Set[int]] = {}
        #: observability: fires per site, for test assertions and logs
        self.fired: Dict[str, int] = {}

    # --- deterministic decision core ---------------------------------------

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(
                _site_seed(self.seed, site))
        return rng

    def arm(self, site: str, *query_indices: int) -> None:
        """Force ``site`` to fire on exactly these (0-based) query indices,
        regardless of rates — the deterministic-placement test API."""
        self._armed.setdefault(site, set()).update(query_indices)

    def decide(self, site: str, rate: float) -> bool:
        """One decision draw at ``site``; counts queries and fires."""
        q = self._queries.get(site, 0)
        self._queries[site] = q + 1
        hit = q in self._armed.get(site, ())
        if not hit and rate > 0.0:
            hit = self._rng(site).random() < rate
        elif rate > 0.0:
            # keep the stream position consistent whether or not armed
            # indices interleave, so arming never shifts later draws
            self._rng(site).random()
        if hit:
            self.fired[site] = self.fired.get(site, 0) + 1
            _record_injection(site)
        return hit

    # --- crash points -------------------------------------------------------

    def arm_crash(self, site: str, *query_indices: int) -> None:
        """Arm crash point ``site`` (a :data:`CRASH_SITES` name) to fire
        on the given 0-based query indices — the first query when none
        are given.  The deterministic crash-matrix API."""
        self.arm(f"crash.{site}", *(query_indices or (0,)))

    def crashpoint(self, site: str) -> None:
        """One pass through crash point ``site``.  Free unless the crash
        kind is active (armed or rated); fires at most what
        :meth:`decide` says; raises :class:`CrashInjected`, or hard-exits
        the process when ``crash_hard`` is set."""
        key = f"crash.{site}"
        if self.crash <= 0.0 and key not in self._armed:
            return
        if not self.decide(key, self.crash):
            return
        if self.crash_hard:
            try:
                obs_journal.emit("crash_injected", site=site, hard=True)
            except Exception:
                pass
            os._exit(CRASH_EXIT_CODE)
        raise CrashInjected(site)

    # --- peer death ---------------------------------------------------------

    def kill(self, peer_id: bytes) -> None:
        self.dead.add(bytes(peer_id))

    def revive(self, peer_id: bytes) -> None:
        self.dead.discard(bytes(peer_id))
        self._kill_after.pop(bytes(peer_id), None)

    def kill_after(self, peer_id: bytes, sends: int) -> None:
        """Peer drops dead after ``sends`` more successful FILE sends."""
        self._kill_after[bytes(peer_id)] = int(sends)

    def is_dead(self, peer_id: bytes) -> bool:
        return bytes(peer_id) in self.dead

    def _count_send(self, peer_id: bytes) -> bool:
        """Advance the kill_after counter; True when this send is the one
        that finds the peer dead."""
        k = bytes(peer_id)
        if k not in self._kill_after:
            return False
        if self._kill_after[k] <= 0:
            del self._kill_after[k]
            self.dead.add(k)
            return True
        self._kill_after[k] -= 1
        return False

    # --- hooks consumed by net/p2p.py ---------------------------------------

    async def on_send(self, peer_id: bytes) -> Optional[str]:
        """Called by Transport.send_data before shipping a FILE frame.
        Returns ACT_DROP / ACT_CORRUPT / None; sleeps injected latency."""
        hexid = bytes(peer_id).hex()
        if self.latency > 0.0 and self.decide(f"send.latency:{hexid}",
                                              self.latency):
            await asyncio.sleep(self.latency_s)
        if self._count_send(peer_id) or self.is_dead(peer_id):
            self.fired[f"send.dead:{hexid}"] = \
                self.fired.get(f"send.dead:{hexid}", 0) + 1
            _record_injection(f"send.dead:{hexid}")
            return ACT_DROP
        if self.decide(f"send.drop:{hexid}", self.drop_send):
            return ACT_DROP
        if self.decide(f"send.corrupt:{hexid}", self.corrupt_frame):
            return ACT_CORRUPT
        return None

    def arm_cut(self, peer_id: bytes, *offsets: int) -> None:
        """Arm exact-offset mid-transfer cuts toward ``peer_id``: the
        connection dies on the FILE_PART whose byte range covers an armed
        offset (one-shot per offset) — "the WAN link dropped at byte N of
        the shard", the deterministic-resume test API."""
        self._cuts.setdefault(bytes(peer_id), set()).update(
            int(o) for o in offsets)

    def on_send_part(self, peer_id: bytes, offset: int,
                     size: int) -> Optional[str]:
        """Called before shipping a FILE_PART covering
        ``[offset, offset + size)``.  Exact-offset cuts fire first (armed,
        one-shot), then the seeded ``cut_part`` rate."""
        hexid = bytes(peer_id).hex()
        armed = self._cuts.get(bytes(peer_id))
        if armed:
            hit = [c for c in armed if offset <= c < offset + size]
            if hit:
                for c in hit:
                    armed.discard(c)
                site = f"send.cut:{hexid}"
                self.fired[site] = self.fired.get(site, 0) + 1
                _record_injection(site)
                return ACT_DROP
        if self.cut_part > 0.0 and self.decide(f"send.cut:{hexid}",
                                               self.cut_part):
            return ACT_DROP
        return None

    def flaky_reconnect(self, peer_id: bytes) -> bool:
        """Called by P2PNode.connect before dialing: True = this dial is
        refused, as a flaky residential peer would."""
        return self.decide(f"dial.flaky:{bytes(peer_id).hex()}",
                           self.reconnect_fail)

    def corrupt(self, raw: bytes, peer_id: bytes) -> bytes:
        """Flip one deterministically chosen byte of the signed frame."""
        rng = self._rng(f"corrupt.byte:{bytes(peer_id).hex()}")
        i = rng.randrange(len(raw))
        return raw[:i] + bytes([raw[i] ^ 0xFF]) + raw[i + 1:]

    def withhold_ack_now(self, peer_id: bytes) -> bool:
        """Called by Receiver.run after the sink persisted the file."""
        return self.decide(f"recv.withhold_ack:{bytes(peer_id).hex()}",
                           self.withhold_ack)


#: The installed plane; None (the default) disables every hook.
PLANE: Optional[FaultPlane] = None


def install(plane: FaultPlane) -> FaultPlane:
    global PLANE
    PLANE = plane
    return plane


def uninstall() -> None:
    global PLANE
    PLANE = None


def crashpoint(site: str) -> None:
    """The module-level crash hook the commit seams call.  One attribute
    load when no plane is installed — same inertness contract as every
    other hook site."""
    plane = PLANE
    if plane is not None:
        plane.crashpoint(site)


def from_env(spec: Optional[str] = None) -> Optional[FaultPlane]:
    """Parse a ``BKW_FAULTS`` spec into a plane (None when unset/empty).

    Format: comma-separated ``key=value``; keys ``seed``, ``drop_send``,
    ``corrupt_frame``, ``withhold_ack``, ``latency`` (probability),
    ``latency_s`` (seconds), ``kill`` ('+'-separated hex client ids),
    ``crash`` ('+'-separated crash sites, each optionally ``site@N`` to
    fire on the Nth query instead of the first), ``crash_rate``
    (probability across every crash point) and ``crash_hard`` (0/1:
    convert an injected crash into a hard ``os._exit`` — the subprocess
    kill -9 mode).
    Example: ``BKW_FAULTS=seed=7,drop_send=0.05,latency=0.2,latency_s=0.1``
    or ``BKW_FAULTS=crash=placement.insert.post@1,crash_hard=1``
    """
    spec = os.environ.get("BKW_FAULTS", "") if spec is None else spec
    spec = spec.strip()
    if not spec:
        return None
    kw: Dict[str, float] = {}
    kills = []
    crashes = []
    crash_hard = False
    for part in spec.split(","):
        if not part.strip():
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        value = value.strip()
        if key == "kill":
            kills.extend(bytes.fromhex(v) for v in value.split("+") if v)
        elif key == "seed":
            kw["seed"] = int(value)
        elif key == "crash":
            for v in value.split("+"):
                if not v:
                    continue
                site, _, at = v.partition("@")
                crashes.append((site, int(at) if at else 0))
        elif key == "crash_rate":
            kw["crash"] = float(value)
        elif key == "crash_hard":
            crash_hard = value.lower() not in ("", "0", "false", "no")
        elif key in ("drop_send", "corrupt_frame", "withhold_ack",
                     "latency", "latency_s", "cut_part", "reconnect_fail"):
            kw[key] = float(value)
        else:
            raise ValueError(f"unknown BKW_FAULTS key {key!r}")
    seed = int(kw.pop("seed", 0))
    plane = FaultPlane(seed, crash_hard=crash_hard, **kw)
    for k in kills:
        plane.kill(k)
    for site, at in crashes:
        plane.arm_crash(site, at)
    return plane


# env activation at import time: the p2p module imports this module, so a
# process started with BKW_FAULTS set gets the plane with no test plumbing
if os.environ.get("BKW_FAULTS"):
    PLANE = from_env()
