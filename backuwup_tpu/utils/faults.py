"""Deterministic, seedable fault-injection plane for the p2p data plane.

The chaos harness of the framework: tests (or an operator via the
``BKW_FAULTS`` env var) install a :class:`FaultPlane` and the hooks at the
Transport/Node seam in :mod:`backuwup_tpu.net.p2p` start injecting

* **drop_send** — the connection dies mid-``send_data`` (socket closed,
  sender sees a ``P2PError``),
* **corrupt_frame** — one byte of the signed frame is flipped in flight
  (the receiver's signature check drops it; the sender times out on the
  ack),
* **withhold_ack** — the receiver persists the file but the ack never
  leaves (the crash-between-write-and-ack window; exercises the
  idempotent re-send path),
* **latency** — an extra await before the frame goes out,
* **peer death** — a peer id is marked dead: it answers no rendezvous,
  accepts no dial, and every in-flight transport to it fails on the next
  send.  :meth:`FaultPlane.kill_after` arms death after N successful
  sends — "the peer vanished mid-backup".
* **mid-transfer cuts** — :meth:`FaultPlane.arm_cut` arms exact byte
  offsets per peer; the chunked sender dies on the FILE_PART covering an
  armed offset (``cut_part`` is the rate-based version).  The resume
  protocol (docs/transfer.md) must continue from the persisted offset.
* **flaky reconnect** — ``reconnect_fail`` makes a fraction of p2p dials
  fail outright, the residential-NAT reconnect lottery.

Two properties the acceptance bar demands, by construction:

* **Inert when disabled.**  The module-global :data:`PLANE` is ``None``
  unless explicitly installed; every hook site is a single
  ``faults.PLANE is not None`` check, so the production path pays one
  attribute load and no frames, allocations, or RNG draws.
* **Deterministic under a seed.**  Every decision site draws from its own
  ``random.Random`` seeded by ``(plane seed, site name)``, so the answer
  stream of one site is a pure function of the seed and that site's query
  count — independent of how asyncio interleaves *other* sites.  Tests
  that need exact placement use :meth:`arm` (fire on the Nth query) which
  bypasses probability entirely.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import random
from typing import Dict, Optional, Set

from ..obs import journal as obs_journal
from ..obs import metrics as obs_metrics

#: send_data asks this before shipping a FILE frame
ACT_DROP = "drop"
ACT_CORRUPT = "corrupt"

_INJECTIONS = obs_metrics.counter(
    "bkw_fault_injections_total", "Fault-plane firings by hook site",
    ("site",))


def _record_injection(site: str) -> None:
    # metric label is the hook prefix (site minus the ':<peer hex>' tail)
    # so cardinality stays bounded; the journal keeps the full site
    _INJECTIONS.inc(site=site.split(":", 1)[0])
    obs_journal.emit("fault", site=site)


def _site_seed(seed: int, site: str) -> int:
    digest = hashlib.blake2s(f"{seed}:{site}".encode()).digest()[:8]
    return int.from_bytes(digest, "little")


class FaultPlane:
    """One installed chaos configuration.

    ``rates`` are per-query probabilities in [0, 1]; ``arm`` pins exact
    query indices per site for deterministic tests.  Site names follow
    ``<hook>:<peer hex>`` so each peer direction has an independent
    stream.
    """

    def __init__(self, seed: int = 0, *, drop_send: float = 0.0,
                 corrupt_frame: float = 0.0, withhold_ack: float = 0.0,
                 latency: float = 0.0, latency_s: float = 0.05,
                 cut_part: float = 0.0, reconnect_fail: float = 0.0):
        self.seed = int(seed)
        self.drop_send = float(drop_send)
        self.corrupt_frame = float(corrupt_frame)
        self.withhold_ack = float(withhold_ack)
        self.latency = float(latency)
        self.latency_s = float(latency_s)
        self.cut_part = float(cut_part)
        self.reconnect_fail = float(reconnect_fail)
        self.dead: Set[bytes] = set()
        self._cuts: Dict[bytes, Set[int]] = {}
        self._kill_after: Dict[bytes, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._queries: Dict[str, int] = {}
        self._armed: Dict[str, Set[int]] = {}
        #: observability: fires per site, for test assertions and logs
        self.fired: Dict[str, int] = {}

    # --- deterministic decision core ---------------------------------------

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(
                _site_seed(self.seed, site))
        return rng

    def arm(self, site: str, *query_indices: int) -> None:
        """Force ``site`` to fire on exactly these (0-based) query indices,
        regardless of rates — the deterministic-placement test API."""
        self._armed.setdefault(site, set()).update(query_indices)

    def decide(self, site: str, rate: float) -> bool:
        """One decision draw at ``site``; counts queries and fires."""
        q = self._queries.get(site, 0)
        self._queries[site] = q + 1
        hit = q in self._armed.get(site, ())
        if not hit and rate > 0.0:
            hit = self._rng(site).random() < rate
        elif rate > 0.0:
            # keep the stream position consistent whether or not armed
            # indices interleave, so arming never shifts later draws
            self._rng(site).random()
        if hit:
            self.fired[site] = self.fired.get(site, 0) + 1
            _record_injection(site)
        return hit

    # --- peer death ---------------------------------------------------------

    def kill(self, peer_id: bytes) -> None:
        self.dead.add(bytes(peer_id))

    def revive(self, peer_id: bytes) -> None:
        self.dead.discard(bytes(peer_id))
        self._kill_after.pop(bytes(peer_id), None)

    def kill_after(self, peer_id: bytes, sends: int) -> None:
        """Peer drops dead after ``sends`` more successful FILE sends."""
        self._kill_after[bytes(peer_id)] = int(sends)

    def is_dead(self, peer_id: bytes) -> bool:
        return bytes(peer_id) in self.dead

    def _count_send(self, peer_id: bytes) -> bool:
        """Advance the kill_after counter; True when this send is the one
        that finds the peer dead."""
        k = bytes(peer_id)
        if k not in self._kill_after:
            return False
        if self._kill_after[k] <= 0:
            del self._kill_after[k]
            self.dead.add(k)
            return True
        self._kill_after[k] -= 1
        return False

    # --- hooks consumed by net/p2p.py ---------------------------------------

    async def on_send(self, peer_id: bytes) -> Optional[str]:
        """Called by Transport.send_data before shipping a FILE frame.
        Returns ACT_DROP / ACT_CORRUPT / None; sleeps injected latency."""
        hexid = bytes(peer_id).hex()
        if self.latency > 0.0 and self.decide(f"send.latency:{hexid}",
                                              self.latency):
            await asyncio.sleep(self.latency_s)
        if self._count_send(peer_id) or self.is_dead(peer_id):
            self.fired[f"send.dead:{hexid}"] = \
                self.fired.get(f"send.dead:{hexid}", 0) + 1
            _record_injection(f"send.dead:{hexid}")
            return ACT_DROP
        if self.decide(f"send.drop:{hexid}", self.drop_send):
            return ACT_DROP
        if self.decide(f"send.corrupt:{hexid}", self.corrupt_frame):
            return ACT_CORRUPT
        return None

    def arm_cut(self, peer_id: bytes, *offsets: int) -> None:
        """Arm exact-offset mid-transfer cuts toward ``peer_id``: the
        connection dies on the FILE_PART whose byte range covers an armed
        offset (one-shot per offset) — "the WAN link dropped at byte N of
        the shard", the deterministic-resume test API."""
        self._cuts.setdefault(bytes(peer_id), set()).update(
            int(o) for o in offsets)

    def on_send_part(self, peer_id: bytes, offset: int,
                     size: int) -> Optional[str]:
        """Called before shipping a FILE_PART covering
        ``[offset, offset + size)``.  Exact-offset cuts fire first (armed,
        one-shot), then the seeded ``cut_part`` rate."""
        hexid = bytes(peer_id).hex()
        armed = self._cuts.get(bytes(peer_id))
        if armed:
            hit = [c for c in armed if offset <= c < offset + size]
            if hit:
                for c in hit:
                    armed.discard(c)
                site = f"send.cut:{hexid}"
                self.fired[site] = self.fired.get(site, 0) + 1
                _record_injection(site)
                return ACT_DROP
        if self.cut_part > 0.0 and self.decide(f"send.cut:{hexid}",
                                               self.cut_part):
            return ACT_DROP
        return None

    def flaky_reconnect(self, peer_id: bytes) -> bool:
        """Called by P2PNode.connect before dialing: True = this dial is
        refused, as a flaky residential peer would."""
        return self.decide(f"dial.flaky:{bytes(peer_id).hex()}",
                           self.reconnect_fail)

    def corrupt(self, raw: bytes, peer_id: bytes) -> bytes:
        """Flip one deterministically chosen byte of the signed frame."""
        rng = self._rng(f"corrupt.byte:{bytes(peer_id).hex()}")
        i = rng.randrange(len(raw))
        return raw[:i] + bytes([raw[i] ^ 0xFF]) + raw[i + 1:]

    def withhold_ack_now(self, peer_id: bytes) -> bool:
        """Called by Receiver.run after the sink persisted the file."""
        return self.decide(f"recv.withhold_ack:{bytes(peer_id).hex()}",
                           self.withhold_ack)


#: The installed plane; None (the default) disables every hook.
PLANE: Optional[FaultPlane] = None


def install(plane: FaultPlane) -> FaultPlane:
    global PLANE
    PLANE = plane
    return plane


def uninstall() -> None:
    global PLANE
    PLANE = None


def from_env(spec: Optional[str] = None) -> Optional[FaultPlane]:
    """Parse a ``BKW_FAULTS`` spec into a plane (None when unset/empty).

    Format: comma-separated ``key=value``; keys ``seed``, ``drop_send``,
    ``corrupt_frame``, ``withhold_ack``, ``latency`` (probability),
    ``latency_s`` (seconds), ``kill`` ('+'-separated hex client ids).
    Example: ``BKW_FAULTS=seed=7,drop_send=0.05,latency=0.2,latency_s=0.1``
    """
    spec = os.environ.get("BKW_FAULTS", "") if spec is None else spec
    spec = spec.strip()
    if not spec:
        return None
    kw: Dict[str, float] = {}
    kills = []
    for part in spec.split(","):
        if not part.strip():
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        value = value.strip()
        if key == "kill":
            kills.extend(bytes.fromhex(v) for v in value.split("+") if v)
        elif key == "seed":
            kw["seed"] = int(value)
        elif key in ("drop_send", "corrupt_frame", "withhold_ack",
                     "latency", "latency_s", "cut_part", "reconnect_fail"):
            kw[key] = float(value)
        else:
            raise ValueError(f"unknown BKW_FAULTS key {key!r}")
    seed = int(kw.pop("seed", 0))
    plane = FaultPlane(seed, **kw)
    for k in kills:
        plane.kill(k)
    return plane


# env activation at import time: the p2p module imports this module, so a
# process started with BKW_FAULTS set gets the plane with no test plumbing
if os.environ.get("BKW_FAULTS"):
    PLANE = from_env()
