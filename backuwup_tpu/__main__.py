"""Executable entry points: ``python -m backuwup_tpu client|server``.

The client main mirrors ``client/src/main.rs:44-85``: boot config store ->
key manager (first-run guide / restore-from-phrase) -> panic hook -> UI
messenger -> P2P handlers -> long-lived server-WS + UI dashboard tasks.
The server main mirrors ``server/src/main.rs:40-65``: database + the
singletons behind an HTTP+WS router.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys
from pathlib import Path
from typing import Optional

# Honor an explicit JAX_PLATFORMS before any backend initializes: some
# accelerator rigs install a sitecustomize that re-pins JAX to the
# hardware plugin through the config API (which beats the env var), so a
# child process asked to run on CPU would instead block on an
# unavailable accelerator.  The config API also wins for us.
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:  # pragma: no cover - jax not installed / exotic rig
        pass


def _addr_host(addr: str) -> str:
    """Host part of a ``host:port`` address, handling bracketed IPv6
    literals like ``[::1]:8080`` and bare ``::1``."""
    from urllib.parse import urlsplit
    try:
        host = urlsplit(f"//{addr}").hostname
        if host:
            return host
    except ValueError:
        pass
    return addr  # bare IPv6 like ::1, or something urlsplit rejects


def _install_excepthook(messenger) -> None:
    """Panic hook (client/src/main.rs:53-61): report to the UI channel,
    then exit nonzero."""
    previous = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            messenger.panic(f"{exc_type.__name__}: {exc}")
        finally:
            previous(exc_type, exc, tb)
            sys.exit(70)

    sys.excepthook = hook


async def _run_client(args) -> int:
    from .app import ClientApp
    from .ui import cli as ui_cli
    from .ui.messenger import Messenger
    from .ui.server import UIServer
    from .store import Store

    messenger = Messenger()
    _install_excepthook(messenger)
    messenger.subscribe(lambda ev: print(
        f"[{ev.kind}] {ev.payload.get('text', '')}".rstrip(), flush=True)
        if ev.kind in ("message", "panic", "error") else None)

    # first-run guide: fresh identity or restore-from-phrase (cli.rs:10-23)
    root_secret: Optional[bytes] = None
    probe = Store(args.config_dir and Path(args.config_dir))
    has_identity = probe.get_root_secret() is not None
    probe.close()
    if not has_identity:
        if args.restore_phrase:
            from .crypto import parse_recovery
            try:
                root_secret = parse_recovery(args.restore_phrase)
            except ValueError as e:
                print(f"invalid --restore-phrase: {e}", file=sys.stderr)
                return 2
        elif sys.stdin.isatty() and not args.non_interactive:
            root_secret = ui_cli.first_run_guide()

    # TLS is on by default (reference posture); a loopback server with no
    # explicit USE_TLS / CA configured is the local-testing case
    # (docs/src/client.md:22) — default it to plaintext so the
    # out-of-the-box `server` + `client` pairing connects.  The decision
    # is passed explicitly to ClientApp (never by mutating os.environ,
    # which would leak into every ServerClient in the process).
    addr = args.server_addr or os.environ.get("SERVER_ADDR",
                                              "127.0.0.1:8080")
    tls: Optional[bool] = None
    if args.no_tls:
        tls = False
    elif "USE_TLS" not in os.environ and "TLS_CA_FILE" not in os.environ \
            and _addr_host(addr) in ("127.0.0.1", "localhost", "::1"):
        print("note: loopback server and no TLS config; using plaintext "
              "(set USE_TLS=1 or TLS_CA_FILE to force TLS)", flush=True)
        tls = False

    app = ClientApp(
        config_dir=args.config_dir and Path(args.config_dir),
        data_dir=args.data_dir and Path(args.data_dir),
        server_addr=args.server_addr,
        messenger=messenger,
        root_secret=root_secret,
        tls=tls)
    if app.fresh_identity and root_secret is None:
        ui_cli.print_recovery_phrase(app.keys.root_secret)
    if args.backup_path:
        app.store.set_backup_path(args.backup_path)

    await app.start()
    ui = UIServer(app, bind=args.ui_bind)
    url = await ui.start()
    messenger.log(f"dashboard at {url}")

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    messenger.log("shutting down")
    await ui.stop()
    await app.stop()
    return 0


async def _run_server(args) -> int:
    from .net.server import CoordinationServer

    server = CoordinationServer(db_path=args.db)
    host, _, port = args.bind.rpartition(":")
    host = host or "127.0.0.1"
    ssl_context = None
    cert = os.environ.get("TLS_CERT_FILE")
    key = os.environ.get("TLS_KEY_FILE")
    if cert and key:
        import ssl
        ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ssl_context.load_cert_chain(cert, key)
    port = await server.start(host, int(port), ssl_context=ssl_context)
    scheme = "https" if ssl_context else "http"
    print(f"coordination server listening on {host}:{port} ({scheme})",
          flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()
    await server.stop()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="backuwup_tpu",
        description="peer-to-peer encrypted backup (TPU-accelerated dedup)")
    sub = parser.add_subparsers(dest="role", required=True)

    c = sub.add_parser("client", help="run the backup client + dashboard")
    c.add_argument("--config-dir", help="state directory (CONFIG_DIR env)")
    c.add_argument("--data-dir", help="data directory (DATA_DIR env)")
    c.add_argument("--server-addr", help="coordination server URL "
                                         "(SERVER_ADDR env)")
    c.add_argument("--ui-bind", help="dashboard bind, host:port "
                                     "(UI_BIND_ADDR env, default "
                                     "127.0.0.1:8102)")
    c.add_argument("--backup-path", help="directory to back up")
    c.add_argument("--restore-phrase",
                   help="recover an identity from this phrase — 24-word "
                        "mnemonic or base32 code (first run)")
    c.add_argument("--non-interactive", action="store_true",
                   help="never prompt; generate a fresh identity if none")
    c.add_argument("--no-tls", action="store_true",
                   help="plaintext control plane (USE_TLS=0)")

    s = sub.add_parser("server", help="run the coordination server")
    s.add_argument("--bind", default="127.0.0.1:8100",
                   help="listen address, host:port")
    s.add_argument("--db", default="backuwup_server.sqlite3",
                   help="SQLite database path")

    args = parser.parse_args(argv)
    runner = _run_client if args.role == "client" else _run_server
    try:
        return asyncio.run(runner(args))
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    sys.exit(main())
