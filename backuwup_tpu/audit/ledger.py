"""Audit ledger policy: pass/fail/miss accounting, backoff, demotion.

Three outcomes, three severities:

* **pass** — proofs all matched.  Counters reset, peer is (re-)promoted,
  next audit after the normal interval.
* **fail** — the peer ANSWERED and the answer proves data loss (bad
  digest, missing/short file).  Demotes after
  ``AUDIT_DEMOTE_FAILURES`` consecutive failures (default 1: a proven
  corruption is immediately disqualifying).
* **miss** — the peer could not be reached during its window.  Offline is
  normal for a desktop P2P fleet, so misses demote only after
  ``AUDIT_DEMOTE_MISSES`` consecutive windows, with exponential backoff
  between retries so a long-dead peer costs ~O(log) audit attempts.

Demoted peers drop out of ``Store.find_peers_with_storage`` — the
free-space ordering new packfiles are matched against — but their ledger
history survives, and a later pass re-promotes them.
"""

from __future__ import annotations

import time
from dataclasses import replace
from typing import Optional

from .. import defaults
from ..store import AuditState, Store
from ..utils import retry


def _backoff(consecutive: int) -> float:
    # the unified retry policy (utils/retry.py); AUDIT pins jitter=0 so the
    # persisted next_due schedule stays exactly predictable
    return retry.AUDIT.delay_s(consecutive)


def record_pass(store: Store, peer: bytes,
                now: Optional[float] = None) -> AuditState:
    now = time.time() if now is None else now
    st = store.get_audit_state(peer)
    st = replace(st, passes=st.passes + 1, consecutive_failures=0,
                 consecutive_misses=0, demoted=False, last_result="pass",
                 last_audit=now, next_due=now + defaults.AUDIT_INTERVAL_S)
    store.put_audit_state(st)
    return st


def record_fail(store: Store, peer: bytes, detail: str = "",
                now: Optional[float] = None) -> AuditState:
    now = time.time() if now is None else now
    st = store.get_audit_state(peer)
    consecutive = st.consecutive_failures + 1
    st = replace(st, failures=st.failures + 1,
                 consecutive_failures=consecutive, consecutive_misses=0,
                 demoted=(st.demoted
                          or consecutive >= defaults.AUDIT_DEMOTE_FAILURES),
                 last_result=f"fail: {detail}" if detail else "fail",
                 last_audit=now, next_due=now + _backoff(consecutive))
    store.put_audit_state(st)
    return st


def record_miss(store: Store, peer: bytes,
                now: Optional[float] = None) -> AuditState:
    now = time.time() if now is None else now
    st = store.get_audit_state(peer)
    consecutive = st.consecutive_misses + 1
    st = replace(st, misses=st.misses + 1, consecutive_misses=consecutive,
                 demoted=(st.demoted
                          or consecutive >= defaults.AUDIT_DEMOTE_MISSES),
                 last_result="miss", last_audit=now,
                 next_due=now + _backoff(consecutive))
    store.put_audit_state(st)
    return st
