"""Challenge-table construction: precompute audit probes at pack time.

The verifier cannot recompute window digests later — its plaintext
packfiles are deleted as soon as a peer acks them (``send.rs:277-289``
semantics) — so every future challenge must be decided, and its expected
answer hashed, while the bytes are still local.  That is exactly the
precomputed-token construction of Juels & Kaliski (PORs, CCS 2007 §3):
each table entry is single-use, consumed in order by a cursor the store
tracks per packfile.

The whole table is hashed in ONE ``backend.digest_many`` batch, so on the
TPU backend pack-time audit prep rides the same device dispatch as chunk
fingerprinting (``ops/digest_pool.py``).
"""

from __future__ import annotations

import os
from typing import List, Sequence

from .. import defaults
from ..snapshot.blob_index import ChallengeEntry
from ..wire import AUDIT_NONCE_LEN, StorageChallenge


def sample_windows(size: int, count: int,
                   window: int = defaults.AUDIT_WINDOW_BYTES,
                   rand=os.urandom) -> List[tuple]:
    """``count`` uniform random (offset, length) windows over ``size`` bytes.

    Length is clamped to the file, offsets are uniform over the valid
    range, so every byte of the packfile is sampled with equal probability
    — the uniformity the detection bound in docs/audit.md relies on.
    """
    if size <= 0:
        raise ValueError("cannot sample windows of an empty packfile")
    length = min(window, size)
    span = size - length + 1
    out = []
    for _ in range(count):
        offset = int.from_bytes(rand(8), "little") % span
        out.append((offset, length))
    return out


def build_challenge_table(backend, data: bytes,
                          count: int = defaults.AUDIT_CHALLENGES_PER_PACKFILE,
                          window: int = defaults.AUDIT_WINDOW_BYTES,
                          rand=os.urandom) -> List[ChallengeEntry]:
    """Precompute ``count`` single-use challenges over packfile ``data``.

    Each entry keys its digest with a fresh random nonce so a peer cannot
    precompute answers, dedup windows, or replay another verifier's
    transcript: digest = blake3(nonce || window-bytes), all entries hashed
    in one batched device call.
    """
    windows = sample_windows(len(data), count, window, rand)
    nonces = [rand(AUDIT_NONCE_LEN) for _ in windows]
    pieces = [n + data[off:off + ln] for n, (off, ln) in zip(nonces, windows)]
    digests = backend.digest_many(pieces)
    return [ChallengeEntry(offset=off, length=ln, nonce=n, digest=d)
            for (off, ln), n, d in zip(windows, nonces, digests)]


def to_wire(packfile_id: bytes,
            entries: Sequence[ChallengeEntry]) -> tuple:
    """Strip expected digests: what actually goes to the prover."""
    return tuple(StorageChallenge(packfile_id=bytes(packfile_id),
                                  offset=e.offset, length=e.length,
                                  nonce=e.nonce)
                 for e in entries)


def detection_probability(sampled_fraction: float, n: int) -> float:
    """P(detect) = 1 - (1 - f)^n for n independent uniform windows when a
    fraction f of the file's bytes is corrupt/missing (docs/audit.md)."""
    return 1.0 - (1.0 - sampled_fraction) ** n
