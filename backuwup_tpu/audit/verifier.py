"""Verifier side: pick single-use challenges, judge the proof batch.

Challenge consumption is crash-safe by construction: the per-packfile
cursor in the store advances the moment entries are selected, BEFORE the
challenges leave the machine, so no table entry is ever sent twice — even
if the round dies mid-flight.  A replayed or reordered proof therefore
never matches a live expectation (and the transport's session-nonce +
sequence header already drops stale frames before they get here).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

from .. import defaults
from ..snapshot.blob_index import ChallengeTable
from ..store import Store
from ..wire import ProofStatus, StorageChallenge, StorageProof
from .challenge import to_wire


@dataclass(frozen=True)
class AuditResult:
    """Outcome of one audit round against one peer."""

    passed: bool
    checked: int
    detail: str = ""


def select_challenges(
        store: Store, tables: ChallengeTable, peer_id: bytes,
        samples: int = defaults.AUDIT_SAMPLES_PER_ROUND,
) -> Tuple[List[StorageChallenge], List[bytes]]:
    """Draw up to ``samples`` unused table entries across everything the
    peer holds, round-robin over packfiles so one big packfile cannot
    starve the rest.  Returns (wire challenges, expected digests).

    A placement with shard_index >= 0 is audited under its 13-byte shard
    id (erasure/stripe.py) — the challenge table, cursor, and the
    prover's on-disk file are all keyed by that id."""
    held = [pid if idx < 0 else pid + bytes([idx])
            for pid, _, idx in store.shard_placements_for_peer(peer_id)]
    pools = []
    for pid in held:
        if not tables.has(pid):
            continue
        entries = tables.load(pid)
        cursor = store.get_audit_cursor(pid)
        if cursor < len(entries):
            pools.append([pid, entries, cursor])
    challenges: List[StorageChallenge] = []
    expected: List[bytes] = []
    while pools and len(challenges) < samples:
        for pool in list(pools):
            pid, entries, cursor = pool
            entry = entries[cursor]
            challenges.extend(to_wire(pid, [entry]))
            expected.append(entry.digest)
            pool[2] = cursor + 1
            store.set_audit_cursor(pid, pool[2])  # burn before sending
            if pool[2] >= len(entries):
                pools.remove(pool)
            if len(challenges) >= samples:
                break
    return challenges, expected


def check_proofs(challenges: Sequence[StorageChallenge],
                 expected: Sequence[bytes],
                 proofs: Sequence[StorageProof]) -> AuditResult:
    """Judge a proof batch positionally: proof i answers challenge i."""
    if len(proofs) != len(challenges):
        return AuditResult(
            passed=False, checked=len(proofs),
            detail=f"answered {len(proofs)}/{len(challenges)} challenges")
    failures = []
    for c, want, p in zip(challenges, expected, proofs):
        if bytes(p.packfile_id) != bytes(c.packfile_id):
            failures.append(f"{bytes(c.packfile_id).hex()[:8]}: wrong packfile"
                            " in proof")
        elif p.status != ProofStatus.OK:
            failures.append(f"{bytes(c.packfile_id).hex()[:8]}:"
                            f" {p.status.name.lower()}")
        elif bytes(p.digest) != bytes(want):
            failures.append(f"{bytes(c.packfile_id).hex()[:8]}: digest"
                            f" mismatch @{c.offset}+{c.length}")
    if failures:
        return AuditResult(passed=False, checked=len(challenges),
                           detail="; ".join(failures))
    return AuditResult(passed=True, checked=len(challenges))
