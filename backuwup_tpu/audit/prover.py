"""Prover side: answer a challenge batch from the obfuscated store.

The prover holds foreign packfiles XOR-obfuscated with its local 4-byte
key (``received_files_writer.rs:76-78`` idiom), so each sampled window is
read from disk (seek + short read — never the whole packfile), de-obfuscated
with the key rotated to the window's offset, and hashed as
blake3(nonce || window).  All OK windows go to the device in ONE
``backend.digest_many`` batch — the audit hot path is the same batched
digest dispatch backup itself uses.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Sequence

from ..net.p2p import obfuscate
from ..store import Store
from ..wire import (PACKFILE_ID_LEN, ProofStatus, StorageChallenge,
                    StorageProof)


def deobfuscate_window(data: bytes, key: bytes, offset: int) -> bytes:
    """Undo the repeating-XOR for a slice starting at ``offset`` of the
    original stream: rotate the 4-byte key by offset mod 4 and XOR."""
    r = offset % 4
    return obfuscate(data, key[r:] + key[:r])


def read_window(path: Path, offset: int, length: int) -> bytes:
    with path.open("rb") as f:
        f.seek(offset)
        return f.read(length)


def compute_proofs(store: Store, backend, verifier_id: bytes,
                   challenges: Sequence[StorageChallenge]) -> List[StorageProof]:
    """One StorageProof per challenge, in challenge order.

    MISSING when the packfile is gone, SHORT when it exists but cannot
    cover the challenged window — both are honest failure admissions that
    let the verifier distinguish data loss from transport trouble.
    """
    key = store.get_obfuscation_key()
    if key is None:
        raise ValueError("obfuscation key not initialized")
    base = store.received_dir(verifier_id)
    proofs: List[StorageProof] = [None] * len(challenges)  # type: ignore
    pieces = []
    piece_slots = []
    for i, c in enumerate(challenges):
        # 12-byte ids name whole packfiles, 13-byte ids name erasure
        # shards; ReceivedFilesWriter stores them in sibling subtrees
        cid = bytes(c.packfile_id)
        sub = "shard" if len(cid) == PACKFILE_ID_LEN + 1 else "pack"
        path = base / sub / cid.hex()
        if not path.is_file():
            proofs[i] = StorageProof(packfile_id=c.packfile_id,
                                     status=ProofStatus.MISSING)
            continue
        if path.stat().st_size < c.offset + c.length:
            proofs[i] = StorageProof(packfile_id=c.packfile_id,
                                     status=ProofStatus.SHORT)
            continue
        window = deobfuscate_window(read_window(path, c.offset, c.length),
                                    key, c.offset)
        pieces.append(bytes(c.nonce) + window)
        piece_slots.append(i)
    if pieces:
        for i, digest in zip(piece_slots, backend.digest_many(pieces)):
            c = challenges[i]
            proofs[i] = StorageProof(packfile_id=c.packfile_id,
                                     status=ProofStatus.OK,
                                     digest=bytes(digest))
    return proofs
