"""Storage attestation: challenge-response audits of peer-held packfiles.

No reference equivalent — the thesis flags undetected data loss at peers
as the open risk of storage-for-storage trading.  This subsystem closes it
with PoR-style random-window audits (Juels & Kaliski, CCS 2007; Shacham &
Waters, ASIACRYPT 2008): the verifier samples random (packfile, offset,
length) windows, the prover answers with keyed BLAKE3 digests computed in
one device batch over the existing digest pipeline, and outcomes feed a
per-peer ledger that demotes unreliable peers out of the free-space
ordering.  See docs/audit.md for the protocol and sampling math.
"""

from .challenge import build_challenge_table, detection_probability
from .ledger import record_fail, record_miss, record_pass
from .prover import compute_proofs
from .verifier import AuditResult, check_proofs, select_challenges

__all__ = [
    "AuditResult",
    "build_challenge_table",
    "check_proofs",
    "compute_proofs",
    "detection_probability",
    "record_fail",
    "record_miss",
    "record_pass",
    "select_challenges",
]
