"""bkwlint: AST-based invariant linter for backuwup_tpu.

Five rules over a shared package loader + call-graph:

* **BKW001** — blocking I/O reachable from ``async def`` without the
  executor seam (event-loop purity).
* **BKW002** — ``await`` while holding a ``threading.Lock``/``RLock``.
* **BKW003** — crash-seam coverage: durable commits need an adjacent
  ``faults.crashpoint``, and the crash-site registry must be exact.
* **BKW004** — ``bkw_*`` metric families vs ``docs/observability.md``,
  both directions, with consistent label sets.
* **BKW005** — wire-enum members vs serve/dispatch arms in net/p2p.py.

Entry points: ``scripts/bkwlint.py``, ``python -m
backuwup_tpu.analysis``, or :func:`run_lint` directly.  See
``docs/analysis.md``.
"""

from .baseline import (BaselineError, apply_baseline, load_baseline,
                       write_baseline)
from .callgraph import CallGraph, build_graph
from .findings import (RULE_IDS, SEV_ERROR, SEV_WARNING, Finding,
                       LintReport)
from .loader import Package, load_package
from .rules_crash import static_crash_sites
from .runner import LintConfig, collect_findings, load_graph, run_lint

__all__ = [
    "BaselineError", "CallGraph", "Finding", "LintConfig", "LintReport",
    "Package", "RULE_IDS", "SEV_ERROR", "SEV_WARNING", "apply_baseline",
    "build_graph", "collect_findings", "load_baseline", "load_graph",
    "load_package", "run_lint", "static_crash_sites", "write_baseline",
]
