"""Package-wide call-graph with name resolution good enough for the
intra-package idioms this codebase actually uses.

The graph is deliberately *not* a type checker.  It resolves exactly the
call shapes the rules need to follow — and treats everything else as an
opaque leaf:

* module-level calls: ``helper()``, ``durable.write_replace()``,
  ``from .store import Store; Store(...)`` (a class call resolves to its
  ``__init__``);
* ``self.method()`` through the enclosing class, its in-package bases,
  *and* its in-package subclasses (the mixin idiom:
  ``_ResumableSinkMixin.sink_part`` touching ``self.partials`` that only
  ``ReceivedFilesWriter.__init__`` assigns);
* one level of instance-attribute typing: ``self.x = C(...)``,
  ``self.x = C.load(...)``, and ``def __init__(self, x: C)`` +
  ``self.x = x`` all record ``x: C`` so ``self.x.m()`` resolves to
  ``C.m``;
* locally defined nested functions called by name.

Nested ``def``/``lambda`` bodies are **not** part of the enclosing
function's behavior — defining a closure is not calling it — so a
``pack_thread`` handed to ``run_in_executor`` never pollutes its async
parent.  Each nested function is its own node.

Every function node carries its :class:`CallSite` list (resolved target
+ dotted repr), which is all the rules need: BKW001 walks edges, BKW003
walks them backwards, and everything pattern-matches on the repr.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .loader import EXTERNAL, ModuleInfo, Package, dotted_repr

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclass
class CallSite:
    """One call expression inside a function body."""

    node: ast.Call
    repr: str  # dotted source form, e.g. "self.index.flush"
    norm: str  # external-alias-normalized form, e.g. "time.sleep"
    target: Optional[str]  # resolved FuncInfo.fid, if any


@dataclass
class FuncInfo:
    fid: str  # "rel::qualname"
    module: ModuleInfo
    qualname: str
    node: object  # ast.FunctionDef | ast.AsyncFunctionDef
    is_async: bool
    cls: Optional[str]  # owning ClassInfo.cid
    parent: Optional[str]  # enclosing FuncInfo.fid for nested defs
    calls: List[CallSite] = field(default_factory=list)
    nested: Dict[str, str] = field(default_factory=dict)  # name -> fid

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclass
class ClassInfo:
    cid: str  # "rel::ClassName"
    module: ModuleInfo
    name: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)  # resolved cids
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fid
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> cid


class CallGraph:
    def __init__(self, pkg: Package):
        self.pkg = pkg
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self._toplevel: Dict[Tuple[str, str], str] = {}  # (mod, name)->fid
        self._mod_classes: Dict[Tuple[str, str], str] = {}
        self._derived: Dict[str, List[str]] = {}
        self._callers: Dict[str, Set[str]] = {}
        self._build()

    # --- construction -------------------------------------------------------

    def _build(self) -> None:
        for mod in self.pkg.modules.values():
            self._scan_module(mod)
        for cls in self.classes.values():
            self._resolve_bases(cls)
        for cls in self.classes.values():
            self._infer_attr_types(cls)
        for fn in self.functions.values():
            self._resolve_calls(fn)
        for fn in self.functions.values():
            for cs in fn.calls:
                if cs.target:
                    self._callers.setdefault(cs.target, set()).add(fn.fid)

    def _scan_module(self, mod: ModuleInfo) -> None:
        for node in mod.tree.body:
            if isinstance(node, _FUNC_NODES):
                self._add_function(mod, node, node.name, None, None)
            elif isinstance(node, ast.ClassDef):
                cid = f"{mod.rel}::{node.name}"
                cls = ClassInfo(cid=cid, module=mod, name=node.name,
                                node=node)
                self.classes[cid] = cls
                self._mod_classes[(mod.name, node.name)] = cid
                for item in node.body:
                    if isinstance(item, _FUNC_NODES):
                        fid = self._add_function(
                            mod, item, f"{node.name}.{item.name}", cid,
                            None)
                        cls.methods[item.name] = fid

    def _add_function(self, mod: ModuleInfo, node, qualname: str,
                      cls: Optional[str], parent: Optional[str]) -> str:
        fid = f"{mod.rel}::{qualname}"
        info = FuncInfo(fid=fid, module=mod, qualname=qualname, node=node,
                        is_async=isinstance(node, ast.AsyncFunctionDef),
                        cls=cls, parent=parent)
        self.functions[fid] = info
        if parent is None and cls is None:
            self._toplevel[(mod.name, node.name)] = fid
        for child in self._body_walk(node):
            if isinstance(child, _FUNC_NODES):
                cfid = self._add_function(
                    mod, child, f"{qualname}.<locals>.{child.name}", cls,
                    fid)
                info.nested[child.name] = cfid
        return fid

    @staticmethod
    def _body_walk(func_node) -> Iterable[ast.AST]:
        """Every node lexically inside ``func_node`` but NOT inside a
        nested def/lambda (those are separate nodes)."""
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, _FUNC_NODES + (ast.Lambda,)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def body_nodes(self, fn: FuncInfo) -> Iterable[ast.AST]:
        return self._body_walk(fn.node)

    # --- class hierarchy ----------------------------------------------------

    def _resolve_class_name(self, mod: ModuleInfo,
                            node: ast.AST) -> Optional[str]:
        """An expression naming a class -> cid (in-package only)."""
        rep = dotted_repr(node)
        if rep is None:
            return None
        parts = rep.split(".")
        if len(parts) == 1:
            cid = self._mod_classes.get((mod.name, parts[0]))
            if cid:
                return cid
            fi = mod.from_imports.get(parts[0])
            if fi:
                return self._mod_classes.get(fi)
            sub = mod.imports.get(parts[0])
            if sub and not sub.startswith(EXTERNAL):
                return None  # a module, not a class
            return None
        head, rest = parts[0], parts[1:]
        target_mod = mod.imports.get(head)
        if target_mod and not target_mod.startswith(EXTERNAL) \
                and len(rest) == 1:
            return self._mod_classes.get((target_mod, rest[0]))
        return None

    def _resolve_bases(self, cls: ClassInfo) -> None:
        for base in cls.node.bases:
            cid = self._resolve_class_name(cls.module, base)
            if cid:
                cls.bases.append(cid)
                self._derived.setdefault(cid, []).append(cls.cid)

    def _class_family(self, cid: str) -> List[str]:
        """cid + bases (transitive) + derived (transitive), cycles-safe."""
        seen: List[str] = []
        stack = [cid]
        while stack:
            c = stack.pop()
            if c in seen or c not in self.classes:
                continue
            seen.append(c)
            stack.extend(self.classes[c].bases)
            stack.extend(self._derived.get(c, []))
        return seen

    def lookup_method(self, cid: str, name: str) -> Optional[str]:
        for c in self._class_family(cid):
            fid = self.classes[c].methods.get(name)
            if fid:
                return fid
        return None

    def _lookup_attr_type(self, cid: str, attr: str) -> Optional[str]:
        for c in self._class_family(cid):
            t = self.classes[c].attr_types.get(attr)
            if t:
                return t
        return None

    def _infer_attr_types(self, cls: ClassInfo) -> None:
        mod = cls.module
        for item in cls.node.body:
            if not isinstance(item, _FUNC_NODES):
                continue
            ann: Dict[str, Optional[str]] = {}
            for arg in list(item.args.args) + list(item.args.kwonlyargs):
                if arg.annotation is not None:
                    ann[arg.arg] = self._resolve_class_name(
                        mod, arg.annotation)
            for n in self._body_walk(item):
                if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                    continue
                tgt = n.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                tcid = None
                v = n.value
                if isinstance(v, ast.Call):
                    tcid = self._resolve_class_name(mod, v.func)
                    if tcid is None and isinstance(v.func, ast.Attribute):
                        # alternate constructor: C.load(...)
                        tcid = self._resolve_class_name(mod, v.func.value)
                elif isinstance(v, ast.Name):
                    tcid = ann.get(v.id)
                if tcid:
                    cls.attr_types.setdefault(tgt.attr, tcid)

    # --- call resolution ----------------------------------------------------

    def _normalize(self, mod: ModuleInfo, rep: str) -> str:
        """Map import aliases to real module names for pattern matching
        (``import subprocess as sp`` -> ``subprocess.*``)."""
        parts = rep.split(".")
        target = mod.imports.get(parts[0])
        if target and target.startswith(EXTERNAL + ":"):
            real = target[len(EXTERNAL) + 1:]
            return ".".join([real] + parts[1:])
        return rep

    def _resolve_target(self, fn: FuncInfo,
                        call: ast.Call) -> Optional[str]:
        mod = fn.module
        f = call.func
        rep = dotted_repr(f)
        if rep is None:
            return None
        parts = rep.split(".")
        # plain name: nested fn, module function, from-import, class
        if len(parts) == 1:
            name = parts[0]
            cur: Optional[FuncInfo] = fn
            while cur is not None:
                if name in cur.nested:
                    return cur.nested[name]
                cur = self.functions.get(cur.parent) if cur.parent \
                    else None
            fid = self._toplevel.get((mod.name, name))
            if fid:
                return fid
            cid = self._mod_classes.get((mod.name, name))
            if cid:
                return self.lookup_method(cid, "__init__")
            fi = mod.from_imports.get(name)
            if fi:
                fid = self._toplevel.get(fi)
                if fid:
                    return fid
                cid = self._mod_classes.get(fi)
                if cid:
                    return self.lookup_method(cid, "__init__")
            return None
        # self.m() / self.attr.m() / cls.m()
        if parts[0] in ("self", "cls") and fn.cls:
            if len(parts) == 2:
                return self.lookup_method(fn.cls, parts[1])
            if len(parts) == 3:
                tcid = self._lookup_attr_type(fn.cls, parts[1])
                if tcid:
                    return self.lookup_method(tcid, parts[2])
            return None
        # module.func() / module.Class() / Class.method()
        target_mod = mod.imports.get(parts[0])
        if target_mod is not None and not target_mod.startswith(EXTERNAL):
            if len(parts) == 2:
                fid = self._toplevel.get((target_mod, parts[1]))
                if fid:
                    return fid
                cid = self._mod_classes.get((target_mod, parts[1]))
                if cid:
                    return self.lookup_method(cid, "__init__")
            elif len(parts) == 3:
                cid = self._mod_classes.get((target_mod, parts[1]))
                if cid:
                    return self.lookup_method(cid, parts[2])
            return None
        cid = self._resolve_class_name(mod, f.value) \
            if isinstance(f, ast.Attribute) else None
        if cid and len(parts) >= 2:
            return self.lookup_method(cid, parts[-1])
        return None

    def _resolve_calls(self, fn: FuncInfo) -> None:
        for n in self._body_walk(fn.node):
            if not isinstance(n, ast.Call):
                continue
            rep = dotted_repr(n.func)
            if rep is None:
                continue
            fn.calls.append(CallSite(
                node=n, repr=rep, norm=self._normalize(fn.module, rep),
                target=self._resolve_target(fn, n)))

    # --- queries ------------------------------------------------------------

    def callers_of(self, fid: str) -> Set[str]:
        return self._callers.get(fid, set())

    def async_functions(self) -> List[FuncInfo]:
        return [f for f in self.functions.values() if f.is_async]

    def reachable_from(self, fid: str,
                       skip_call=None) -> Dict[str, Tuple[str, CallSite]]:
        """BFS over resolved edges: reached fid -> (via fid, call site).

        ``skip_call(site) -> bool`` prunes edges (the executor seam).
        The parent links let rules print a human call chain.
        """
        parents: Dict[str, Tuple[str, CallSite]] = {}
        queue = [fid]
        seen = {fid}
        while queue:
            cur = queue.pop(0)
            info = self.functions.get(cur)
            if info is None:
                continue
            for cs in info.calls:
                if skip_call is not None and skip_call(cs):
                    continue
                if cs.target and cs.target not in seen:
                    seen.add(cs.target)
                    parents[cs.target] = (cur, cs)
                    queue.append(cs.target)
        return parents

    def chain(self, root: str, fid: str,
              parents: Dict[str, Tuple[str, CallSite]]) -> List[str]:
        """Qualname path root -> ... -> fid from a reachable_from map."""
        names = [self.functions[fid].qualname]
        cur = fid
        while cur != root and cur in parents:
            cur = parents[cur][0]
            names.append(self.functions[cur].qualname)
        return list(reversed(names))


def build_graph(pkg: Package) -> CallGraph:
    return CallGraph(pkg)
