"""BKW007: SLO-catalog sync — objectives vs metric families vs docs.

The SLO plane (obs/slo.py) is declarative: ``defaults.SLO_CATALOG``
names the ``bkw_*`` family each objective burns against.  A typo'd
family or a label that no construction site declares would make the
objective silently score burn 0 forever — the exact failure mode a
declarative catalog exists to prevent.  This rule checks, without
importing anything:

* the catalog literal parses (``ast.literal_eval`` on the assignment);
* every entry is well-formed (id, known kind, positive budget, ratio
  entries carry ``total_family``);
* every referenced family — ``family`` and ``total_family`` — is
  constructed somewhere (reusing BKW004's collector), and the entry's
  ``labels`` keys are a subset of the family's declared label set;
* both directions against ``docs/observability.md``'s Objectives
  table: every catalog id has a doc row, every doc row names a catalog
  id, and the doc row's family matches the catalog's.

Doc rows are recognized by shape: a table row whose FIRST cell carries
a backticked non-``bkw_`` identifier and whose later cells carry a
backticked ``bkw_*`` family — disjoint from BKW004's catalog-table
rows, which put the family itself in the first cell.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional

from .callgraph import CallGraph
from .findings import SEV_ERROR, Finding
from .rules_drift import collect_metric_families

CATALOG_MODULE = "defaults.py"
CATALOG_NAME = "SLO_CATALOG"
KNOWN_KINDS = ("counter_rate", "ratio", "quantile", "gauge_below")

_DOC_ID_RE = re.compile(r"`([a-z][a-z0-9_]*)`")
_DOC_FAMILY_RE = re.compile(r"`(bkw_[a-zA-Z0-9_]+)`")


def load_catalog(graph: CallGraph):
    """(entries, line) from the literal assignment, or (None, line) when
    the assignment exists but is not a pure literal."""
    mod = graph.pkg.modules.get(CATALOG_MODULE)
    if mod is None:
        return None, 1
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == CATALOG_NAME
                   for t in node.targets):
            continue
        try:
            return ast.literal_eval(node.value), node.lineno
        except (ValueError, TypeError, SyntaxError):
            return None, node.lineno
    return None, 1


def parse_objectives_doc(doc_path: Path) -> Dict[str, dict]:
    """objective id -> {line, families} from the doc's Objectives table
    rows (non-bkw backticked id in the first cell, a ``bkw_*`` family in
    a later cell)."""
    out: Dict[str, dict] = {}
    for i, raw in enumerate(doc_path.read_text().splitlines(), start=1):
        line = raw.strip()
        if not line.startswith("|") or line.startswith("|---") \
                or line.startswith("| Objective"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if len(cells) < 4:
            continue
        ids = [t for t in _DOC_ID_RE.findall(cells[0])
               if not t.startswith("bkw_")]
        if not ids or _DOC_FAMILY_RE.findall(cells[0]):
            continue  # a BKW004 catalog row, not an objective row
        families = tuple(fam for cell in cells[1:]
                         for fam in _DOC_FAMILY_RE.findall(cell))
        if not families:
            continue
        out.setdefault(ids[0], {"line": i, "families": families})
    return out


def check_bkw007(graph: CallGraph,
                 doc_path: Optional[Path]) -> List[Finding]:
    findings: List[Finding] = []
    entries, line = load_catalog(graph)
    if entries is None:
        findings.append(Finding(
            rule="BKW007", severity=SEV_ERROR, path=CATALOG_MODULE,
            line=line,
            message=(f"{CATALOG_NAME} is missing or not a pure literal"
                     f" — the SLO catalog must be statically checkable"),
            anchor="slo-unparsable-catalog"))
        return findings

    families = collect_metric_families(graph)

    def family_labels(fam: str):
        sets = {s["labels"] for s in families.get(fam, ())
                if s["labels"] is not None}
        return set().union(*sets) if sets else set()

    seen: Dict[str, dict] = {}
    for idx, entry in enumerate(entries):
        oid = str(entry.get("id", "")) if isinstance(entry, dict) else ""
        kind = entry.get("kind") if isinstance(entry, dict) else None
        budget = entry.get("budget", 0) if isinstance(entry, dict) else 0
        bad = (not oid or oid in seen or kind not in KNOWN_KINDS
               or not isinstance(budget, (int, float)) or budget <= 0
               or (kind == "ratio" and not entry.get("total_family")))
        if bad:
            findings.append(Finding(
                rule="BKW007", severity=SEV_ERROR, path=CATALOG_MODULE,
                line=line,
                message=(f"SLO catalog entry #{idx} ({oid or '?'}) is"
                         f" malformed: needs a unique id, kind in"
                         f" {KNOWN_KINDS}, budget > 0, and"
                         f" total_family for ratio kinds"),
                anchor=f"slo-bad-entry:{oid or idx}"))
            continue
        seen[oid] = entry
        refs = [("family", str(entry.get("family", "")))]
        if entry.get("total_family"):
            refs.append(("total_family", str(entry["total_family"])))
        for role, fam in refs:
            if fam not in families:
                findings.append(Finding(
                    rule="BKW007", severity=SEV_ERROR,
                    path=CATALOG_MODULE, line=line,
                    message=(f"SLO objective '{oid}' {role} '{fam}' is"
                             f" not constructed anywhere — the"
                             f" objective would score burn 0 forever"),
                    anchor=f"slo-unknown-family:{oid}:{role}"))
        extra = set(dict(entry.get("labels") or {})) \
            - family_labels(str(entry.get("family", "")))
        if entry.get("family") in families and extra:
            findings.append(Finding(
                rule="BKW007", severity=SEV_ERROR, path=CATALOG_MODULE,
                line=line,
                message=(f"SLO objective '{oid}' selects labels"
                         f" {sorted(extra)} that family"
                         f" '{entry['family']}' does not declare"),
                anchor=f"slo-label-drift:{oid}"))

    if doc_path is None or not Path(doc_path).exists():
        if seen:
            findings.append(Finding(
                rule="BKW007", severity=SEV_ERROR, path="docs", line=1,
                message=("objectives document not found; cannot check"
                         " SLO catalog sync"),
                anchor="slo-missing-doc"))
        return findings

    doc = parse_objectives_doc(Path(doc_path))
    doc_rel = Path(doc_path).name
    for oid, entry in sorted(seen.items()):
        row = doc.get(oid)
        if row is None:
            findings.append(Finding(
                rule="BKW007", severity=SEV_ERROR, path=CATALOG_MODULE,
                line=line,
                message=(f"SLO objective '{oid}' has no row in the"
                         f" {doc_rel} Objectives table"),
                anchor=f"slo-undocumented:{oid}"))
        elif str(entry.get("family", "")) not in row["families"]:
            findings.append(Finding(
                rule="BKW007", severity=SEV_ERROR,
                path=f"docs/{doc_rel}", line=row["line"],
                message=(f"Objectives row for '{oid}' names"
                         f" {row['families']} but the catalog burns"
                         f" against '{entry.get('family')}'"),
                anchor=f"slo-doc-family-drift:{oid}"))
    for oid, row in sorted(doc.items()):
        if oid not in seen:
            findings.append(Finding(
                rule="BKW007", severity=SEV_ERROR,
                path=f"docs/{doc_rel}", line=row["line"],
                message=(f"Objectives table documents '{oid}' but"
                         f" {CATALOG_NAME} has no such entry — prune"
                         f" the row or restore the objective"),
                anchor=f"slo-uncatalogued:{oid}"))
    return findings
