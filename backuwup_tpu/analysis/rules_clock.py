"""BKW006: sim-covered modules read time only through the clock seam.

The simulation plane (``docs/simulation.md``) re-runs the REAL
matchmaking, retry, peer-stats, and durability-sweep code on virtual
time.  That promise is only as strong as the absence of stray wall-clock
reads: one ``time.time()`` inside a covered module and a simulated week
silently mixes real seconds into virtual ones — no crash, just wrong
numbers.  So the seam is enforced statically: inside the covered set,
any direct call to ``time.time`` / ``time.monotonic`` / ``time.sleep``
/ ``asyncio.sleep`` is a finding, and the deliberate terminals
(``SystemClock`` itself, the sim's own wall-side instrumentation) carry
baseline entries with justifications rather than being special-cased
here — the PR-15 contract: silencing a finding costs a written reason.

Covered modules are a hand-kept list plus the whole ``sim/`` tree.  The
list grows when a module is put on the virtual clock, and the rule is
how the list stays honest: porting a module without adding it here
changes nothing, adding it without porting it turns every stray clock
read into a finding.
"""

from __future__ import annotations

from typing import List

from .callgraph import CallGraph
from .findings import SEV_ERROR, Finding

#: modules whose time reads must route through utils/clock.py — the
#: exact rel paths plus every file under the prefixes
CLOCKED_MODULES = (
    "utils/clock.py",
    "utils/retry.py",
    "net/matchmaking.py",
    "net/peer_stats.py",
    "obs/invariants.py",
    "obs/series.py",
    "obs/slo.py",
)
CLOCKED_PREFIXES = ("sim/",)

#: normalized call forms that read or wait on the real clock
_WALL_CALLS = ("time.time", "time.monotonic", "time.sleep",
               "asyncio.sleep")


def _covered(rel: str) -> bool:
    return rel in CLOCKED_MODULES or \
        any(rel.startswith(p) for p in CLOCKED_PREFIXES)


def check_bkw006(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    for fn in sorted(graph.functions.values(), key=lambda f: f.fid):
        if not _covered(fn.module.rel):
            continue
        for cs in fn.calls:
            if cs.norm not in _WALL_CALLS:
                continue
            findings.append(Finding(
                rule="BKW006", severity=SEV_ERROR,
                path=fn.module.rel, line=cs.node.lineno,
                message=(
                    f"direct wall-clock call '{cs.repr}' in sim-covered"
                    f" module; route it through the utils/clock.py seam"
                    f" (clock.now()/monotonic()/await clock.sleep()) so"
                    f" the simulation plane can substitute virtual"
                    f" time"),
                anchor=f"{fn.qualname}->{cs.repr}"))
    return findings
