"""Finding model for the bkwlint static-analysis toolkit.

Every rule reports :class:`Finding` records with a **stable key** —
``rule:path:anchor`` where the anchor is derived from *what* the finding
is about (function qualname, metric family, enum member), never from a
line number.  Keys are what the baseline file matches on, so an
unrelated edit that shifts lines can neither silence a real finding nor
resurrect a baselined one.

Severities:

* ``error`` — the invariant the codebase promises is broken; the gate
  fails.
* ``warning`` — the rule fired on a heuristic resolution (e.g. a
  lock-ish name it could not trace to ``threading.Lock``); still gated,
  but the message says why confidence is lower.

The rule-id registry lives here so ``--rule`` filtering and docs have
one source of truth.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

SEV_ERROR = "error"
SEV_WARNING = "warning"

#: rule id -> one-line summary (the catalog docs/analysis.md renders)
RULE_IDS: Dict[str, str] = {
    "BKW001": "no blocking I/O reachable from an async def off the"
              " executor seam",
    "BKW002": "no await while holding a threading.Lock/RLock",
    "BKW003": "every durable-commit seam has a crashpoint and the"
              " crash-site registry is exact",
    "BKW004": "every constructed bkw_* metric family is cataloged (and"
              " vice versa) with consistent labels",
    "BKW005": "every RequestType/P2PBodyKind member has a live"
              " serve/dispatch arm in net/p2p.py",
    "BKW006": "sim-covered modules read time only through the"
              " utils/clock.py seam",
    "BKW007": "every SLO catalog entry burns against a constructed"
              " bkw_* family with a valid label subset (and is"
              " documented, both directions)",
}


@dataclass(frozen=True)
class Finding:
    """One rule violation at one location."""

    rule: str
    severity: str
    path: str  # package-relative, e.g. "net/p2p.py" (or "docs/...")
    line: int
    message: str
    anchor: str  # line-independent identity within (rule, path)

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.anchor}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line,
                "message": self.message, "key": self.key}

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule}"
                f" {self.severity}: {self.message}")


@dataclass
class LintReport:
    """The runner's output: active findings plus baseline bookkeeping."""

    findings: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[dict] = field(default_factory=list)
    #: matched baseline entries still carrying the write-baseline
    #: placeholder ("TODO…") — suppressions nobody has justified yet
    unjustified: List[dict] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return (not self.findings and not self.stale_baseline
                and not self.unjustified)

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
            "unjustified": list(self.unjustified),
            "clean": self.clean,
        }
