"""bkwlint command line.

Exit-code contract (stable — scripted callers depend on it):

* ``0`` — clean: no unbaselined findings, no stale baseline entries,
  every baselined entry carries a real justification
* ``1`` — unbaselined findings present
* ``2`` — usage / environment error (bad path, unparseable source,
  malformed baseline, unknown rule)
* ``3`` — findings all baselined, but the baseline itself needs work:
  stale entries remain (fixed code must shed its exceptions) or an
  entry's justification still starts with the ``TODO`` placeholder
  ``--write-baseline`` stamps (a suppression nobody explained)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .baseline import BaselineError, write_baseline
from .findings import RULE_IDS, LintReport
from .runner import LintConfig, collect_findings, run_lint


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bkwlint",
        description="AST invariant linter for backuwup_tpu"
                    " (BKW001-BKW005)")
    p.add_argument("package", nargs="?", default=None,
                   help="package root to lint (default: the repo's"
                        " backuwup_tpu tree)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline JSON (default: repo"
                        " .bkwlint-baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--rule", action="append", default=None,
                   metavar="BKW00N",
                   help="run only this rule (repeatable)")
    p.add_argument("--doc", default=None, metavar="FILE",
                   help="metrics catalog markdown (default: repo"
                        " docs/observability.md)")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write current findings as a baseline to FILE"
                        " (placeholder justifications — edit before"
                        " committing) and exit 0")
    p.add_argument("--justification", default=None, metavar="TEXT",
                   help="with --write-baseline: stamp every entry with"
                        " TEXT instead of the TODO placeholder (use for"
                        " a batch of exceptions sharing one real"
                        " reason; TODO-prefixed entries fail the gate"
                        " with exit 3 until edited)")
    return p


def _config(args) -> LintConfig:
    repo = Path(__file__).resolve().parents[2]
    cfg = LintConfig.for_repo(repo)
    if args.package is not None:
        cfg.package_root = Path(args.package)
        if args.doc is None:
            cfg.doc_path = None  # foreign tree: no implicit repo catalog
        if args.baseline is None:
            cfg.baseline_path = None
    if args.doc is not None:
        cfg.doc_path = Path(args.doc)
    if args.baseline is not None:
        cfg.baseline_path = Path(args.baseline)
    if args.no_baseline:
        cfg.baseline_path = None
    if args.rule:
        cfg.rules = {r.upper() for r in args.rule}
    return cfg


def _render_text(report: LintReport, out) -> None:
    for f in report.findings:
        print(f.render(), file=out)
    for entry in report.stale_baseline:
        print(f"baseline: stale entry {entry['key']!r} matches no"
              f" current finding — remove it", file=out)
    for entry in report.unjustified:
        print(f"baseline: entry {entry['key']!r} still carries the"
              f" TODO placeholder — write a real justification",
              file=out)
    n, b, s = (len(report.findings), len(report.baselined),
               len(report.stale_baseline))
    u = len(report.unjustified)
    print(f"bkwlint: {n} finding(s), {b} baselined, {s} stale"
          f" baseline entr{'y' if s == 1 else 'ies'}, {u} unjustified",
          file=out)


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out or sys.stdout
    args = _build_parser().parse_args(argv)
    cfg = _config(args)
    if cfg.rules is not None:
        unknown = cfg.rules - set(RULE_IDS)
        if unknown:
            print(f"bkwlint: unknown rule(s): {sorted(unknown)}"
                  f" (have: {sorted(RULE_IDS)})", file=sys.stderr)
            return 2
    if not Path(cfg.package_root).is_dir():
        print(f"bkwlint: package root not found: {cfg.package_root}",
              file=sys.stderr)
        return 2

    try:
        if args.write_baseline:
            findings = collect_findings(cfg)
            write_baseline(Path(args.write_baseline), findings,
                           args.justification
                           or "TODO: justify this exception")
            print(f"bkwlint: wrote {len(findings)} entr"
                  f"{'y' if len(findings) == 1 else 'ies'} to"
                  f" {args.write_baseline}", file=out)
            return 0
        report = run_lint(cfg)
    except (SyntaxError, BaselineError, OSError) as e:
        print(f"bkwlint: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        json.dump(report.to_dict(), out, indent=2)
        out.write("\n")
    else:
        _render_text(report, out)
    if report.findings:
        return 1
    if report.stale_baseline or report.unjustified:
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
