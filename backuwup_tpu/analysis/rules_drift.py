"""BKW004 / BKW005: drift rules — code vs catalog, enum vs dispatch.

**BKW004 — metrics-catalog sync.**  The ``bkw_*`` families the code
registers through the ``obs/metrics.py`` get-or-create constructors and
the rows of ``docs/observability.md``'s Catalog table must agree both
ways, and every call site of one family must declare the same label
set (the runtime registry raises on conflict — this rule catches it
before an import ever runs, and catches the silent case the runtime
cannot: a family nobody documents).

The doc side is parsed from the Catalog's markdown table: any
backticked ``bkw_*`` token in a table row is a documented family; the
backticked tokens of the Labels column are its documented label set.

**BKW005 — wire-handler exhaustiveness.**  Every member of
``RequestType`` / ``P2PBodyKind`` in ``wire.py`` must be referenced in
``net/p2p.py`` (a member without a serve/dispatch arm is dead protocol
surface the serve loop will drop on the floor), and every
``<Enum>.<MEMBER>`` attribute reference anywhere in the package must
name a live member (a dead member would only fail at runtime, on the
rare path that takes it).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph
from .findings import SEV_ERROR, SEV_WARNING, Finding
from .loader import dotted_repr, resolve_strs_arg

METRIC_CTORS = ("counter", "gauge", "histogram")
_DOC_FAMILY_RE = re.compile(r"`(bkw_[a-zA-Z0-9_]+)`")
_DOC_LABEL_RE = re.compile(r"`([a-zA-Z_][a-zA-Z0-9_]*)`")


# --- BKW004 -----------------------------------------------------------------


def collect_metric_families(graph: CallGraph) -> Dict[str, List[dict]]:
    """family name -> construction sites [{rel, line, kind, labels}]."""
    out: Dict[str, List[dict]] = {}
    for mod in graph.pkg.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            rep = dotted_repr(node.func)
            if rep is None:
                continue
            tail = rep.rsplit(".", 1)[-1]
            if tail not in METRIC_CTORS or not node.args:
                continue
            a0 = node.args[0]
            if not (isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)
                    and a0.value.startswith("bkw_")):
                continue
            labels: Optional[tuple] = ()
            if len(node.args) >= 3:
                labels = resolve_strs_arg(mod, node.args[2])
            for kw in node.keywords:
                if kw.arg == "labelnames":
                    labels = resolve_strs_arg(mod, kw.value)
            out.setdefault(a0.value, []).append({
                "rel": mod.rel, "line": node.lineno, "kind": tail,
                "labels": labels})
    return out


def parse_catalog(doc_path: Path) -> Dict[str, dict]:
    """family -> {line, labels} from the markdown Catalog table."""
    out: Dict[str, dict] = {}
    for i, raw in enumerate(doc_path.read_text().splitlines(), start=1):
        line = raw.strip()
        if not line.startswith("|") or line.startswith("|---") \
                or line.startswith("| Metric"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if len(cells) < 4:
            continue
        fams = _DOC_FAMILY_RE.findall(cells[0])
        if not fams:
            continue
        labels = tuple(_DOC_LABEL_RE.findall(cells[2]))
        for fam in fams:
            out.setdefault(fam, {"line": i, "labels": labels})
    return out


def check_bkw004(graph: CallGraph,
                 doc_path: Optional[Path]) -> List[Finding]:
    findings: List[Finding] = []
    families = collect_metric_families(graph)

    for fam, sites in sorted(families.items()):
        label_sets = {s["labels"] for s in sites}
        kinds = {s["kind"] for s in sites}
        if len(label_sets) > 1 or len(kinds) > 1:
            where = ", ".join(f"{s['rel']}:{s['line']}"
                              f" {s['kind']}{s['labels']}" for s in sites)
            findings.append(Finding(
                rule="BKW004", severity=SEV_ERROR,
                path=sites[0]["rel"], line=sites[0]["line"],
                message=(f"metric family '{fam}' constructed with"
                         f" conflicting type/label sets: {where} —"
                         f" obs.metrics raises MetricError at import"),
                anchor=f"conflict:{fam}"))
        if None in label_sets:
            findings.append(Finding(
                rule="BKW004", severity=SEV_WARNING,
                path=sites[0]["rel"], line=sites[0]["line"],
                message=(f"metric family '{fam}' label set is not"
                         f" statically resolvable — use a literal"
                         f" tuple or a module-level constant"),
                anchor=f"dynamic-labels:{fam}"))

    if doc_path is None or not Path(doc_path).exists():
        if families:
            findings.append(Finding(
                rule="BKW004", severity=SEV_ERROR, path="docs",
                line=1, message=("metrics catalog document not found;"
                                 " cannot check bkw_* family sync"),
                anchor="missing-catalog"))
        return findings

    doc = parse_catalog(Path(doc_path))
    doc_rel = Path(doc_path).name
    for fam, sites in sorted(families.items()):
        if fam not in doc:
            findings.append(Finding(
                rule="BKW004", severity=SEV_ERROR,
                path=sites[0]["rel"], line=sites[0]["line"],
                message=(f"metric family '{fam}' is registered but has"
                         f" no row in the {doc_rel} catalog"),
                anchor=f"undocumented:{fam}"))
            continue
        code_labels = next(iter(ls for ls in
                                {s["labels"] for s in sites}
                                if ls is not None), ())
        doc_labels = doc[fam]["labels"]
        if set(doc_labels) != set(code_labels):
            findings.append(Finding(
                rule="BKW004", severity=SEV_ERROR,
                path=f"docs/{doc_rel}", line=doc[fam]["line"],
                message=(f"catalog row for '{fam}' documents labels"
                         f" {tuple(doc_labels)} but the code constructs"
                         f" it with {tuple(code_labels)}"),
                anchor=f"label-drift:{fam}"))
    for fam, info in sorted(doc.items()):
        if fam not in families:
            findings.append(Finding(
                rule="BKW004", severity=SEV_ERROR,
                path=f"docs/{doc_rel}", line=info["line"],
                message=(f"catalog documents '{fam}' but no code"
                         f" constructs that family — prune the row or"
                         f" restore the metric"),
                anchor=f"unconstructed:{fam}"))
    return findings


# --- BKW005 -----------------------------------------------------------------

WIRE_MODULE = "wire.py"
HANDLER_MODULE = "net/p2p.py"
CHECKED_ENUMS = ("RequestType", "P2PBodyKind")
_ENUM_BASES = ("IntEnum", "Enum", "IntFlag")


def collect_enums(graph: CallGraph) -> Dict[str, Dict[str, int]]:
    """enum name -> {member -> line} from the wire module."""
    wire = graph.pkg.modules.get(WIRE_MODULE)
    out: Dict[str, Dict[str, int]] = {}
    if wire is None:
        return out
    for node in wire.tree.body:
        if not isinstance(node, ast.ClassDef) \
                or node.name not in CHECKED_ENUMS:
            continue
        bases = {dotted_repr(b) for b in node.bases}
        if not any(b and b.rsplit(".", 1)[-1] in _ENUM_BASES
                   for b in bases):
            continue
        members: Dict[str, int] = {}
        for item in node.body:
            if isinstance(item, ast.Assign) \
                    and len(item.targets) == 1 \
                    and isinstance(item.targets[0], ast.Name) \
                    and not item.targets[0].id.startswith("_"):
                members[item.targets[0].id] = item.lineno
        out[node.name] = members
    return out


def collect_enum_refs(graph: CallGraph) -> Dict[
        Tuple[str, str], List[Tuple[str, int]]]:
    """(enum, member) -> [(rel, line)] attribute references anywhere."""
    refs: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
    for mod in graph.pkg.modules.values():
        if mod.rel == WIRE_MODULE:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            rep = dotted_repr(node)
            if rep is None:
                continue
            parts = rep.split(".")
            if len(parts) < 2:
                continue
            enum, member = parts[-2], parts[-1]
            if enum in CHECKED_ENUMS and member.isupper():
                refs.setdefault((enum, member), []).append(
                    (mod.rel, node.lineno))
    return refs


def check_bkw005(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    enums = collect_enums(graph)
    if not enums:
        return findings  # fixture package without a wire module
    refs = collect_enum_refs(graph)

    handler_refs: Set[Tuple[str, str]] = set()
    for (enum, member), locs in refs.items():
        if any(rel == HANDLER_MODULE for rel, _ in locs):
            handler_refs.add((enum, member))

    for enum, members in sorted(enums.items()):
        for member, line in sorted(members.items()):
            if (enum, member) not in handler_refs:
                findings.append(Finding(
                    rule="BKW005", severity=SEV_ERROR,
                    path=WIRE_MODULE, line=line,
                    message=(f"wire enum member {enum}.{member} has no"
                             f" serve/dispatch arm in {HANDLER_MODULE}"
                             f" — dead protocol surface"),
                    anchor=f"unhandled:{enum}.{member}"))
    for (enum, member), locs in sorted(refs.items()):
        if enum in enums and member not in enums[enum]:
            rel, line = locs[0]
            findings.append(Finding(
                rule="BKW005", severity=SEV_ERROR,
                path=rel, line=line,
                message=(f"reference to {enum}.{member} names a member"
                         f" that does not exist in {WIRE_MODULE} —"
                         f" AttributeError on this code path"),
                anchor=f"dead-member:{enum}.{member}"))
    return findings
