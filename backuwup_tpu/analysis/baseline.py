"""Baseline/allowlist for bkwlint: deliberate exceptions, justified.

``.bkwlint-baseline.json`` holds entries ``{"key", "justification"}``
matched against :attr:`Finding.key` — the line-independent identity, so
a baseline survives unrelated edits.  Two hard rules:

* every entry MUST carry a non-empty justification (an unexplained
  exception is just a suppressed bug), and
* an entry matching **no** current finding is *stale* and fails the
  gate — fixed code must shed its exception, or the baseline rots into
  an allowlist nobody can audit.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional

from .findings import Finding, LintReport


class BaselineError(ValueError):
    """Malformed baseline file."""


def load_baseline(path: Optional[Path]) -> Dict[str, str]:
    """key -> justification (empty when ``path`` is None/missing)."""
    if path is None:
        return {}
    path = Path(path)
    if not path.exists():
        return {}
    try:
        doc = json.loads(path.read_text())
    except ValueError as e:
        raise BaselineError(f"{path}: not valid JSON: {e}") from e
    if not isinstance(doc, dict) or doc.get("version") != 1 \
            or not isinstance(doc.get("entries"), list):
        raise BaselineError(
            f"{path}: expected {{'version': 1, 'entries': [...]}}")
    out: Dict[str, str] = {}
    for i, entry in enumerate(doc["entries"]):
        if not isinstance(entry, dict) or not entry.get("key") \
                or not str(entry.get("justification", "")).strip():
            raise BaselineError(
                f"{path}: entry {i} needs a key and a non-empty"
                f" justification")
        if entry["key"] in out:
            raise BaselineError(
                f"{path}: duplicate key {entry['key']!r}")
        out[entry["key"]] = str(entry["justification"])
    return out


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, str]) -> LintReport:
    """Split findings into active/suppressed; flag unmatched entries."""
    report = LintReport()
    matched = set()
    for f in findings:
        if f.key in baseline:
            matched.add(f.key)
            report.baselined.append(f)
        else:
            report.findings.append(f)
    for key, why in baseline.items():
        if key not in matched:
            report.stale_baseline.append(
                {"key": key, "justification": why})
        elif why.strip().startswith("TODO"):
            # a matched entry still carrying the write-baseline
            # placeholder is a suppression nobody explained — it gates
            # exactly like a stale entry (exit 3), because "baselined"
            # is only meaningful when someone wrote down WHY
            report.unjustified.append(
                {"key": key, "justification": why})
    return report


def write_baseline(path: Path, findings: List[Finding],
                   justification: str) -> None:
    """Regenerate a baseline from current findings (one shared
    placeholder justification — edit per-entry before committing)."""
    entries = [{"key": f.key, "justification": justification,
                "message": f.message}
               for f in sorted(findings, key=lambda f: f.key)]
    Path(path).write_text(json.dumps(
        {"version": 1, "entries": entries}, indent=2, sort_keys=False)
        + "\n")
