"""``python -m backuwup_tpu.analysis`` — the container check role's
entry point (no scripts/ tree needed inside the image)."""

import sys

from .cli import main

sys.exit(main())
