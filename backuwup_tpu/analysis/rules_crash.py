"""BKW003: crash-seam coverage — the static crash matrix.

Replaces the grep-based completeness test (tests/test_gc.py) with an
AST account of the same CrashMonkey/ALICE posture: the set of places a
crash can be *injected* must exactly cover the set of places a crash
can *hurt*.

Three checks:

1. **Registry exactness.**  Every ``faults.crashpoint(X)`` argument must
   resolve — a string literal, or a module-level constant bound by
   ``X = faults.register_crash_site("...")`` — to a registered site, and
   every registered site must have at least one call site (a registered
   seam nobody calls is a dead crash-matrix entry).
2. **Commit-seam coverage.**  Every call to the fsync-disciplined
   helpers ``durable.commit_replace`` / ``durable.write_replace`` and
   every ``index.flush()`` seam must have a crashpoint *adjacent*:
   lexically in the same function, inside the callee it invokes (the
   ``BlobIndex.flush -> save`` case, via the call-graph), or in a direct
   caller (the closure-staged-on-the-executor idiom,
   ``sink_part.stage -> PartialStore.append``).  A commit with no
   injectable crash next to it is a seam the matrix cannot exercise.
3. Unresolvable ``crashpoint(<expr>)`` arguments are findings too — a
   dynamic site name cannot be enumerated.

The fault plane itself (``utils/faults.py``) is exempt: it *defines*
the hooks.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .findings import SEV_ERROR, Finding
from .loader import resolve_str_arg

FAULTS_MODULE = "utils/faults.py"

#: the helper layer itself is not a seam — ``write_replace`` calling
#: ``commit_replace`` is composition, not a commit site; coverage is
#: checked where application code invokes the helpers
DURABLE_MODULE = "utils/durable.py"

#: durable-commit helper tails (module-qualified or from-imported).
#: ``fsync_file`` joined when the replicated op log arrived: its append
#: path commits via open-append + fsync rather than write_replace, and
#: an un-injectable log append is exactly the torn-tail case the crash
#: matrix exists to exercise.
COMMIT_HELPERS = ("commit_replace", "write_replace", "fsync_file")

#: replication commit points (net/serverstore.py).  The op-log methods
#: are the durable edges of the ship/promote protocol — append (record
#: durable on this node), set_epoch (fencing bump), truncate_after
#: (divergent-tail amputation) — and ``_ship_tail`` is the ack barrier
#: write futures resolve behind.  Each must sit next to a crashpoint
#: for the same reason a write_replace must.
_OPLOG_METHODS = ("append", "set_epoch", "truncate_after")


def _is_crashpoint(norm: str) -> bool:
    return norm == "crashpoint" or norm.endswith(".crashpoint")


def _is_register(node: ast.Call, norm: str) -> bool:
    return norm == "register_crash_site" \
        or norm.endswith(".register_crash_site")


def collect_registry(graph: CallGraph) -> Tuple[
        Dict[str, Tuple[str, int]], Dict[str, Dict[str, str]]]:
    """(site -> (rel, line) of registration,
    module rel -> {const name -> site literal})."""
    registered: Dict[str, Tuple[str, int]] = {}
    consts: Dict[str, Dict[str, str]] = {}
    for mod in graph.pkg.modules.values():
        if mod.rel == FAULTS_MODULE:
            continue
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt, v = node.targets[0], node.value
            if not (isinstance(tgt, ast.Name) and isinstance(v, ast.Call)):
                continue
            from .loader import dotted_repr
            rep = dotted_repr(v.func)
            if rep is None or not _is_register(v, rep):
                continue
            site = resolve_str_arg(mod, v.args[0]) if v.args else None
            if site is not None:
                registered[site] = (mod.rel, node.lineno)
                consts.setdefault(mod.rel, {})[tgt.id] = site
    return registered, consts


def _crashpoint_site(graph: CallGraph, fn: FuncInfo, call_args: list,
                     consts: Dict[str, Dict[str, str]]) -> Optional[str]:
    if not call_args:
        return None
    arg = call_args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.Name):
        return consts.get(fn.module.rel, {}).get(arg.id)
    return None


def _has_lexical_crashpoint(fn: FuncInfo) -> bool:
    return any(_is_crashpoint(cs.norm) for cs in fn.calls)


def _callee_has_crashpoint(graph: CallGraph, fid: str,
                           depth: int = 6) -> bool:
    """Crashpoint anywhere in ``fid``'s body or its in-package callees."""
    seen: Set[str] = set()
    stack = [(fid, 0)]
    while stack:
        cur, d = stack.pop()
        if cur in seen or d > depth:
            continue
        seen.add(cur)
        info = graph.functions.get(cur)
        if info is None:
            continue
        if _has_lexical_crashpoint(info):
            return True
        stack.extend((cs.target, d + 1) for cs in info.calls if cs.target)
    return False


def _is_commit_seam(cs) -> Optional[str]:
    """'durable-helper' / 'index-flush' when the call is a commit seam."""
    parts = cs.norm.split(".")
    if parts[-1] in COMMIT_HELPERS:
        return f"durable.{parts[-1]}"
    if parts[-1] == "flush" and len(parts) >= 2 \
            and parts[-2].endswith("index"):
        return "index.flush"
    if parts[-1] in _OPLOG_METHODS and len(parts) >= 2 \
            and parts[-2] == "log":
        return f"oplog.{parts[-1]}"
    if parts[-1] == "_ship_tail":
        return "repl.ship"
    return None


def check_bkw003(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    registered, consts = collect_registry(graph)
    called_sites: Dict[str, List[Tuple[str, int]]] = {}

    for fn in sorted(graph.functions.values(), key=lambda f: f.fid):
        if fn.module.rel == FAULTS_MODULE:
            continue
        for cs in fn.calls:
            if _is_crashpoint(cs.norm):
                site = _crashpoint_site(graph, fn, cs.node.args, consts)
                if site is None:
                    findings.append(Finding(
                        rule="BKW003", severity=SEV_ERROR,
                        path=fn.module.rel, line=cs.node.lineno,
                        message=(
                            f"crashpoint argument in '{fn.qualname}'"
                            f" does not resolve to a"
                            f" register_crash_site literal — the crash"
                            f" matrix cannot enumerate it"),
                        anchor=f"unresolved:{fn.qualname}"))
                else:
                    called_sites.setdefault(site, []).append(
                        (fn.module.rel, cs.node.lineno))
                continue
            seam = _is_commit_seam(cs)
            if seam is None or fn.module.rel == DURABLE_MODULE:
                continue
            covered = _has_lexical_crashpoint(fn)
            if not covered and cs.target and seam == "index.flush":
                covered = _callee_has_crashpoint(graph, cs.target)
            if not covered:
                covered = any(
                    _has_lexical_crashpoint(graph.functions[c])
                    for c in graph.callers_of(fn.fid)
                    if c in graph.functions)
            if not covered:
                findings.append(Finding(
                    rule="BKW003", severity=SEV_ERROR,
                    path=fn.module.rel, line=cs.node.lineno,
                    message=(
                        f"commit seam '{cs.repr}' ({seam}) in"
                        f" '{fn.qualname}' has no faults.crashpoint in"
                        f" the same function, its callee, or a direct"
                        f" caller — the crash matrix cannot exercise"
                        f" this commit"),
                    anchor=f"seam:{fn.qualname}:{cs.repr}"))

    for site, (rel, line) in sorted(registered.items()):
        if site not in called_sites:
            findings.append(Finding(
                rule="BKW003", severity=SEV_ERROR,
                path=rel, line=line,
                message=(f"crash site '{site}' is registered but never"
                         f" passed to faults.crashpoint — a dead"
                         f" crash-matrix entry"),
                anchor=f"dead-site:{site}"))
    for site, locs in sorted(called_sites.items()):
        if site not in registered:
            rel, line = locs[0]
            findings.append(Finding(
                rule="BKW003", severity=SEV_ERROR,
                path=rel, line=line,
                message=(f"crashpoint site '{site}' has no"
                         f" register_crash_site declaration — it would"
                         f" escape faults.crash_sites()"),
                anchor=f"unregistered-site:{site}"))
    return findings


def static_crash_sites(graph: CallGraph) -> Set[str]:
    """The statically enumerated registry (parity hook for tests)."""
    registered, _ = collect_registry(graph)
    return set(registered)
