"""Shared module loader for bkwlint: parse a package tree once.

Every rule consumes the same :class:`Package` — one ``ast`` parse per
file, package-relative module names, an import map (who calls ``wire``
what), and the module-level *simple constants* (strings and tuples of
strings) that the codebase uses for crash-site names and metric label
sets.  Nothing here imports the analyzed code; the toolkit must be able
to lint a tree that does not import (that is half the point).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

#: sentinel module name for imports that leave the analyzed package
EXTERNAL = "<external>"


@dataclass
class ModuleInfo:
    """One parsed source file of the analyzed package."""

    path: Path
    rel: str  # e.g. "net/p2p.py"
    name: str  # package-relative dotted name, "" for the root __init__
    tree: ast.Module
    #: local alias -> package-relative dotted module name, or EXTERNAL
    imports: Dict[str, str] = field(default_factory=dict)
    #: imported name -> (package-relative module, attribute name)
    from_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: module-level NAME = "str" / ("a", "b") constant bindings
    constants: Dict[str, object] = field(default_factory=dict)

    def source_line(self, node: ast.AST) -> int:
        return getattr(node, "lineno", 1)


@dataclass
class Package:
    root: Path
    name: str  # top-level package name (root directory name)
    modules: Dict[str, ModuleInfo] = field(default_factory=dict)  # by rel

    def by_name(self, dotted: str) -> Optional[ModuleInfo]:
        return self._by_name.get(dotted)

    def __post_init__(self):
        self._by_name: Dict[str, ModuleInfo] = {}

    def _index(self) -> None:
        self._by_name = {m.name: m for m in self.modules.values()}


def _module_name(root: Path, path: Path) -> str:
    rel = path.relative_to(root)
    parts = list(rel.parts)
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]
    return ".".join(parts)


def _package_parts(mod: ModuleInfo) -> List[str]:
    """The package a module lives in (itself, for ``__init__`` files)."""
    if mod.path.name == "__init__.py":
        return mod.name.split(".") if mod.name else []
    parts = mod.name.split(".")
    return parts[:-1]


def _resolve_relative(mod: ModuleInfo, level: int,
                      target: str) -> Optional[str]:
    """``from <level dots><target> import ...`` -> package-relative name
    (None when the import climbs out of the analyzed package)."""
    base = _package_parts(mod)
    if level > len(base) + 1:
        return None
    if level:
        base = base[:len(base) - (level - 1)]
    parts = base + ([p for p in target.split(".") if p] if target else [])
    return ".".join(parts)


def _collect_imports(pkg: Package, mod: ModuleInfo) -> None:
    for node in mod.tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                top = alias.name
                if top == pkg.name or top.startswith(pkg.name + "."):
                    inner = top[len(pkg.name):].lstrip(".")
                    mod.imports[local] = inner
                else:
                    mod.imports[local] = EXTERNAL + ":" + alias.name
        elif isinstance(node, ast.ImportFrom):
            target = node.module or ""
            if node.level:
                resolved = _resolve_relative(mod, node.level, target)
            elif target == pkg.name or target.startswith(pkg.name + "."):
                resolved = target[len(pkg.name):].lstrip(".")
            else:
                resolved = None
            for alias in node.names:
                local = alias.asname or alias.name
                if resolved is None:
                    mod.imports.setdefault(
                        local, EXTERNAL + ":" + target)
                    continue
                sub = (resolved + "." + alias.name).lstrip(".")
                if pkg.by_name(sub) is not None:
                    # `from .utils import durable` style: a submodule
                    mod.imports[local] = sub
                else:
                    mod.from_imports[local] = (resolved, alias.name)


def _collect_constants(mod: ModuleInfo) -> None:
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        value = node.value
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            mod.constants[tgt.id] = value.value
        elif isinstance(value, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in value.elts):
            mod.constants[tgt.id] = tuple(e.value for e in value.elts)


def load_package(root: Path) -> Package:
    """Parse every ``*.py`` under ``root`` (skipping caches) into a
    :class:`Package`.  Raises ``SyntaxError`` with the offending path in
    the message when a file does not parse — an unparseable tree cannot
    be linted and must fail loudly."""
    root = Path(root).resolve()
    pkg = Package(root=root, name=root.name)
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = str(path.relative_to(root))
        try:
            tree = ast.parse(path.read_text(), filename=rel)
        except SyntaxError as e:
            raise SyntaxError(f"{rel}: {e}") from e
        pkg.modules[rel] = ModuleInfo(
            path=path, rel=rel, name=_module_name(root, path), tree=tree)
    pkg._index()
    for mod in pkg.modules.values():
        _collect_imports(pkg, mod)
        _collect_constants(mod)
    return pkg


def dotted_repr(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_str_arg(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """A string literal, or a module-level constant holding one."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        v = mod.constants.get(node.id)
        if isinstance(v, str):
            return v
    return None


def resolve_strs_arg(mod: ModuleInfo, node: ast.AST) -> Optional[tuple]:
    """A tuple/list of string literals, or a constant holding one."""
    if isinstance(node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts):
        return tuple(e.value for e in node.elts)
    if isinstance(node, ast.Name):
        v = mod.constants.get(node.id)
        if isinstance(v, tuple):
            return v
    return None
