"""BKW001 / BKW002: event-loop purity rules.

**BKW001 — no blocking I/O reachable from an async def.**  The static
twin of the swarm harness's ``commits_off_event_loop`` gate: walk the
call-graph from every ``async def`` body and flag any path that reaches
a blocking primitive (``time.sleep``, ``os.fsync``/``fdatasync``,
``sqlite3.*``, ``subprocess.*``, builtin ``open``, and the pathlib
``read_*``/``write_*`` helpers this codebase uses for file I/O) unless
the call is routed through the executor seam (``Engine._blocking``,
``loop.run_in_executor``, ``asyncio.to_thread``).  Closures handed TO
the executor are sync functions that are never *called* from the async
body, so the graph naturally keeps them off the loop's account.

Sync callables are also on the loop's account when they are *scheduled*
onto it: the first argument of ``loop.call_soon``,
``loop.call_soon_threadsafe``, or ``Future.add_done_callback`` runs on
the event-loop thread even though no async body ever calls it.  Each
resolvable callback becomes an additional BKW001 root (the dataflow
engine's seal->send wakeup, docs/dataflow.md, is exactly this shape —
``notify_packfile`` must stay O(set-an-event)).

One finding per (blocking call site, nearest async root) — anchored at
the blocking site so the key survives refactors of the async caller's
internals.

**BKW002 — no await while holding a threading lock.**  A lexical rule:
an ``await`` (or ``async with``/``async for``) inside a plain ``with``
block whose context manager is a ``threading.Lock``/``RLock`` parks the
coroutine while every OTHER thread — and any other task that touches
the same lock via sync code — blocks.  Resolution: the context
expression's assignment is traced to ``threading.Lock()``/``RLock()``
(error), or merely *smells* like a lock by name (warning); asyncio
primitives, which must be entered with ``async with`` anyway, never
match.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .callgraph import CallGraph, CallSite, FuncInfo
from .findings import SEV_ERROR, SEV_WARNING, Finding

#: exact dotted forms that run their payload off the event loop
EXECUTOR_SEAM_SUFFIXES = ("._blocking", ".run_in_executor", ".to_thread")

#: loop-scheduling primitives whose callable argument later runs ON the
#: event-loop thread even though no async body ever calls it directly
LOOP_CALLBACK_SUFFIXES = (".call_soon", ".call_soon_threadsafe",
                          ".add_done_callback")

#: pathlib-style attribute calls that hit the disk whoever the receiver
BLOCKING_ATTRS = ("read_bytes", "write_bytes", "read_text", "write_text")

#: dotted-prefix -> category for module-level blocking primitives
BLOCKING_PREFIXES = (("sqlite3.", "sqlite3"), ("subprocess.", "subprocess"))

BLOCKING_EXACT = {
    "time.sleep": "time.sleep",
    "os.fsync": "os.fsync",
    "os.fdatasync": "os.fdatasync",
    "open": "open",
}


def _is_executor_seam(cs: CallSite) -> bool:
    return any(cs.norm.endswith(s) for s in EXECUTOR_SEAM_SUFFIXES) \
        or cs.norm in ("to_thread", "run_in_executor")


def _blocking_category(cs: CallSite) -> Optional[str]:
    if _is_executor_seam(cs):
        return None
    cat = BLOCKING_EXACT.get(cs.norm)
    if cat:
        return cat
    for prefix, name in BLOCKING_PREFIXES:
        if cs.norm.startswith(prefix) or cs.norm == prefix[:-1]:
            return name
    tail = cs.norm.rsplit(".", 1)[-1]
    if "." in cs.norm and tail in BLOCKING_ATTRS:
        return tail
    return None


def _direct_blocking(fn: FuncInfo) -> List[Tuple[CallSite, str]]:
    return [(cs, cat) for cs in fn.calls
            for cat in (_blocking_category(cs),) if cat]


def _loop_callback_roots(
        graph: CallGraph) -> List[Tuple[FuncInfo, FuncInfo, CallSite]]:
    """Every resolvable sync callable handed to a loop-scheduling
    primitive: (callback fn, scheduling fn, scheduling call site)."""
    roots: List[Tuple[FuncInfo, FuncInfo, CallSite]] = []
    seen = set()
    for fn in sorted(graph.functions.values(), key=lambda f: f.fid):
        for cs in fn.calls:
            if not any(cs.norm.endswith(s)
                       for s in LOOP_CALLBACK_SUFFIXES):
                continue
            if not cs.node.args:
                continue
            # the callback is positional arg 0 for all three primitives;
            # resolve it with the same machinery as a call target
            fake = ast.Call(func=cs.node.args[0], args=[], keywords=[])
            target = graph._resolve_target(fn, fake)
            info = graph.functions.get(target) if target else None
            if info is None or info.is_async or info.fid in seen:
                continue  # async callbacks are already roots
            seen.add(info.fid)
            roots.append((info, fn, cs))
    return roots


def check_bkw001(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    reported = set()  # (blocking fid, call line-agnostic anchor)

    def scan_root(root: FuncInfo, how: str) -> None:
        parents = graph.reachable_from(root.fid, skip_call=_is_executor_seam)
        for fid in [root.fid] + sorted(parents):
            holder = graph.functions.get(fid)
            if holder is None:
                continue
            for cs, cat in _direct_blocking(holder):
                anchor = f"{holder.qualname}->{cs.repr}"
                dedup = (holder.fid, cs.repr)
                if dedup in reported:
                    continue
                reported.add(dedup)
                chain = graph.chain(root.fid, fid, parents)
                via = " -> ".join(chain) if len(chain) > 1 \
                    else holder.qualname
                findings.append(Finding(
                    rule="BKW001", severity=SEV_ERROR,
                    path=holder.module.rel, line=cs.node.lineno,
                    message=(
                        f"blocking call '{cs.repr}' ({cat}) reachable"
                        f" from {how} via {via};"
                        f" route it through Engine._blocking /"
                        f" run_in_executor / asyncio.to_thread"),
                    anchor=anchor))

    for root in sorted(graph.async_functions(), key=lambda f: f.fid):
        scan_root(root, f"async '{root.qualname}'")
    for cb, sched_fn, sched_cs in _loop_callback_roots(graph):
        prim = sched_cs.norm.rsplit(".", 1)[-1]
        scan_root(cb, (f"loop-thread callback '{cb.qualname}'"
                       f" (scheduled via {prim} in"
                       f" '{sched_fn.qualname}')"))
    return findings


# --- BKW002 -----------------------------------------------------------------

_THREADING_LOCKS = ("threading.Lock", "threading.RLock")
_ASYNC_LOCKS = ("asyncio.Lock", "asyncio.Condition", "asyncio.Semaphore")


def _lock_kind(graph: CallGraph, fn: FuncInfo,
               expr: ast.AST) -> Optional[Tuple[str, str]]:
    """(severity, description) when ``with expr`` takes a threading
    lock; None for asyncio primitives and non-lock-ish expressions."""
    if isinstance(expr, ast.Call):
        rep = _norm(graph, fn, expr.func)
        if rep in _THREADING_LOCKS:
            return SEV_ERROR, rep
        return None
    rep_raw = _norm(graph, fn, expr, raw=True)
    if rep_raw is None:
        return None
    assigned = _trace_lock_assignment(graph, fn, expr)
    if assigned in _THREADING_LOCKS:
        return SEV_ERROR, assigned
    if assigned in _ASYNC_LOCKS:
        return None
    if "lock" in rep_raw.rsplit(".", 1)[-1].lower():
        return SEV_WARNING, f"'{rep_raw}' (lock-like name, unresolved)"
    return None


def _norm(graph: CallGraph, fn: FuncInfo, node: ast.AST, raw=False):
    from .loader import dotted_repr
    rep = dotted_repr(node)
    if rep is None:
        return None
    return rep if raw else graph._normalize(fn.module, rep)


def _trace_lock_assignment(graph: CallGraph, fn: FuncInfo,
                           expr: ast.AST) -> Optional[str]:
    """What ``expr`` was assigned: 'threading.Lock' etc., or None."""
    def value_kind(v: ast.AST) -> Optional[str]:
        if isinstance(v, ast.Call):
            return _norm(graph, fn, v.func)
        return None

    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self" \
            and fn.cls and fn.cls in graph.classes:
        for cid in graph._class_family(fn.cls):
            cls = graph.classes[cid]
            for item in cls.node.body:
                for n in ast.walk(item):
                    if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                            and isinstance(n.targets[0], ast.Attribute) \
                            and n.targets[0].attr == expr.attr:
                        kind = value_kind(n.value)
                        if kind:
                            return kind
        return None
    if isinstance(expr, ast.Name):
        for n in graph.body_nodes(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and n.targets[0].id == expr.id:
                kind = value_kind(n.value)
                if kind:
                    return kind
        for n in fn.module.tree.body:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and n.targets[0].id == expr.id:
                kind = value_kind(n.value)
                if kind:
                    return kind
    return None


def _awaits_inside(graph: CallGraph, with_node: ast.With) -> List[ast.AST]:
    out = []
    stack = [n for item in with_node.body for n in [item]]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, (ast.Await, ast.AsyncWith, ast.AsyncFor)):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def check_bkw002(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    for fn in sorted(graph.functions.values(), key=lambda f: f.fid):
        if not fn.is_async:
            continue
        for node in graph.body_nodes(fn):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                kind = _lock_kind(graph, fn, item.context_expr)
                if kind is None:
                    continue
                severity, desc = kind
                awaits = _awaits_inside(graph, node)
                if not awaits:
                    continue
                from .loader import dotted_repr
                lock_rep = dotted_repr(item.context_expr) or "<lock>"
                findings.append(Finding(
                    rule="BKW002", severity=severity,
                    path=fn.module.rel, line=awaits[0].lineno,
                    message=(
                        f"await inside 'with {lock_rep}' in"
                        f" '{fn.qualname}' holds a threading lock"
                        f" ({desc}) across a suspension point"),
                    anchor=f"{fn.qualname}:{lock_rep}"))
    return findings
