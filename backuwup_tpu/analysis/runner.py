"""bkwlint runner: load once, build the graph once, run every rule.

The orchestration layer the CLI, the tier-1 gate, and the fixture tests
all share.  ``run_lint`` is pure — paths in, :class:`LintReport` out —
so tests can point it at throwaway fixture packages and the CLI at the
real tree with identical semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, List, Optional, Set

from .baseline import apply_baseline, load_baseline
from .callgraph import CallGraph, build_graph
from .findings import RULE_IDS, Finding, LintReport
from .loader import Package, load_package
from .rules_async import check_bkw001, check_bkw002
from .rules_clock import check_bkw006
from .rules_crash import check_bkw003
from .rules_drift import check_bkw004, check_bkw005
from .rules_slo import check_bkw007


@dataclass
class LintConfig:
    package_root: Path
    doc_path: Optional[Path] = None  # metrics catalog for BKW004
    baseline_path: Optional[Path] = None
    rules: Optional[Set[str]] = None  # None = all

    @staticmethod
    def for_repo(repo_root: Path) -> "LintConfig":
        """The production configuration: the backuwup_tpu package, its
        observability catalog, and the checked-in baseline."""
        repo_root = Path(repo_root)
        return LintConfig(
            package_root=repo_root / "backuwup_tpu",
            doc_path=repo_root / "docs" / "observability.md",
            baseline_path=repo_root / ".bkwlint-baseline.json")


def _rule_table(cfg: LintConfig) -> Dict[str, Callable[[CallGraph],
                                                       List[Finding]]]:
    return {
        "BKW001": check_bkw001,
        "BKW002": check_bkw002,
        "BKW003": check_bkw003,
        "BKW004": lambda g: check_bkw004(g, cfg.doc_path),
        "BKW005": check_bkw005,
        "BKW006": check_bkw006,
        "BKW007": lambda g: check_bkw007(g, cfg.doc_path),
    }


def collect_findings(cfg: LintConfig,
                     graph: Optional[CallGraph] = None) -> List[Finding]:
    """All raw findings (pre-baseline), sorted for stable output."""
    if graph is None:
        graph = build_graph(load_package(cfg.package_root))
    selected = cfg.rules or set(RULE_IDS)
    unknown = selected - set(RULE_IDS)
    if unknown:
        raise ValueError(f"unknown rule ids: {sorted(unknown)}")
    findings: List[Finding] = []
    for rule_id, check in _rule_table(cfg).items():
        if rule_id in selected:
            findings.extend(check(graph))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.anchor))
    return findings


def run_lint(cfg: LintConfig,
             graph: Optional[CallGraph] = None) -> LintReport:
    """Findings filtered through the baseline: the gate's entry point."""
    findings = collect_findings(cfg, graph)
    baseline = load_baseline(cfg.baseline_path)
    if cfg.rules is not None:
        # a rule-filtered run must not call the other rules' baseline
        # entries stale — they were never given a chance to match
        baseline = {k: v for k, v in baseline.items()
                    if k.split(":", 1)[0] in cfg.rules}
    return apply_baseline(findings, baseline)


def load_graph(package_root: Path) -> CallGraph:
    """Convenience for callers that reuse the graph across runs."""
    return build_graph(load_package(Path(package_root)))


__all__ = ["LintConfig", "collect_findings", "run_lint", "load_graph",
           "Package", "LintReport"]
