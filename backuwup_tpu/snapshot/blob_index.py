"""Blob index: the client-side dedup authority (blob hash -> packfile).

Re-designs ``client/src/backup/filesystem/packfile/blob_index.rs``:

* In memory: hash -> packfile-id map plus a ``queued`` set for blobs that
  are encrypted-and-buffered but not yet inside a written packfile
  (``blob_index.rs:52-53,130-140``) — both consulted for dedup.
* On disk: sequentially numbered encrypted files of at most
  ``INDEX_FILE_MAX_ENTRIES`` entries (``blob_index.rs:16-19``); file key =
  HKDF(backup secret, b"index"), nonce = the 12-byte little-endian file
  counter (``blob_index.rs:183-237``), so index files are tamper-evident
  and positionally bound.
* The index is a cache: it can always be rebuilt from packfile headers
  (``blob_index.rs:23-43``) — :meth:`BlobIndex.rebuild_from_packfiles`.

This in-memory map is the CPU fallback of the dedup lookup; the sharded
TPU HBM probe (:mod:`backuwup_tpu.ops.dedup_index`) accelerates the same
contract for huge indexes.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ModuleNotFoundError:  # containers without the wheel: libcrypto shim
    from ..utils.compat_crypto import AESGCM

from .. import defaults
from ..crypto import KeyManager
from ..utils import durable, faults
from ..utils.serialization import Reader, Writer
from ..wire import AUDIT_NONCE_LEN, BLOB_HASH_LEN, PACKFILE_ID_LEN

INDEX_KEY_INFO = b"index"
CHALLENGE_KEY_INFO = b"audit"
_NAME_RE = re.compile(r"^\d{6}$")

#: Deletion record inside index files (docs/lifecycle.md).  GC flushes a
#: (hash, TOMBSTONE_PID) entry for every blob it dropped; with the
#: later-files-win load order that kills the mapping on reload (and on a
#: restored index), so a dead blob can never dedup a future backup
#: against a packfile that no longer exists.  Real packfile ids are 12
#: random bytes, so the all-zero id is free to act as the sentinel.
TOMBSTONE_PID = b"\x00" * PACKFILE_ID_LEN

# Crash-matrix seams: the window either side of each durable commit.
_CP_CHALLENGE_PRE = faults.register_crash_site("challenge.save.pre")
_CP_CHALLENGE_POST = faults.register_crash_site("challenge.save.post")
_CP_INDEX_PRE = faults.register_crash_site("index.save.pre")
_CP_INDEX_POST = faults.register_crash_site("index.save.post")


def index_file_name(counter: int) -> str:
    """Zero-padded numbering (file_utils.rs:55-57)."""
    return f"{counter:06d}"


@dataclass(frozen=True)
class ChallengeEntry:
    """One precomputed audit probe: expected digest of a sampled window.

    ``digest = blake3(nonce || packfile_bytes[offset : offset+length])`` —
    the verifier records it at pack time (while the plaintext packfile is
    still on disk) because the local copy is deleted once a peer acks it.
    """

    offset: int
    length: int
    nonce: bytes  # AUDIT_NONCE_LEN; keys the digest so peers can't precompute
    digest: bytes  # BLOB_HASH_LEN


class ChallengeTable:
    """Write-once encrypted audit challenge tables, one file per packfile.

    Same persistence idiom as the blob index: AES-GCM with a positionally
    bound nonce — here the 12-byte packfile id itself, which is unique per
    table, and the file is never rewritten, so the (key, nonce) pair
    encrypts exactly one plaintext.  Key = HKDF(backup secret, b"audit"),
    distinct from the index key so audit state and dedup state are
    cryptographically separated.
    """

    def __init__(self, keys: KeyManager, table_dir: Path):
        self.table_dir = Path(table_dir)
        self._key = keys.derive_backup_key(CHALLENGE_KEY_INFO)

    def path(self, packfile_id: bytes) -> Path:
        return self.table_dir / bytes(packfile_id).hex()

    def has(self, packfile_id: bytes) -> bool:
        return self.path(packfile_id).is_file()

    def save(self, packfile_id: bytes,
             entries: Iterable[ChallengeEntry]) -> Path:
        # id is a 12-byte packfile id or a 13-byte shard id (packfile id +
        # index byte, erasure/stripe.py); both are unique and both work as
        # the GCM nonce (lengths != 12 go through EVP_CTRL_GCM_SET_IVLEN)
        pid = bytes(packfile_id)
        if len(pid) not in (PACKFILE_ID_LEN, PACKFILE_ID_LEN + 1):
            raise ValueError("bad packfile/shard id length")
        path = self.path(pid)
        if path.exists():
            raise FileExistsError(
                f"challenge table for {pid.hex()} already written"
                " (tables are write-once; rewriting would reuse the nonce)")
        entries = list(entries)
        w = Writer()
        w.u64(len(entries))
        for e in entries:
            w.u64(e.offset)
            w.u64(e.length)
            w.fixed(bytes(e.nonce))
            w.fixed(bytes(e.digest))
        ct = AESGCM(self._key).encrypt(pid, w.take(), None)
        self.table_dir.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        tmp.write_bytes(ct)
        faults.crashpoint(_CP_CHALLENGE_PRE)
        durable.commit_replace(tmp, path)
        faults.crashpoint(_CP_CHALLENGE_POST)
        return path

    def forget(self, packfile_ids: Iterable[bytes]) -> int:
        """Delete the table files of dead packfiles — BOTH the whole-file
        table (12-byte id) and every per-shard table (13-byte id = the
        packfile id plus one index byte, so its hex name extends the
        packfile's).  Callers of ``BlobIndex.forget_packfiles`` pair it
        with this so audit state cannot resurrect a dead packfile;
        returns files removed.  Unlike ``save``, deletion is idempotent:
        re-running after a crash just finds nothing left to remove."""
        removed = 0
        if not self.table_dir.is_dir():
            return removed
        for pid in packfile_ids:
            prefix = bytes(pid).hex()
            for path in self.table_dir.glob(f"{prefix}*"):
                try:
                    path.unlink()
                    removed += 1
                except OSError:
                    pass
        return removed

    def load(self, packfile_id: bytes) -> List[ChallengeEntry]:
        pid = bytes(packfile_id)
        plain = AESGCM(self._key).decrypt(
            pid, self.path(pid).read_bytes(), None)
        r = Reader(plain)
        out = []
        for _ in range(r.u64()):
            offset = r.u64()
            length = r.u64()
            nonce = r.fixed(AUDIT_NONCE_LEN)
            digest = r.fixed(BLOB_HASH_LEN)
            out.append(ChallengeEntry(offset, length, nonce, digest))
        r.expect_end()
        return out


class BlobIndex:
    def __init__(self, keys: KeyManager, index_dir: Path):
        self.index_dir = Path(index_dir)
        self._key = keys.derive_backup_key(INDEX_KEY_INFO)
        self._map: Dict[bytes, bytes] = {}
        self._queued: Set[bytes] = set()
        self._unsaved: List[tuple] = []
        # Never reuse a file counter: the counter is the AES-GCM nonce, and a
        # (key, nonce) pair must encrypt exactly one plaintext.  Scan the
        # directory up front so even recovery paths that skip load() (e.g.
        # rebuild_from_packfiles after a corrupt file) keep counters fresh.
        self._next_file = self._scan_next_file()

    def _scan_next_file(self) -> int:
        if not self.index_dir.is_dir():
            return 0
        # a crashed flush leaves NNNNNN.tmp behind: that counter's nonce
        # already encrypted one plaintext, so it is burned either way
        numbers = [int(p.name.split(".")[0]) for p in self.index_dir.iterdir()
                   if _NAME_RE.match(p.name.split(".")[0])]
        return max(numbers) + 1 if numbers else 0

    # --- dedup contract (blob_index.rs:130-148) ----------------------------

    def is_duplicate(self, blob_hash: bytes) -> bool:
        h = bytes(blob_hash)
        return h in self._map or h in self._queued

    def mark_queued(self, blob_hash: bytes) -> None:
        self._queued.add(bytes(blob_hash))

    def finalize_packfile(self, packfile_id: bytes,
                          blob_hashes: Iterable[bytes]) -> None:
        """Blobs of a just-written packfile become committed entries."""
        pid = bytes(packfile_id)
        for h in blob_hashes:
            h = bytes(h)
            self._queued.discard(h)
            if h not in self._map:
                self._map[h] = pid
                self._unsaved.append((h, pid))

    def lookup(self, blob_hash: bytes) -> Optional[bytes]:
        return self._map.get(bytes(blob_hash))

    def hashes_for_packfiles(self, packfile_ids: Iterable[bytes]) -> Set[bytes]:
        """Committed blob hashes living in any of ``packfile_ids`` — the
        finalize_packfile bookkeeping read backwards (lost packfile ->
        which blobs must be re-packed)."""
        targets = {bytes(p) for p in packfile_ids}
        return {h for h, pid in self._map.items() if pid in targets}

    def forget_packfiles(self, packfile_ids: Iterable[bytes]) -> Set[bytes]:
        """Drop every committed entry that maps into ``packfile_ids``.

        The repair path calls this for packfiles whose only replicas were
        on a lost peer: once forgotten, ``is_duplicate`` answers False for
        exactly those blobs, so a re-pack over the unchanged source
        re-creates them (CDC + blake3 are deterministic) while every other
        blob still dedups away.  Returns the forgotten hashes.
        """
        targets = {bytes(p) for p in packfile_ids}
        lost = {h for h, pid in self._map.items() if pid in targets}
        for h in lost:
            del self._map[h]
        self._unsaved = [(h, pid) for h, pid in self._unsaved
                         if pid not in targets]
        return lost

    def record_tombstones(self, blob_hashes: Iterable[bytes]) -> int:
        """Queue deletion records for dropped blobs (GC's swap step).

        Unlike :meth:`forget_packfiles` — which only edits memory, on the
        promise that the blobs are immediately re-packed — a tombstone is
        flushed into the index files themselves, so the deletion survives
        reload and restore.  Returns tombstones queued."""
        n = 0
        for h in blob_hashes:
            h = bytes(h)
            self._map.pop(h, None)
            self._queued.discard(h)
            self._unsaved.append((h, TOMBSTONE_PID))
            n += 1
        return n

    def blob_map(self) -> Dict[bytes, bytes]:
        """Committed hash -> packfile-id snapshot — GC's mark phase joins
        this against the retained-snapshot manifests."""
        return dict(self._map)

    def packfile_ids(self) -> Set[bytes]:
        return set(self._map.values())

    def known_hashes(self) -> List[bytes]:
        """Every hash the index answers is_duplicate=True for (committed and
        queued) — the seed set for the device-resident dedup table."""
        return list(self._map.keys() | self._queued)

    @property
    def queued_count(self) -> int:
        return len(self._queued)

    def __len__(self) -> int:
        return len(self._map)

    @property
    def unsaved_entries(self) -> int:
        return len(self._unsaved)

    # --- encrypted split persistence (blob_index.rs:183-237) ---------------

    def _nonce(self, counter: int) -> bytes:
        return counter.to_bytes(PACKFILE_ID_LEN, "little")

    def flush(self) -> List[Path]:
        """Write unsaved entries into new numbered files (<=50k each).

        Returns the paths written — the send pipeline watermarks these by
        number (``config/backup.rs:80-98``).
        """
        self.index_dir.mkdir(parents=True, exist_ok=True)
        written = []
        cap = defaults.INDEX_FILE_MAX_ENTRIES
        while self._unsaved:
            batch, self._unsaved = self._unsaved[:cap], self._unsaved[cap:]
            w = Writer()
            w.u64(len(batch))
            for h, pid in batch:
                w.fixed(h)
                w.fixed(pid)
            ct = AESGCM(self._key).encrypt(self._nonce(self._next_file),
                                           w.take(), None)
            path = self.index_dir / index_file_name(self._next_file)
            tmp = path.with_suffix(".tmp")
            tmp.write_bytes(ct)
            faults.crashpoint(_CP_INDEX_PRE)
            durable.commit_replace(tmp, path)
            faults.crashpoint(_CP_INDEX_POST)
            written.append(path)
            self._next_file += 1
        return written

    def load(self) -> int:
        """Read every index file in numeric order; returns entry count.

        Later files WIN on duplicate hashes: a repair round re-homes blobs
        whose packfile died with a peer and flushes the new mapping into a
        new (higher-numbered) index file, so after a reload — or a restore
        that pulls every index file back — the hash must resolve to the
        replacement packfile, not the retired one still named by the
        original file.
        """
        if not self.index_dir.is_dir():
            return 0
        files = sorted(p for p in self.index_dir.iterdir()
                       if _NAME_RE.match(p.name))
        for path in files:
            counter = int(path.name)
            plain = AESGCM(self._key).decrypt(self._nonce(counter),
                                              path.read_bytes(), None)
            r = Reader(plain)
            for _ in range(r.u64()):
                h = r.fixed(BLOB_HASH_LEN)
                pid = r.fixed(PACKFILE_ID_LEN)
                if pid == TOMBSTONE_PID:
                    self._map.pop(h, None)
                else:
                    self._map[h] = pid
            r.expect_end()
            self._next_file = max(self._next_file, counter + 1)
        return len(self._map)

    def rebuild_from_packfiles(self, reader, pack_dir: Path) -> int:
        """Reconstruct the map from packfile headers (blob_index.rs:23-43).

        ``reader`` is a :class:`~backuwup_tpu.snapshot.packfile.PackfileReader`
        over ``pack_dir``.
        """
        pack_dir = Path(pack_dir)
        if not pack_dir.is_dir():
            return 0
        for shard in sorted(pack_dir.iterdir()):
            if not shard.is_dir():
                continue
            for f in sorted(shard.iterdir()):
                try:
                    pid = bytes.fromhex(f.name)
                except ValueError:
                    continue
                if len(pid) != PACKFILE_ID_LEN:
                    continue
                for entry in reader.read_header(pid):
                    self._map.setdefault(entry.hash, pid)
        return len(self._map)
