"""Snapshot engine: packfiles, blob index, tree packing/unpacking."""
