"""Directory unpacker: content-addressed snapshot -> filesystem tree.

Re-designs ``client/src/backup/filesystem/dir_unpacker.rs``: breadth-first
walk from the snapshot root, ``next_sibling`` chains re-joined into full
child lists (``:104-115``), files reassembled chunk by chunk, mtimes
restored (``:95-101``).
"""

from __future__ import annotations

import os
from collections import deque
from pathlib import Path
from typing import Callable, List, Optional

from ..wire import Blob, BlobKind, Tree, TreeKind


class RestoreError(Exception):
    pass


def fetch_full_tree(resolve: Callable[[bytes], Blob], head_hash: bytes) -> Tree:
    """Follow the sibling chain, merging children (dir_unpacker.rs:104-115)."""
    blob = resolve(head_hash)
    if blob.kind != BlobKind.TREE:
        raise RestoreError(f"blob {bytes(head_hash).hex()} is not a tree")
    tree = Tree.decode_bytes(blob.data)
    children: List[bytes] = list(tree.children)
    nxt = tree.next_sibling
    while nxt is not None:
        page = Tree.decode_bytes(resolve(nxt).data)
        children.extend(page.children)
        nxt = page.next_sibling
    tree.children = children
    tree.next_sibling = None
    return tree


def snapshot_coverage_gap(resolve: Callable[[bytes], Blob],
                          has_blob: Callable[[bytes], bool],
                          snapshot_hash: bytes) -> Optional[bytes]:
    """Walk the snapshot's tree graph without writing anything; return the
    first unresolvable blob hash, or ``None`` when every tree and file
    chunk is present.  Lets a restore with failed peer streams proceed
    anyway when the restored data already covers the snapshot (e.g. a
    phantom negotiated peer that stores nothing — see the matcher's
    crash-window note in net/server.py)."""
    try:
        root = fetch_full_tree(resolve, snapshot_hash)
    except Exception:
        return bytes(snapshot_hash)
    queue = deque([root])
    while queue:
        tree = queue.popleft()
        for child_hash in tree.children:
            if tree.kind == TreeKind.DIR:
                try:
                    queue.append(fetch_full_tree(resolve, child_hash))
                except Exception:
                    return bytes(child_hash)
            elif not has_blob(child_hash):
                return bytes(child_hash)
    return None


class DirUnpacker:
    """``resolve`` maps a blob hash to a :class:`Blob` (index + reader)."""

    def __init__(self, resolve: Callable[[bytes], Blob],
                 progress: Optional[Callable] = None):
        self.resolve = resolve
        self.progress = progress or (lambda **kw: None)
        self.files_restored = 0
        self.bytes_restored = 0

    def _restore_file(self, tree: Tree, path: Path) -> None:
        with open(path, "wb") as f:
            for chunk_hash in tree.children:
                blob = self.resolve(chunk_hash)
                if blob.kind != BlobKind.FILE_CHUNK:
                    raise RestoreError(
                        f"file child {bytes(chunk_hash).hex()} is not a chunk")
                f.write(blob.data)
                self.bytes_restored += len(blob.data)
        if tree.metadata.mtime_ns:
            os.utime(path, ns=(tree.metadata.mtime_ns, tree.metadata.mtime_ns))
        self.files_restored += 1
        self.progress(file=str(path))

    def unpack(self, snapshot_hash: bytes, dest: Path) -> None:
        dest = Path(dest)
        dest.mkdir(parents=True, exist_ok=True)
        root = fetch_full_tree(self.resolve, snapshot_hash)
        if root.kind != TreeKind.DIR:
            raise RestoreError("snapshot root is not a directory tree")
        queue = deque([(root, dest)])
        dir_times = []
        while queue:
            tree, path = queue.popleft()
            path.mkdir(parents=True, exist_ok=True)
            if tree.metadata.mtime_ns:
                dir_times.append((path, tree.metadata.mtime_ns))
            for child_hash in tree.children:
                child = fetch_full_tree(self.resolve, child_hash)
                if child.kind == TreeKind.DIR:
                    queue.append((child, path / child.name))
                else:
                    self._restore_file(child, path / child.name)
        # directory mtimes last, depth-first, so file writes don't clobber
        for path, mtime_ns in reversed(dir_times):
            os.utime(path, ns=(mtime_ns, mtime_ns))
