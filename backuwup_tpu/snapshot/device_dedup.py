"""Device-accelerated dedup front: batched classify on the sharded HBM table.

The reference answers "have I stored this blob?" one binary-search at a time
on the host (``blob_index.rs:130-148``).  Here the question is asked for a
whole batch of fingerprints in one device program against the
:class:`~backuwup_tpu.ops.dedup_index.ShardedDedupIndex` — the hash table
sharded over the mesh in HBM, probed via ``all_gather``/``psum`` collectives
(SURVEY.md section 7 step 3e).

:class:`MeshDedupIndex` is the bridge into the engine:

* the dedup *decision* for every chunk batch comes from the device table,
* :class:`~backuwup_tpu.snapshot.blob_index.BlobIndex` remains the persisted
  authority (hash -> packfile mapping, encrypted index files) and the parity
  oracle — the packer asserts both agree on every classification,
* table pressure (:class:`DedupIndexFull`) triggers an automatic capacity
  doubling with a reseed from the host authority, so the device table is a
  cache that can always be rebuilt — the same reconstructibility stance the
  reference takes for its index files (``blob_index.rs:23-43``).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import numpy as np
from jax.sharding import Mesh

from .. import defaults
from ..ops.dedup_index import (
    DedupIndexFull,
    ShardedDedupIndex,
    hashes_to_queries,
)
from .blob_index import BlobIndex

_SEED_BATCH = 8192


class MeshDedupIndex:
    """Batched membership classify+insert over a device mesh."""

    def __init__(self, mesh: Mesh, host_index: BlobIndex,
                 axis: str = "data",
                 capacity: Optional[int] = None):
        self.mesh = mesh
        self.axis = axis
        self.host = host_index
        n_dev = mesh.shape[axis]
        known = len(host_index) + host_index.queued_count
        need = max(defaults.DEDUP_SHARD_CAPACITY,
                   _next_pow2(4 * max(known, 1) // max(n_dev, 1)))
        self.capacity = capacity or need
        # sharded all-ones value slabs for classify_dispatch, keyed by
        # per-shard lane count (insert_device never donates its value arg)
        self._ones_cache: OrderedDict = OrderedDict()
        self._rebuild()

    def _rebuild(self) -> None:
        self.sharded = ShardedDedupIndex.create(
            self.mesh, self.axis, capacity=self.capacity)
        hashes = self.host.known_hashes()
        for s in range(0, len(hashes), _SEED_BATCH):
            batch = hashes[s:s + _SEED_BATCH]
            self.sharded.insert(
                hashes_to_queries(batch),
                np.ones(len(batch), dtype=np.uint32))

    def _grow(self) -> None:
        # 4x jump + on-device migration: the resident keys re-hash into
        # the bigger table without ever crossing the host link, and the
        # geometric step keeps total migration work O(N) amortized over
        # all inserts (the old path re-uploaded every known hash through
        # _SEED_BATCH chunks on every doubling).  If migration itself
        # exhausts probes (pathological clustering), keep growing — the
        # old table is left intact by a failed grown(), so state stays
        # consistent.
        cap = self.capacity * 4
        while True:
            try:
                self.sharded = self.sharded.grown(cap)
                break
            except DedupIndexFull:
                cap *= 4
        self.capacity = cap

    def classify_dispatch(self, q_dev):
        """Device-resident classify+insert of a sharded query slab.

        ``q_dev`` is the ``(D, n, 4)`` u32 slab straight off the mesh
        manifest (``queries_from_cvs`` of the digest accumulator) — the
        fingerprints never visit the host.  New keys insert with value 1;
        returns the ``(found, lost)`` device vectors WITHOUT any host
        synchronization: ``found != 0`` means the key was resident BEFORE
        this batch's insert, nonzero ``lost`` lanes (residual races /
        exhausted probes) must be resolved against the host authority —
        :meth:`resolve_hints` does both.
        """
        d, n = int(q_dev.shape[0]), int(q_dev.shape[1])
        return self.sharded.insert_device(q_dev, self._ones(d, n))

    def _ones(self, d: int, n: int):
        v = self._ones_cache.get(n)
        if v is None:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            # LRU: evict the coldest lane count; the old wholesale
            # clear() dropped hot entries (e.g. the full-batch lane
            # count that recurs on every steady-state dispatch) on every
            # 17th distinct shape (the pipeline _nv_cache idiom)
            while len(self._ones_cache) >= 64:
                self._ones_cache.popitem(last=False)
            v = self._ones_cache[n] = jax.device_put(
                jnp.ones((d, n), dtype=jnp.uint32),
                NamedSharding(self.mesh, P(self.axis)))
        else:
            self._ones_cache.move_to_end(n)
        return v

    def resolve_hints(self, hashes: List[bytes],
                      raw: List[Optional[bool]]) -> List[bool]:
        """Merge per-occurrence device found-flags into final dup hints.

        ``raw[i]`` is occurrence i's flag from :meth:`classify_dispatch`
        (truthy = key resident before its insert batch) or ``None`` when
        the device path could not classify it (shard fallback, candidate
        overflow, lost lane, tiny/long/empty stream).  Device semantics
        collapse cleanly: occurrences of one hash within one insert batch
        all report the pre-batch state, and a later batch of the same
        flush sees the earlier batch's insert as resident — so ANDing the
        concrete flags recovers "was it resident before the flush", and
        the ref-order walk below restores first-occurrence-new /
        repeat-duplicate.  Any ``None`` occurrence poisons the hash to
        ``None``: the host authority answers, and the hash is re-inserted
        host-side so the device table stays a superset of the pack batch
        (fallback shards may have inserted a wrong-digest key — harmless
        junk at 2^-128 collision odds, same stance as the 128-bit key
        truncation).
        """
        hashes = [bytes(h) for h in hashes]
        if not hashes:
            return []
        _unset = object()
        facts: dict = {}
        for h, f in zip(hashes, raw):
            prev = facts.get(h, _unset)
            if prev is None:
                continue
            if f is None:
                facts[h] = None
            elif prev is _unset:
                facts[h] = bool(f)
            else:
                facts[h] = prev and bool(f)
        pend = [h for h, f in facts.items() if f is None]
        host_facts = {}
        if pend:
            for h in pend:
                host_facts[h] = self.host.is_duplicate(h)
            q = hashes_to_queries(pend)
            vals = np.ones(len(pend), dtype=np.uint32)
            while True:
                try:
                    self.sharded.insert(q, vals)
                    break
                except DedupIndexFull:
                    self._grow()
        flags: List[bool] = []
        seen: set = set()
        for h in hashes:
            if h in seen:
                flags.append(True)
            else:
                seen.add(h)
                f = facts[h]
                flags.append(host_facts[h] if f is None else f)
        return flags

    def classify_insert(self, hashes: List[bytes]) -> List[bool]:
        """is-duplicate flag per hash; new hashes become table-resident.

        Intra-batch repeats are resolved host-side (first occurrence "new",
        the rest "duplicate") because the device kernel's contract requires
        distinct keys per batch (dedup_index.py module doc).
        """
        hashes = [bytes(h) for h in hashes]
        if not hashes:
            return []
        first: dict = {}
        uniq: List[bytes] = []
        for h in hashes:
            if h not in first:
                first[h] = len(uniq)
                uniq.append(h)
        q = hashes_to_queries(uniq)
        vals = np.ones(len(uniq), dtype=np.uint32)
        interrupted = False
        while True:
            try:
                found = self.sharded.insert(q, vals)
                break
            except DedupIndexFull:
                # the failed attempt may have scattered part of the batch
                # before probing exhausted; after the on-device migration
                # the retry would see those keys as resident, so the
                # batch's verdicts are resolved against the host authority
                # below (which still reflects only prior batches)
                self._grow()
                interrupted = True
        flags: List[bool] = []
        seen: set = set()
        for h in hashes:
            if h in seen:
                flags.append(True)
            elif interrupted:
                seen.add(h)
                flags.append(self.host.is_duplicate(h))
            else:
                seen.add(h)
                flags.append(bool(found[first[h]] > 0))
        return flags


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p
