"""Packfile write/read: dedup -> compress -> encrypt -> pack.

Re-designs the reference packfile manager (``client/src/backup/filesystem/
packfile/mod.rs:46-64``, ``pack.rs``, ``unpack.rs``) with the same on-disk
format semantics:

    u64-LE header_ct_len || AESGCM(header) || blob section
    blob section entry:  nonce(12) || AESGCM(zstd(blob data))

* per-blob key  = HKDF(backup secret, blob_hash)   (pack.rs:66-70)
* header key    = HKDF(backup secret, b"header")   (pack.rs:206-215)
* header nonce  = the random 12-byte packfile id   (packfile/mod.rs:25,
  types.rs PackfileId doubles as nonce)
* blob nonce    = random 12 bytes per blob
* header        = sequence of PackfileHeaderBlob{hash, kind, compression,
  length, offset} in the deterministic binary codec

Write policy mirrors ``packfile/mod.rs:25-29``: flush a packfile when the
buffered plain size crosses PACKFILE_TARGET_SIZE or PACKFILE_MAX_BLOBS,
hard-capped at PACKFILE_MAX_SIZE.  Files shard into ``pack/<2 hex>/<hex>``
directories (``file_utils.rs:40-52``).

An unflushed manager going out of scope is a bug in the caller; the
reference panics in ``Drop`` (``packfile/mod.rs:86-92``), here ``close()``
raises ``DirtyPackfileError`` if data would be lost.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, List, Optional

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ModuleNotFoundError:  # containers without the wheel: libcrypto shim
    from ..utils.compat_crypto import AESGCM

from .. import defaults
from ..crypto import KeyManager
from ..obs import metrics as obs_metrics
from ..utils import durable, faults, zstd
from ..utils.serialization import Reader, Writer
from ..wire import (
    PACKFILE_ID_LEN,
    Blob,
    CompressionKind,
    PackfileHeaderBlob,
)

HEADER_KEY_INFO = b"header"
NONCE_LEN = 12

# Crash-matrix seams around the packfile seal commit (docs/crash_consistency.md)
_CP_SEAL_PRE = faults.register_crash_site("pack.seal.pre")
_CP_SEAL_POST = faults.register_crash_site("pack.seal.post")

_STAGE_SECONDS = obs_metrics.histogram(
    "bkw_pack_stage_seconds",
    "Packfile pipeline stage times (seal=zstd+AES-GCM per blob,"
    " write=assemble+fsync per packfile, stall=packer blocked on the"
    " double buffer, chunk_hash=CDC+fingerprint per stream)",
    ("stage",))


class PackfileError(Exception):
    pass


class DirtyPackfileError(PackfileError):
    """close() called with unflushed blobs (reference Drop panic analog)."""


class BlobNotFoundError(PackfileError):
    pass


def packfile_path(base: Path, packfile_id: bytes) -> Path:
    """pack/<2-hex>/<hex> sharding (file_utils.rs:40-52)."""
    hexid = bytes(packfile_id).hex()
    return Path(base) / hexid[:2] / hexid


def _compress(data: bytes) -> tuple:
    if zstd.available():
        return CompressionKind.ZSTD, zstd.compress(
            data, defaults.ZSTD_COMPRESSION_LEVEL)
    import zlib
    return CompressionKind.ZLIB, zlib.compress(
        data, defaults.ZSTD_COMPRESSION_LEVEL)


def _decompress(kind: CompressionKind, data: bytes) -> bytes:
    if kind == CompressionKind.NONE:
        return data
    if kind == CompressionKind.ZSTD:
        return zstd.decompress(data)
    if kind == CompressionKind.ZLIB:
        import zlib
        return zlib.decompress(data)
    raise PackfileError(f"unknown compression kind {kind}")


@dataclass
class _Pending:
    header: PackfileHeaderBlob
    record: bytes  # nonce || ciphertext
    plain_len: int


class PackfileWriter:
    """Accumulates encrypted blobs and writes packfiles.

    ``on_packfile(packfile_id, path, blob_hashes, size)`` fires after each
    file lands on disk — the seam the send pipeline and blob index hang off.

    With ``seal_workers=0`` (the default) every blob is compressed +
    encrypted inline in ``add_blob`` and packfiles are written
    synchronously at the thresholds — the original behavior, byte for
    byte.  With ``seal_workers > 0`` the seal work (zstd + AES-GCM, both
    release the GIL) runs on a small thread pool and packfile assembly +
    disk writes run on a single ordered writer thread, double-buffered:
    at most ``defaults.PACK_SEAL_QUEUE_PACKFILES`` batches may be in
    flight before ``add_blob`` blocks, so chunk+hash, seal, and upload
    overlap instead of summing (docs/transfer.md).  The hard size cap is
    then enforced on the writer thread against actual ciphertext sizes
    (a batch splits into several packfiles if needed); worker errors
    surface on the next ``add_blob``/``flush``.  ``on_packfile`` fires on
    the writer thread — same off-loop contract as the packer-thread
    callback in synchronous mode.
    """

    # encoded header entry: hash(32) + kind(4) + compression(4) + length(8)
    # + offset(8); file layout: len(8) + AESGCM tag(16) + count field(8)
    _HEADER_ENTRY = 56
    _FILE_OVERHEAD = 8 + 16 + 8

    def __init__(self, keys: KeyManager, out_dir: Path,
                 on_packfile: Optional[Callable] = None,
                 seal_workers: int = 0):
        self.keys = keys
        self.out_dir = Path(out_dir)
        self.on_packfile = on_packfile
        self._pending: List[_Pending] = []
        self._pending_plain = 0
        self._pending_ct = 0
        self._header_key = keys.derive_backup_key(HEADER_KEY_INFO)
        self.bytes_written = 0
        self.seal_workers = max(0, int(seal_workers or 0))
        self._seal_pool: Optional[ThreadPoolExecutor] = None
        self._write_pool: Optional[ThreadPoolExecutor] = None
        self._batch: List = []  # futures of _Pending, submission order
        self._writes: deque = deque()  # in-flight assemble+write futures
        self._stats_lock = threading.Lock()
        self.stage_seconds = {"seal": 0.0, "write": 0.0, "stall": 0.0}
        if self.seal_workers:
            self._seal_pool = ThreadPoolExecutor(
                max_workers=self.seal_workers,
                thread_name_prefix="pack-seal")
            # exactly one writer thread: packfile writes stay ordered
            self._write_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="pack-write")

    def _file_size(self, n_blobs: int, ct_bytes: int) -> int:
        return self._FILE_OVERHEAD + n_blobs * self._HEADER_ENTRY + ct_bytes

    @property
    def _cap(self) -> int:
        # the binding cap is the smaller of the format cap (16 MiB,
        # packfile/mod.rs:27) and what one signed transport message can
        # carry (defaults.PACKFILE_WIRE_MAX) — a packfile that cannot be
        # sent would strand the backup
        return min(defaults.PACKFILE_MAX_SIZE, defaults.PACKFILE_WIRE_MAX)

    @property
    def pending_blobs(self) -> int:
        return len(self._pending) + len(self._batch)

    def _seal_blob(self, blob_hash: bytes, kind, data: bytes) -> _Pending:
        """compress + encrypt one blob (GIL-releasing hot path)."""
        t0 = time.monotonic()
        comp_kind, comp = _compress(data)
        key = self.keys.derive_backup_key(blob_hash)
        nonce = os.urandom(NONCE_LEN)
        ct = AESGCM(key).encrypt(nonce, comp, None)
        record = nonce + ct
        header = PackfileHeaderBlob(
            hash=blob_hash, kind=kind, compression=comp_kind,
            length=len(record), offset=0)  # offset assigned at write time
        dt = time.monotonic() - t0
        with self._stats_lock:
            self.stage_seconds["seal"] += dt
        _STAGE_SECONDS.observe(dt, stage="seal")
        return _Pending(header, record, len(data))

    def add_blob(self, blob: Blob) -> None:
        """Encrypt + queue one blob; trigger a packfile write at thresholds.

        Dedup is the caller's job (the blob index) — this layer packs what
        it is given, mirroring pack.rs:31-55's split of responsibilities.
        """
        if self.seal_workers:
            self._add_blob_pipelined(blob)
            return
        p = self._seal_blob(blob.hash, blob.kind, blob.data)
        record = p.record
        cap = self._cap
        if self._file_size(1, len(record)) > cap:
            raise PackfileError("single blob exceeds packfile max size")
        # hard cap is enforced *before* anything hits disk: flush the current
        # batch if this blob would push the file over the cap
        if self._pending and (
                self._file_size(len(self._pending) + 1,
                                self._pending_ct + len(record))
                > cap):
            self._write_packfile()
        self._pending.append(p)
        self._pending_plain += len(blob.data)
        self._pending_ct += len(record)
        if (self._pending_plain >= defaults.PACKFILE_TARGET_SIZE
                or len(self._pending) >= defaults.PACKFILE_MAX_BLOBS):
            self._write_packfile()

    # --- pipelined seal path (seal_workers > 0) ----------------------------

    def _add_blob_pipelined(self, blob: Blob) -> None:
        self._batch.append(self._seal_pool.submit(
            self._seal_blob, blob.hash, blob.kind, blob.data))
        self._pending_plain += len(blob.data)
        if (self._pending_plain >= defaults.PACKFILE_TARGET_SIZE
                or len(self._batch) >= defaults.PACKFILE_MAX_BLOBS):
            self._submit_batch()

    def _submit_batch(self) -> None:
        batch, self._batch = self._batch, []
        self._pending_plain = 0
        # double buffering: at most PACK_SEAL_QUEUE_PACKFILES batches may
        # be sealing/writing; beyond that the packer thread stalls here
        # (and surfaces any earlier writer-thread error)
        t0 = time.monotonic()
        while len(self._writes) >= max(1, defaults.PACK_SEAL_QUEUE_PACKFILES):
            self._writes.popleft().result()
        dt = time.monotonic() - t0
        with self._stats_lock:
            self.stage_seconds["stall"] += dt
        _STAGE_SECONDS.observe(dt, stage="stall")
        self._writes.append(self._write_pool.submit(
            self._assemble_batch, batch))

    def _assemble_batch(self, batch: List) -> None:
        """Writer thread: wait for the batch's seals, split on the hard
        cap against actual ciphertext sizes, and write each group."""
        pendings = [f.result() for f in batch]
        cap = self._cap
        group: List[_Pending] = []
        ct = 0
        for p in pendings:
            if self._file_size(1, len(p.record)) > cap:
                raise PackfileError("single blob exceeds packfile max size")
            if group and (self._file_size(len(group) + 1,
                                          ct + len(p.record)) > cap):
                self._write_group(group)
                group, ct = [], 0
            group.append(p)
            ct += len(p.record)
        if group:
            self._write_group(group)

    def emit_partial(self) -> None:
        """Hand whatever is buffered below the target size to the seal
        pipeline NOW (the packer's lag bound, docs/dataflow.md), without
        draining in-flight writes like :meth:`flush` does.  Packfile
        boundaries move, bytes do not — the snapshot id is
        content-addressed and independent of how blobs group into
        packfiles, so partial emission never changes the snapshot."""
        if self.seal_workers:
            if self._batch:
                self._submit_batch()
            return
        if self._pending:
            self._write_packfile()

    def flush(self) -> None:
        if self.seal_workers:
            if self._batch:
                self._submit_batch()
            while self._writes:
                self._writes.popleft().result()
            return
        if self._pending:
            self._write_packfile()

    def close(self) -> None:
        if self._pending or self._batch:
            raise DirtyPackfileError(
                f"{len(self._pending) + len(self._batch)} unflushed blobs"
                " — call flush()")
        self.shutdown()

    def shutdown(self) -> None:
        """Tear down the seal/writer pools without the dirty check (for
        ``finally`` blocks where flush may already have raised)."""
        if self._seal_pool is not None:
            self._seal_pool.shutdown(wait=True)
        if self._write_pool is not None:
            self._write_pool.shutdown(wait=True)

    def _write_packfile(self) -> None:
        self._write_group(self._pending)
        self._pending = []
        self._pending_plain = 0
        self._pending_ct = 0

    def _write_group(self, pendings: List[_Pending]) -> None:
        t0 = time.monotonic()
        packfile_id = os.urandom(PACKFILE_ID_LEN)
        offset = 0
        headers = []
        for p in pendings:
            headers.append(PackfileHeaderBlob(
                hash=p.header.hash, kind=p.header.kind,
                compression=p.header.compression, length=p.header.length,
                offset=offset))
            offset += len(p.record)
        w = Writer()
        w.u64(len(headers))
        for h in headers:
            h.encode(w)
        header_ct = AESGCM(self._header_key).encrypt(packfile_id, w.take(), None)
        path = packfile_path(self.out_dir, packfile_id)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        with open(tmp, "wb") as f:
            f.write(len(header_ct).to_bytes(8, "little"))
            f.write(header_ct)
            for p in pendings:
                f.write(p.record)
        faults.crashpoint(_CP_SEAL_PRE)
        durable.commit_replace(tmp, path)
        faults.crashpoint(_CP_SEAL_POST)
        size = path.stat().st_size
        dt = time.monotonic() - t0
        with self._stats_lock:
            self.bytes_written += size
            self.stage_seconds["write"] += dt
        _STAGE_SECONDS.observe(dt, stage="write")
        hashes = [h.hash for h in headers]
        assert size <= self._cap, "cap enforced before write"
        if self.on_packfile is not None:
            self.on_packfile(packfile_id, path, hashes, size)


class PackfileReader:
    """Random access to blobs in a directory of packfiles (unpack.rs:23-83)."""

    def __init__(self, keys: KeyManager, base_dir: Path):
        self.keys = keys
        self.base_dir = Path(base_dir)
        self._header_key = keys.derive_backup_key(HEADER_KEY_INFO)
        self._header_cache: Dict[bytes, list] = {}

    def read_header(self, packfile_id: bytes) -> list:
        pid = bytes(packfile_id)
        if pid in self._header_cache:
            return self._header_cache[pid]
        path = packfile_path(self.base_dir, pid)
        with open(path, "rb") as f:
            hlen = int.from_bytes(f.read(8), "little")
            header_ct = f.read(hlen)
        plain = AESGCM(self._header_key).decrypt(pid, header_ct, None)
        r = Reader(plain)
        entries = [PackfileHeaderBlob.decode(r) for _ in range(r.u64())]
        r.expect_end()
        self._header_cache[pid] = entries
        return entries

    def get_blob(self, packfile_id: bytes, blob_hash: bytes) -> Blob:
        entries = self.read_header(packfile_id)
        entry = next((e for e in entries if e.hash == bytes(blob_hash)), None)
        if entry is None:
            raise BlobNotFoundError(bytes(blob_hash).hex())
        path = packfile_path(self.base_dir, packfile_id)
        with open(path, "rb") as f:
            hlen = int.from_bytes(f.read(8), "little")
            f.seek(8 + hlen + entry.offset)
            record = f.read(entry.length)
        nonce, ct = record[:NONCE_LEN], record[NONCE_LEN:]
        key = self.keys.derive_backup_key(entry.hash)
        data = _decompress(entry.compression, AESGCM(key).decrypt(nonce, ct, None))
        return Blob(hash=entry.hash, kind=entry.kind, data=data)

    def iter_blobs(self, packfile_id: bytes):
        """All blobs of one packfile: one open, one sequential pass."""
        entries = self.read_header(packfile_id)
        path = packfile_path(self.base_dir, packfile_id)
        with open(path, "rb") as f:
            hlen = int.from_bytes(f.read(8), "little")
            base = 8 + hlen
            for entry in sorted(entries, key=lambda e: e.offset):
                f.seek(base + entry.offset)
                record = f.read(entry.length)
                nonce, ct = record[:NONCE_LEN], record[NONCE_LEN:]
                key = self.keys.derive_backup_key(entry.hash)
                data = _decompress(entry.compression,
                                   AESGCM(key).decrypt(nonce, ct, None))
                yield Blob(hash=entry.hash, kind=entry.kind, data=data)
