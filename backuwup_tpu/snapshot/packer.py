"""Directory packer: filesystem tree -> content-addressed snapshot.

Re-designs ``client/src/backup/filesystem/dir_packer.rs``:

* Deepest-first directory walk (``browse_dir_tree``, ``dir_packer.rs:89-132``)
  so every child tree hash exists before its parent is built.
* Files are chunked + fingerprinted through a :class:`ChunkerBackend`
  (CPU oracle or the TPU kernels) — the batched analog of the reference's
  per-file FastCDC/blake3 hot loop (``:246-311``).  All files of one
  directory form one device batch.
* Tree nodes (``Tree`` wire blobs) carry name, metadata, and child hashes;
  nodes with more than TREE_MAX_CHILDREN children split into a
  ``next_sibling`` chain (``dir_packer.rs:35,313-363``), built back-to-front
  so each page embeds the following page's hash.
* The root tree's blob hash is the snapshot id (``dir_packer.rs:47-84``).
* Dedup: every blob (chunk or tree) is checked against the blob index
  before packing (``pack.rs:31-55``); duplicate data costs one hash lookup.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, List, Optional

from .. import defaults
from ..obs import metrics as obs_metrics
from ..obs import profile as obs_profile
from ..ops.backend import ChunkerBackend
from ..ops.blake3_cpu import blake3_hash
from ..utils import tracing
from ..wire import Blob, BlobKind, Tree, TreeKind, TreeMetadata
from .blob_index import BlobIndex
from .packfile import PackfileWriter

_STAGE_SECONDS = obs_metrics.histogram(
    "bkw_pack_stage_seconds", "", ("stage",))  # declared in packfile.py


@dataclass
class PackStats:
    files: int = 0
    failed_files: int = 0
    dirs: int = 0
    bytes_read: int = 0
    chunks: int = 0
    chunks_deduped: int = 0
    bytes_deduped: int = 0
    dedup_divergences: int = 0
    # wall seconds inside the chunk+hash backend calls — with the
    # pipelined seal (packfile.py seal_workers) this stage overlaps the
    # seal/write/upload stages instead of summing with them
    chunk_hash_s: float = 0.0


class DirPacker:
    def __init__(self, backend: ChunkerBackend, writer: PackfileWriter,
                 index: BlobIndex,
                 progress: Optional[Callable] = None,
                 batch_bytes: int = 256 * defaults.MiB,
                 should_pause: Optional[Callable] = None,
                 dedup_batch: Optional[Callable] = None,
                 dedup_index=None,
                 on_blob: Optional[Callable] = None):
        self.backend = backend
        self.writer = writer
        self.index = index
        self.progress = progress or (lambda **kw: None)
        self.batch_bytes = batch_bytes
        self.should_pause = should_pause or (lambda: None)
        # manifest hook: called (hash, size) for EVERY blob the snapshot
        # references — duplicates included — so the caller can record the
        # snapshot's full reachable-blob manifest (GC's mark source,
        # docs/lifecycle.md) without a second tree walk
        self.on_blob = on_blob
        # device dedup front.  ``dedup_index`` (a MeshDedupIndex) is the
        # full handle: pack batches then classify through the backend's
        # fused manifest+classify seam (on the TPU backend the digests
        # reach the sharded table without leaving the mesh).
        # ``dedup_batch`` is the narrower legacy hook (batched
        # classify+insert callable); None for both = host-only dedup.
        self.dedup_index = dedup_index
        if dedup_batch is None and dedup_index is not None:
            dedup_batch = dedup_index.classify_insert
        self.dedup_batch = dedup_batch
        self._device_sync: List[bytes] = []
        self.stats = PackStats()
        # lag-bounded incremental emission (docs/dataflow.md): deadline
        # for the next forced partial-packfile emission
        self._emit_deadline = time.monotonic() + defaults.PACK_EMIT_MAX_LAG_S

    # --- blob plumbing -----------------------------------------------------

    def _add_blob(self, blob_hash: bytes, kind: BlobKind, data: bytes,
                  dup_hint: Optional[bool] = None) -> None:
        """Dedup-then-pack one blob (pack.rs:31-55 semantics).

        ``dup_hint`` is the device table's classification when the blob was
        part of a batched classify.  The host index is the authority: on
        disagreement the host verdict wins and the event is logged loudly —
        device=dup/host=new is expected-by-design (astronomically rare
        128-bit truncation collisions in the device table's key prefix,
        see device_dedup.py), and degrading beats failing the whole backup.
        """
        if self.on_blob is not None:
            self.on_blob(bytes(blob_hash), len(data))
        host_dup = self.index.is_duplicate(blob_hash)
        if dup_hint is not None and dup_hint != host_dup:
            self.stats.dedup_divergences += 1
            logging.getLogger(__name__).warning(
                "device/host dedup divergence on %s: device=%s host=%s; "
                "using host verdict", bytes(blob_hash).hex(), dup_hint,
                host_dup)
        if dup_hint is None and self.dedup_batch is not None:
            # blob classified host-side only (tree node or streamed chunk):
            # sync it into the device table at the next batch boundary
            self._device_sync.append(bytes(blob_hash))
        if host_dup:
            self.stats.chunks_deduped += 1
            self.stats.bytes_deduped += len(data)
            return
        self.index.mark_queued(blob_hash)
        self.should_pause()
        self.writer.add_blob(Blob(hash=blob_hash, kind=kind, data=data))

    def _flush_device_sync(self) -> None:
        if self.dedup_batch is not None and self._device_sync:
            self.dedup_batch(self._device_sync)
            self._device_sync.clear()

    def _maybe_emit_partial(self) -> None:
        """Incremental emission instead of end-of-tree flush: blobs
        buffered below the packfile target size must not wait for
        ``pack()``'s final flush longer than PACK_EMIT_MAX_LAG_S — on a
        tree of many small directories that flush used to be the ONLY
        emission, so the wire idled for the whole walk.  The deadline
        re-arms whenever the writer is empty, so steady target-size
        emission never pays extra sub-target packfiles."""
        now = time.monotonic()
        if not self.writer.pending_blobs:
            self._emit_deadline = now + defaults.PACK_EMIT_MAX_LAG_S
            return
        if now >= self._emit_deadline:
            self.writer.emit_partial()
            self._emit_deadline = now + defaults.PACK_EMIT_MAX_LAG_S

    def _add_tree(self, tree: Tree) -> bytes:
        encoded = tree.encode_bytes()
        h = blake3_hash(encoded)
        self._add_blob(h, BlobKind.TREE, encoded)
        return h

    def _tree_with_split(self, kind: TreeKind, name: str, meta: TreeMetadata,
                         children: List[bytes]) -> bytes:
        """Build one logical node, splitting into a next_sibling chain at
        TREE_MAX_CHILDREN (dir_packer.rs:313-363); returns the head hash."""
        cap = defaults.TREE_MAX_CHILDREN
        pages = [children[i:i + cap] for i in range(0, len(children), cap)] or [[]]
        next_hash: Optional[bytes] = None
        for page in reversed(pages):
            next_hash = self._add_tree(Tree(
                kind=kind, name=name, metadata=meta, children=list(page),
                next_sibling=next_hash))
        return next_hash

    # --- file chunking (the TPU-batched hot path) --------------------------

    def _pack_files(self, files: List[Path]) -> List[Optional[bytes]]:
        """Chunk+hash a batch of files; returns each file's tree hash
        (None for files that vanished or failed to read)."""
        hashes: List[Optional[bytes]] = [None] * len(files)
        batch_idx: List[int] = []
        batch_data: List[bytes] = []
        batch_meta: List[TreeMetadata] = []

        def flush_batch():
            if not batch_idx:
                return
            t0 = time.monotonic()
            hint_list = None
            if self.dedup_index is not None:
                # blobs classified host-side since the last batch (streamed
                # chunks, tree nodes) must reach the device table BEFORE
                # this batch is classified, or a re-occurrence of one of
                # them would read as device-new/host-dup and trip the
                # divergence guard in _add_blob
                self._flush_device_sync()
                # fused manifest+classify: on the TPU backend each digest
                # batch hands its accumulator to the sharded table on
                # device (zero per-batch host round trips); index-stage
                # dispatches are accounted inside the backend/driver
                with tracing.span("packer.manifest_many"):
                    manifests, hint_list = \
                        self.backend.manifest_many_classified(
                            batch_data, self.dedup_index)
            else:
                with tracing.span("packer.manifest_many"):
                    manifests = self.backend.manifest_many(batch_data)
            dt = time.monotonic() - t0
            self.stats.chunk_hash_s += dt
            _STAGE_SECONDS.observe(dt, stage="chunk_hash")
            total_refs = sum(len(m) for m in manifests)
            if total_refs and hint_list is None:
                # one batched dedup classification per pack batch, whether
                # the device table or the host blob index answers it
                obs_profile.dispatch("index", actual_bytes=32 * total_refs,
                                     padded_bytes=32 * total_refs)
            hints = iter(())
            if hint_list is not None:
                hints = iter(hint_list)
            elif self.dedup_batch is not None:
                # legacy hook path (no full index handle): same sync-then-
                # classify ordering, one device round trip for the batch
                self._flush_device_sync()
                hints = iter(self.dedup_batch(
                    [ref.hash for m in manifests for ref in m]))
            for i, data, meta, manifest in zip(batch_idx, batch_data,
                                               batch_meta, manifests):
                for ref in manifest:
                    self.stats.chunks += 1
                    self._add_blob(ref.hash, BlobKind.FILE_CHUNK,
                                   data[ref.offset:ref.offset + ref.length],
                                   dup_hint=next(hints, None))
                hashes[i] = self._tree_with_split(
                    TreeKind.FILE, files[i].name, meta,
                    [ref.hash for ref in manifest])
                self.stats.files += 1
                self.progress(file=str(files[i]), bytes=len(data))
            self._flush_device_sync()
            self._maybe_emit_partial()
            batch_idx.clear()
            batch_data.clear()
            batch_meta.clear()

        pending = 0
        for i, path in enumerate(files):
            try:
                st = path.lstat()
                if st.st_size > self.batch_bytes:
                    # oversized file: stream it so memory stays bounded
                    hashes[i] = self._pack_file_streaming(path, st)
                    continue
                data = path.read_bytes()
            except OSError:
                self.stats.failed_files += 1
                continue
            self.stats.bytes_read += len(data)
            batch_idx.append(i)
            batch_data.append(data)
            batch_meta.append(TreeMetadata(
                size=len(data), mtime_ns=st.st_mtime_ns,
                ctime_ns=st.st_ctime_ns))
            pending += len(data)
            if pending >= self.batch_bytes:
                flush_batch()
                pending = 0
        flush_batch()
        return hashes

    def _pack_file_streaming(self, path: Path, st: os.stat_result) -> bytes:
        """Chunk one huge file through the backend's streaming manifest;
        blobs pack as chunks finalize, so memory stays ~one segment.

        The file is mmapped and fed as memoryview windows
        (dir_packer.rs:252's memmap2 analog), so the packer never holds a
        second buffered copy of the file; the backend still assembles one
        per-segment buffer when it splices the carry onto each window.
        The same documented race as the reference applies: a file
        mutating mid-chunk produces a wrong (detectably inconsistent)
        backup of that file, never a crash — mmap failures (e.g. the
        file was truncated to empty after the stat) fall back to plain
        reads.
        """
        import mmap as _mmap

        children: List[bytes] = []

        def emit(ref, data):
            self.stats.chunks += 1
            self.stats.bytes_read += ref.length
            children.append(ref.hash)
            self._add_blob(ref.hash, BlobKind.FILE_CHUNK, data)

        with open(path, "rb") as f:
            try:
                mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            except (OSError, ValueError):
                mm = None  # empty/truncated/unmappable: plain reads
            t0 = time.monotonic()
            if mm is None:
                self.backend.manifest_stream(
                    f.read, segment_bytes=self.batch_bytes, emit=emit)
            else:
                view = memoryview(mm)
                pos = 0

                def read(n: int):
                    nonlocal pos
                    out = view[pos:pos + n]
                    pos += len(out)
                    return out

                try:
                    self.backend.manifest_stream(
                        read, segment_bytes=self.batch_bytes, emit=emit)
                finally:
                    view.release()
                    try:
                        mm.close()
                    except BufferError:
                        # an in-flight exception's traceback still holds
                        # window slices; closing would mask the real
                        # error — let GC drop the mapping instead
                        pass
        dt = time.monotonic() - t0
        self.stats.chunk_hash_s += dt
        _STAGE_SECONDS.observe(dt, stage="chunk_hash")
        if children:
            # the streamed file's chunks were classified host-side one by
            # one; account them as a single per-file dedup pass
            obs_profile.dispatch("index", actual_bytes=32 * len(children),
                                 padded_bytes=32 * len(children))
        self.stats.files += 1
        self.progress(file=str(path), bytes=st.st_size)
        return self._tree_with_split(
            TreeKind.FILE, path.name,
            TreeMetadata(size=st.st_size, mtime_ns=st.st_mtime_ns,
                         ctime_ns=st.st_ctime_ns),
            children)

    # --- directory walk ----------------------------------------------------

    def pack(self, root: Path) -> bytes:
        """Pack ``root`` recursively; returns the snapshot id (root hash)."""
        root = Path(root)
        if not root.is_dir():
            raise NotADirectoryError(str(root))
        # discover directories breadth-first, then process deepest-first so
        # children always hash before parents (dir_packer.rs:89-132)
        order: List[Path] = [root]
        for d in order:
            try:
                subdirs = sorted(p for p in d.iterdir()
                                 if p.is_dir() and not p.is_symlink())
            except OSError:
                subdirs = []
            order.extend(subdirs)
        dir_hash: dict = {}
        for d in reversed(order):
            try:
                entries = sorted(d.iterdir())
            except OSError:
                entries = []
            files = [p for p in entries
                     if p.is_file() and not p.is_symlink()]
            subdirs = [p for p in entries if p.is_dir() and not p.is_symlink()]
            children = [h for h in self._pack_files(files) if h is not None]
            children.extend(dir_hash[s] for s in subdirs if s in dir_hash)
            try:
                st = d.stat()
                meta = TreeMetadata(size=0, mtime_ns=st.st_mtime_ns,
                                    ctime_ns=st.st_ctime_ns)
            except OSError:  # directory vanished mid-walk: keep its children
                meta = TreeMetadata()
            name = "" if d == root else d.name
            dir_hash[d] = self._tree_with_split(TreeKind.DIR, name, meta,
                                                children)
            self.stats.dirs += 1
            self._maybe_emit_partial()
        self._flush_device_sync()
        self.writer.flush()
        return dir_hash[root]
