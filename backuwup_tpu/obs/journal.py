"""Persistent event journal: size-rotated append-only JSONL + panic dumps.

Every StatusEvent the messenger emits, every span close, every
retry/backoff firing, and every fault-plane injection lands here as one
JSON line, so a post-mortem can replay exactly what the process saw —
with ``trace_id`` fields joining the lines of one backup across the
pack thread, the transfer plane, and (via the wire propagation in
:mod:`backuwup_tpu.obs.trace`) the peer that stored the bytes.

The plane follows the fault-plane idiom (utils/faults.py): a module
global :data:`JOURNAL` that is ``None`` unless installed, so the hook
call — :func:`emit` — costs one attribute load on the production path
and never raises into the data path.  A process started with
``BKW_JOURNAL=<path>`` gets the journal with no plumbing.

Rotation is by size: when the live file passes ``max_bytes`` it is
renamed to ``<path>.1`` (older generations shift up, the oldest beyond
``keep`` is dropped) and a fresh file starts.  :meth:`Journal.panic_dump`
writes ``<path stem>.panic.json`` containing the registry snapshot plus
the last N journal lines — the flight recorder read-out for the
``messenger.panic`` / excepthook path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import IO, List, Optional

from .. import defaults
from ..utils import durable
from . import metrics as _metrics


class Journal:
    """One append-only JSONL journal with size rotation."""

    def __init__(self, path, max_bytes: Optional[int] = None,
                 keep: Optional[int] = None):
        self.path = Path(path)
        self.max_bytes = int(defaults.OBS_JOURNAL_MAX_BYTES
                             if max_bytes is None else max_bytes)
        self.keep = int(defaults.OBS_JOURNAL_KEEP if keep is None else keep)
        self._lock = threading.Lock()
        self._fh: Optional[IO[str]] = None
        self.lines_written = 0
        self.rotations = 0
        self.path.parent.mkdir(parents=True, exist_ok=True)

    # --- writing -----------------------------------------------------------

    def emit(self, kind: str, **fields) -> None:
        rec = {"ts": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        line = json.dumps(rec, sort_keys=True, default=str) + "\n"
        with self._lock:
            fh = self._open_locked()
            fh.write(line)
            fh.flush()
            self.lines_written += 1
            if fh.tell() >= self.max_bytes:
                self._rotate_locked()

    def _open_locked(self) -> IO[str]:
        if self._fh is None or self._fh.closed:
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def _rotate_locked(self) -> None:
        self._fh.close()
        self._fh = None
        oldest = self.path.with_name(self.path.name + f".{self.keep}")
        if oldest.exists():
            oldest.unlink()
        for i in range(self.keep - 1, 0, -1):
            src = self.path.with_name(self.path.name + f".{i}")
            if src.exists():
                src.rename(self.path.with_name(self.path.name + f".{i + 1}"))
        if self.keep > 0:
            self.path.rename(self.path.with_name(self.path.name + ".1"))
        else:
            self.path.unlink()
        # one barrier for the whole rename chain: a crash mid-rotation may
        # lose a generation shift but never a committed journal file
        durable.fsync_dir(self.path.parent)
        self.rotations += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()
            self._fh = None

    # --- reading -----------------------------------------------------------

    def files(self) -> List[Path]:
        """Journal files oldest-first (rotated generations then live)."""
        out = [self.path.with_name(self.path.name + f".{i}")
               for i in range(self.keep, 0, -1)]
        out.append(self.path)
        return [p for p in out if p.exists()]

    def tail(self, n: int) -> List[dict]:
        """Last ``n`` parsed records across rotation boundaries (bad
        lines — a torn write at crash time — are skipped)."""
        lines: List[str] = []
        for p in self.files():
            try:
                lines.extend(p.read_text(encoding="utf-8").splitlines())
            except OSError:
                continue
        out = []
        for line in lines[-max(int(n), 0):]:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out

    # --- post-mortem -------------------------------------------------------

    def panic_dump(self, message: str,
                   tail_n: Optional[int] = None) -> Path:
        """Flight-recorder read-out: metrics snapshot + journal tail."""
        tail_n = defaults.OBS_PANIC_TAIL_LINES if tail_n is None else tail_n
        out = self.path.with_name(self.path.name + ".panic.json")
        doc = {"ts": round(time.time(), 6), "message": str(message),
               "metrics": _metrics.registry().snapshot(),
               "journal_tail": self.tail(tail_n)}
        tmp = out.with_name(out.name + ".tmp")
        tmp.write_text(json.dumps(doc, sort_keys=True, default=str),
                       encoding="utf-8")
        durable.commit_replace(tmp, out)
        return out


#: The installed journal; None (the default) disables every hook.
JOURNAL: Optional[Journal] = None


def install(journal: Journal) -> Journal:
    global JOURNAL
    JOURNAL = journal
    return journal


def uninstall() -> None:
    global JOURNAL
    j, JOURNAL = JOURNAL, None
    if j is not None:
        j.close()


def get() -> Optional[Journal]:
    return JOURNAL


def emit(kind: str, **fields) -> None:
    """Record one line on the installed journal; no-op when none is
    installed, and a failing disk never raises into the data path."""
    j = JOURNAL
    if j is None:
        return
    try:
        j.emit(kind, **fields)
    except Exception:
        pass


def panic(message: str) -> Optional[Path]:
    """Write the panic dump on the installed journal (None when absent)."""
    j = JOURNAL
    if j is None:
        return None
    try:
        j.emit("panic", message=str(message))
        return j.panic_dump(message)
    except Exception:
        return None


# env activation at import time (the faults.py idiom): a process started
# with BKW_JOURNAL=<path> journals with no test or app plumbing
if os.environ.get("BKW_JOURNAL"):
    JOURNAL = Journal(os.environ["BKW_JOURNAL"])
