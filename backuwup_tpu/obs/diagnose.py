"""Evidence-ranked root-cause explainer for SLO breaches.

Dapper's core claim (PAPERS.md) is that telemetry becomes actionable
when signals from different planes are *correlated*, not merely
collected.  :func:`explain` does exactly that at breach time: it takes
the breach window and scores every piece of evidence that overlaps it —

* **journal events** — armed fault-site injections (``fault`` events,
  including ``crash.*`` crashpoints and ``dial.dead``/``send.dead``
  kill evidence), durability status changes, demotions/promotions,
  sequence breaks, GC/repair activity;
* **anomalous series** — the recorder's robust-zscore flags over the
  same window (obs/series.py);
* **slow trace spans** — ``span`` journal events whose duration is an
  outlier against the window's other spans of the same name.

Scoring is layered so harder evidence outranks softer evidence: an
injected fault in the window beats a durability transition, which beats
a generic lifecycle event, which beats a statistical anomaly.  Within a
layer, repetition raises the score slightly (capped) and ties break on
the cause id — every input is rounded before ranking, so the same
breach against the same evidence yields a byte-identical report.  That
determinism is load-bearing: the sim plane gates on
``same seed => identical diagnosis_report``.

The report is journaled (``diagnosis_report``) for ``obs_dump.py
--explain`` and returned to the caller (the scenario harness asserts
the armed fault site ranks in the top-3 causes).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import defaults
from . import journal as obs_journal
from . import metrics as obs_metrics

_C_REPORTS = obs_metrics.counter(
    "bkw_diagnosis_reports_total",
    "Breach diagnosis reports generated", ("objective",))

#: Evidence-layer base scores; faults must outrank everything a healthy
#: run can emit, statistical anomalies must rank below any hard event.
_SCORE_FAULT = 4.0
_SCORE_DURABILITY = 2.5
_SCORE_EVENT = 2.0
_SCORE_SPAN = 1.5
_SCORE_SERIES_MAX = 1.0
#: Repetition bonus per extra occurrence of the same cause, capped.
_REPEAT_BONUS = 0.1
_REPEAT_CAP = 1.0

#: Journal kinds that are infrastructure, not evidence.
_SKIP_KINDS = frozenset({
    "slo_breach", "slo_recovered", "slo_diagnose_error",
    "diagnosis_report", "series_sample", "series_sample_error",
})


def _event_cause(ev: dict):
    """(cause_id, kind, base_score, evidence) for one journal event, or
    None when the event carries no diagnostic weight."""
    kind = str(ev.get("kind", ""))
    if not kind or kind in _SKIP_KINDS:
        return None
    if kind == "fault":
        site = str(ev.get("site", "?"))
        return (f"fault:{site}", "fault", _SCORE_FAULT,
                {"site": site})
    if kind == "durability":
        status = str(ev.get("status", "?"))
        return (f"durability:{status}", "durability", _SCORE_DURABILITY,
                {"status": status, "summary": ev.get("summary")})
    if kind == "span":
        return None  # handled by _span_causes (needs peer comparison)
    detail = {}
    for field in ("site", "peer", "client", "status", "reason"):
        if field in ev:
            detail[field] = ev[field]
    return (f"event:{kind}", "event", _SCORE_EVENT, detail)


def _span_causes(spans: List[dict]) -> List[tuple]:
    """Flag span names whose worst duration dominates the window: the
    max must be >= 3x the window median for that name (and the name must
    have >= 2 samples, else there is no baseline to dominate)."""
    by_name: Dict[str, List[float]] = {}
    for ev in spans:
        try:
            by_name.setdefault(str(ev.get("name", "?")), []).append(
                float(ev.get("dur_s", 0.0)))
        except (TypeError, ValueError):
            continue
    out = []
    for name, durs in sorted(by_name.items()):
        if len(durs) < 2:
            continue
        durs_sorted = sorted(durs)
        med = durs_sorted[len(durs_sorted) // 2]
        worst = durs_sorted[-1]
        if med > 0 and worst >= 3.0 * med:
            out.append((f"span:{name}", "span", _SCORE_SPAN,
                        {"name": name, "worst_s": round(worst, 6),
                         "median_s": round(med, 6),
                         "samples": len(durs)}))
    return out


def explain(breach, recorder=None, events: Optional[List[dict]] = None,
            now: Optional[float] = None,
            window_s: Optional[float] = None,
            top: Optional[int] = None) -> dict:
    """Build the ranked diagnosis report for one breach.

    ``breach`` is an ``obs.slo.Breach`` or its dict form.  ``events``
    is the journal-event window to correlate (dicts with at least
    ``kind``; ``ts`` filters when present relative to ``now``) — when
    None, the installed journal's tail is used.  ``recorder`` supplies
    the anomaly flags; None skips the series layer (the sim plane's
    synthetic-events path).  Deterministic for identical inputs.
    """
    bd = breach.to_dict() if hasattr(breach, "to_dict") else dict(breach)
    window_s = float(defaults.DIAGNOSE_WINDOW_S
                     if window_s is None else window_s)
    top = int(defaults.DIAGNOSE_TOP_CAUSES if top is None else top)
    now = float(bd.get("t", 0.0)) if now is None else float(now)

    if events is None:
        jr = obs_journal.get()
        events = jr.tail(512) if jr is not None else []

    causes: Dict[str, dict] = {}

    def add(cause_id, kind, score, evidence):
        cur = causes.get(cause_id)
        if cur is None:
            causes[cause_id] = {"id": cause_id, "kind": kind,
                                "score": score, "count": 1,
                                "evidence": evidence}
        else:
            cur["count"] += 1
            cur["score"] = min(cur["score"] + _REPEAT_BONUS,
                               score + _REPEAT_CAP)

    spans: List[dict] = []
    lo = now - window_s
    windowed = 0
    for ev in events:
        if not isinstance(ev, dict):
            continue
        ts = ev.get("ts")
        if ts is not None:
            try:
                ts = float(ts)
            except (TypeError, ValueError):
                ts = None
        if ts is not None and not (lo <= ts <= now):
            continue
        windowed += 1
        if str(ev.get("kind", "")) == "span":
            spans.append(ev)
            continue
        got = _event_cause(ev)
        if got is not None:
            add(*got)

    for got in _span_causes(spans):
        add(*got)

    if recorder is not None:
        for a in recorder.anomalies(window_s):
            score = round(min(abs(a["z"]), 10.0) / 10.0
                          * _SCORE_SERIES_MAX, 4)
            add(f"series:{a['key']}", "series", score,
                {"z": a["z"], "last": a["last"]})

    ranked = sorted(causes.values(),
                    key=lambda c: (-round(c["score"], 4), c["id"]))
    report = {
        "objective": bd.get("objective", "?"),
        "status": bd.get("status", "?"),
        "t": round(now, 6),
        "window_s": round(window_s, 3),
        "evidence_events": windowed,
        "causes": [{"id": c["id"], "kind": c["kind"],
                    "score": round(c["score"], 4),
                    "count": c["count"], "evidence": c["evidence"]}
                   for c in ranked[:top]],
    }
    _C_REPORTS.inc(objective=report["objective"])
    obs_journal.emit("diagnosis_report", **report)
    return report
