"""Perfetto timeline export: journals -> Chrome trace-event JSON.

Turns the PR-5 span tree into a viewable picture: every ``span`` line a
journal recorded becomes a complete ("X") trace event, every other
journal line an instant ("i"), and multiple clients' journals merge
into one document — each journal gets its own Perfetto process row,
while the trace ids that already ride the p2p and client<->server
envelopes key the cross-process correlation (sender pack spans and
receiver store spans carry the same ``trace_id`` arg, and
``trace_id=`` filtering cuts the merged view down to one backup).

Journal span lines record the CLOSE time (``ts``) plus ``dur_s``, so an
event's start is ``ts - dur_s``.  Spans sharing a trace are laid on one
Perfetto track (tid) per process; parent spans close after their
children, so the nesting renders as a flame without explicit stack
events.  Stdlib-only, like the rest of ``obs/``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_GENERATOR = "backuwup-tpu obs.timeline"


def journal_records(path) -> List[dict]:
    """Parse one journal JSONL file, silently skipping torn/garbage
    lines (a crash mid-write must not make the timeline unreadable)."""
    out = []
    p = Path(path)
    if not p.exists():
        return out
    with p.open("r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "ts" in rec:
                out.append(rec)
    return out


def _us(seconds: float) -> int:
    return int(round(float(seconds) * 1e6))


def to_trace_events(sources: Sequence[Tuple[str, Iterable[dict]]],
                    trace_id: Optional[str] = None) -> List[dict]:
    """Convert ``(label, records)`` journal sources into trace events.

    Each source becomes one Perfetto process (pid 1..N, named via an
    "M" metadata event).  Within a process, every distinct trace id is
    one track (tid, by first appearance); records without a trace id
    share track 0.  With ``trace_id`` set, only records carrying that
    exact id survive — the merged cross-process view of one backup.
    """
    events: List[dict] = []
    for pid, (label, records) in enumerate(sources, start=1):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": str(label)},
        })
        tids: Dict[str, int] = {}
        for rec in records:
            rec_tid = rec.get("trace_id")
            if trace_id is not None and rec_tid != trace_id:
                continue
            if rec_tid:
                tid = tids.setdefault(rec_tid, len(tids) + 1)
            else:
                tid = 0
            ts = float(rec.get("ts", 0.0))
            if rec.get("kind") == "span":
                dur = float(rec.get("dur_s") or 0.0)
                events.append({
                    "name": str(rec.get("name", "span")),
                    "cat": "span", "ph": "X",
                    "ts": _us(ts - dur), "dur": max(_us(dur), 1),
                    "pid": pid, "tid": tid,
                    "args": {"trace_id": rec_tid,
                             "span_id": rec.get("span_id"),
                             "parent_id": rec.get("parent_id")},
                })
            else:
                args = {k: v for k, v in rec.items()
                        if k not in ("ts", "kind")}
                events.append({
                    "name": str(rec.get("kind", "event")),
                    "cat": "journal", "ph": "i", "s": "t",
                    "ts": _us(ts), "pid": pid, "tid": tid,
                    "args": args,
                })
    # Deterministic order: metadata first, then by time within pid ties.
    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0),
                               e["pid"], e["tid"], e["name"]))
    return events


def build_timeline(sources: Sequence[Tuple[str, Iterable[dict]]],
                   trace_id: Optional[str] = None) -> dict:
    """The full Chrome trace-event document (Perfetto's legacy JSON
    format: load via ui.perfetto.dev or chrome://tracing)."""
    return {
        "traceEvents": to_trace_events(sources, trace_id=trace_id),
        "displayTimeUnit": "ms",
        "otherData": {"generator": _GENERATOR},
    }


def export_timeline(paths: Sequence, out_path,
                    trace_id: Optional[str] = None,
                    labels: Optional[Sequence[str]] = None) -> dict:
    """Merge journal files into one timeline JSON written to
    ``out_path``; returns the document.  ``labels`` names the Perfetto
    process rows (defaults to each file's stem)."""
    sources = []
    for i, path in enumerate(paths):
        label = (labels[i] if labels is not None and i < len(labels)
                 else Path(path).stem)
        sources.append((label, journal_records(path)))
    doc = build_timeline(sources, trace_id=trace_id)
    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n",
                   encoding="utf-8")
    return doc
