"""Device-pipeline profiler: dispatch accounting + honest stage timing.

The performance half of the obs plane (GWP, Ren et al. — PAPERS.md):
always-on, low-overhead counters wired into the pipeline entry points
in :mod:`backuwup_tpu.ops.pipeline` / :mod:`backuwup_tpu.ops.backend`,
plus the chained-execution device timer that used to live duplicated
across ``scripts/devtime.py`` and the ``probe_*``/``profile_*`` pile.

Dispatch accounting semantics (the hand-countable contract the tests
pin; one *dispatch* = one device program launch, or its CPU-fallback
moral equivalent):

=========  =================================================================
stage      what counts as one dispatch
=========  =================================================================
scan       device: one fused ``scan_select_batch``/``scan_digest_batch``
           launch per batch.  CPU/native fallback: one ``chunk()`` pass
           per stream (native runs the whole pipeline in one C call per
           stream and counts once under every stage).
select     rides the scan program on every path (fused boundary
           selection), so it counts 1:1 with scan.
gather     device: one ``gather_chunks``/``_gather_digest`` tile launch.
           CPU fallback: one host piece-slicing pass per stream that
           produced at least one chunk.
digest     device: one batched digest launch (``_gather_digest`` tile,
           fused scan+digest batch, or ``blake3_many_tpu`` tiny-stream
           batch).  CPU fallback: one batched ``digest_many`` call per
           ``manifest_many``/stream segment with at least one piece.
index      one batched dedup classification per pack batch (device
           ``dedup_batch`` table classify or the host blob-index pass),
           bytes = 32 per ref classified.
=========  =================================================================

Bytes ride each dispatch twice: *actual* payload bytes and *padded*
bytes as dispatched (tile/bucket padding included), so
``bkw_pipeline_pad_efficiency`` exposes how much of every launch was
real work — the number PERF.md round-5 item 1 (merging the per-class
digest dispatches) moves.

Like the rest of ``obs/`` this module is import-light: stdlib +
defaults only; jax/numpy are imported lazily inside the timing helpers.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from . import journal as _journal
from . import metrics as _metrics

STAGES = ("scan", "select", "gather", "digest", "index")

_DISPATCH = _metrics.counter(
    "bkw_device_dispatch_total",
    "Pipeline dispatches by logical stage (a fused program counts once "
    "under every stage it implements)", labelnames=("stage",))
_STAGE_BYTES = _metrics.counter(
    "bkw_pipeline_stage_bytes_total",
    "Actual payload bytes processed per pipeline stage",
    labelnames=("stage",))
_STAGE_PADDED = _metrics.counter(
    "bkw_pipeline_stage_padded_bytes_total",
    "Bytes as dispatched per pipeline stage, tile/bucket padding "
    "included", labelnames=("stage",))
_PAD_EFFICIENCY = _metrics.gauge(
    "bkw_pipeline_pad_efficiency",
    "Cumulative actual/padded byte ratio per stage (1.0 = no padding "
    "waste)", labelnames=("stage",))
_PROFILE_SECONDS = _metrics.histogram(
    "bkw_profile_stage_seconds",
    "Honest chained-execution device seconds per profiled stage "
    "(dev_time_stage)", labelnames=("stage",))

# Per-device twins of the dispatch/bytes/pad families for the mesh
# pipeline (shard_map over the row axis).  Additive alongside the
# unlabeled families above, PR-7 style (bkw_peer_transfer_* next to
# bkw_transfer_*): one shard_map launch still counts ONCE per stage in
# bkw_device_dispatch_total, and additionally once per participating
# device here — so the unlabeled families keep their hand-countable
# "one program launch" meaning while these expose the per-shard split.
_DISPATCH_DEV = _metrics.counter(
    "bkw_mesh_device_dispatch_total",
    "Mesh-pipeline dispatches by stage and participating device shard",
    labelnames=("stage", "device"))
_STAGE_BYTES_DEV = _metrics.counter(
    "bkw_mesh_stage_bytes_total",
    "Actual payload bytes per stage per device shard",
    labelnames=("stage", "device"))
_STAGE_PADDED_DEV = _metrics.counter(
    "bkw_mesh_stage_padded_bytes_total",
    "Bytes as dispatched per stage per device shard, padding included",
    labelnames=("stage", "device"))
_PAD_EFFICIENCY_DEV = _metrics.gauge(
    "bkw_mesh_pad_efficiency",
    "Cumulative actual/padded byte ratio per stage per device shard",
    labelnames=("stage", "device"))
_HBM_HIGH = _metrics.gauge(
    "bkw_mesh_hbm_highwater_bytes",
    "Peak bytes in flight per device across the mesh driver's dispatch "
    "window (buffers + packed cuts + digest accumulator + dedup lanes)",
    labelnames=("device",))

# Tiered dedup index families (dedupstore/, docs/dedup_tiering.md): the
# hot/cold/host probe split, the promotion/demotion clock, and the HBM
# footprint of the hot fingerprint table.  Declared here (not in
# dedupstore/) so every family has exactly one construction site and the
# report below can fold the tier split into the per-backup delta.
TIER_PATHS = ("device", "cold", "host")

_TIER_PROBES = _metrics.counter(
    "bkw_tier_probes_total",
    "Tiered dedup probes by answering path (device = hot HBM table, "
    "cold = host LSM fall-through, host = authority fallback)",
    labelnames=("path",))
_TIER_HITS = _metrics.counter(
    "bkw_tier_hits_total",
    "Tiered dedup probe hits (key classified duplicate) by answering "
    "path", labelnames=("path",))
_TIER_PROMOTIONS = _metrics.counter(
    "bkw_tier_promotions_total",
    "Fingerprints promoted cold -> hot by the probe-frequency clock")
_TIER_DEMOTIONS = _metrics.counter(
    "bkw_tier_demotions_total",
    "Fingerprints demoted hot -> cold under the DEDUP_HBM_BUDGET_BYTES "
    "cap")
_TIER_HBM = _metrics.gauge(
    "bkw_tier_hbm_bytes",
    "Current HBM bytes held by the hot fingerprint table (slots x 20 "
    "bytes x mesh devices)")
_TIER_HBM_HIGH = _metrics.gauge(
    "bkw_tier_hbm_highwater_bytes",
    "Peak HBM bytes ever held by the hot fingerprint table")
_TIER_COLD_RUNS = _metrics.gauge(
    "bkw_tier_cold_runs",
    "Sorted immutable runs on disk in the cold fingerprint store")
_TIER_COLD_RECORDS = _metrics.gauge(
    "bkw_tier_cold_records",
    "Records across the cold store's runs + memtable (cross-run "
    "duplicates counted until compaction merges them)")
_TIER_COLD_COMMITS = _metrics.counter(
    "bkw_tier_cold_run_commits_total",
    "Durable cold-tier run commits by kind", labelnames=("kind",))

# Span names whose bkw_span_seconds sums a pipeline report attributes as
# per-stage wall time (the device pipeline's dispatch/collect pairs plus
# the packer entry point that drives them).
REPORT_SPANS = (
    "pipeline.scan_select_dispatch",
    "pipeline.cut_collect",
    "pipeline.digest_dispatch",
    "pipeline.digest_collect",
    "pipeline.scan_digest_dispatch",
    "pipeline.scan_digest_collect",
    "pipeline.mesh_dispatch",
    "pipeline.mesh_collect",
    "pipeline.h2d_stage",
    "packer.manifest_many",
)

# Streaming-dataflow overlap families (the engine's stage graph,
# docs/dataflow.md): per-stage busy seconds attributed to one backup at
# end of run, plus the overlap-efficiency verdict the bench
# `20_dataflow` gate watches.  Declared here — the single construction
# site for every bkw_* family — and folded by :func:`overlap_report`.
_BACKUP_STAGE_BUSY = _metrics.counter(
    "bkw_backup_stage_busy_seconds_total",
    "Busy seconds per backup dataflow stage (chunk_hash / seal / write /"
    " send), attributed per run from the stage-seconds registry deltas",
    labelnames=("stage",))
_BACKUP_OVERLAP = _metrics.gauge(
    "bkw_backup_overlap_efficiency",
    "max(per-stage busy seconds) / end-to-end wall for the most recent"
    " backup; 1.0 means the wall clock converged to the slowest stage")


def dispatch(stage: str, count: int = 1, actual_bytes: int = 0,
             padded_bytes: int = 0) -> None:
    """Record ``count`` dispatches for ``stage`` (see the module table
    for what counts as one).  Cheap enough to be always on."""
    if stage not in STAGES:
        raise ValueError(f"unknown pipeline stage {stage!r}")
    _DISPATCH.inc(count, stage=stage)
    if actual_bytes:
        _STAGE_BYTES.inc(actual_bytes, stage=stage)
    if padded_bytes:
        _STAGE_PADDED.inc(padded_bytes, stage=stage)
        padded = _STAGE_PADDED.value(stage=stage)
        if padded > 0:
            _PAD_EFFICIENCY.set(
                _STAGE_BYTES.value(stage=stage) / padded, stage=stage)


def dispatch_device(stage: str, device: int, count: int = 1,
                    actual_bytes: int = 0, padded_bytes: int = 0) -> None:
    """Record one device shard's share of a mesh launch.

    Touches ONLY the per-device families — the caller records the launch
    itself once via :func:`dispatch`, so ``bkw_device_dispatch_total``
    stays the hand-countable program-launch count and
    ``bkw_mesh_device_dispatch_total`` sums to launches x mesh size."""
    if stage not in STAGES:
        raise ValueError(f"unknown pipeline stage {stage!r}")
    dev = str(device)
    _DISPATCH_DEV.inc(count, stage=stage, device=dev)
    if actual_bytes:
        _STAGE_BYTES_DEV.inc(actual_bytes, stage=stage, device=dev)
    if padded_bytes:
        _STAGE_PADDED_DEV.inc(padded_bytes, stage=stage, device=dev)
        padded = _STAGE_PADDED_DEV.value(stage=stage, device=dev)
        if padded > 0:
            _PAD_EFFICIENCY_DEV.set(
                _STAGE_BYTES_DEV.value(stage=stage, device=dev) / padded,
                stage=stage, device=dev)


def hbm_high_water(device: int, in_flight_bytes: int) -> None:
    """Raise (never lower) the per-device HBM high-water gauge."""
    dev = str(device)
    cur = _HBM_HIGH.value(device=dev)
    if in_flight_bytes > cur:
        _HBM_HIGH.set(in_flight_bytes, device=dev)


# --- tiered dedup accounting (dedupstore/) -----------------------------------

def tier_probes(path: str, probes: int, hits: int = 0) -> None:
    """Record ``probes`` classify lanes answered on ``path`` (device /
    cold / host), ``hits`` of which classified duplicate."""
    if path not in TIER_PATHS:
        raise ValueError(f"unknown tier path {path!r}")
    if probes:
        _TIER_PROBES.inc(probes, path=path)
    if hits:
        _TIER_HITS.inc(hits, path=path)


def tier_promotions(n: int) -> None:
    if n:
        _TIER_PROMOTIONS.inc(n)


def tier_demotions(n: int) -> None:
    if n:
        _TIER_DEMOTIONS.inc(n)


def tier_hbm_bytes(table_bytes: int) -> None:
    """Set the hot-table HBM gauge; the high-water twin only rises."""
    _TIER_HBM.set(table_bytes)
    if table_bytes > _TIER_HBM_HIGH.value():
        _TIER_HBM_HIGH.set(table_bytes)


def tier_cold_state(runs: int, records: int) -> None:
    _TIER_COLD_RUNS.set(runs)
    _TIER_COLD_RECORDS.set(records)


def tier_cold_commit(kind: str) -> None:
    _TIER_COLD_COMMITS.inc(1, kind=kind)


# --- honest device timing (the scripts/devtime.py technique) ----------------

def _sync(out):
    """Force one tiny device->host download: block_until_ready lies on
    the dev rig, but a 1-element ``np.asarray`` cannot return before the
    producing computation finished."""
    import jax
    import numpy as np

    leaf = jax.tree_util.tree_leaves(out)[0]
    return np.asarray(leaf.ravel()[0])


def dev_time(fn, *args, n: int = 20) -> float:
    """Honest per-call device seconds for ``fn(*args)``.

    Times ``n`` chained executions plus ONE tiny download, subtracts the
    download-only baseline, and averages — dispatch overhead amortises
    while the sync cost cancels.  Callers must pass already-jitted
    callables with device-resident args."""
    out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    _sync(out)
    base = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    _sync(out)
    total = time.perf_counter() - t0
    return max(total - base, 1e-9) / n


def dev_time_stage(stage: str, fn, *args, n: int = 20) -> float:
    """:func:`dev_time` with the registry as sink: observes the result
    into ``bkw_profile_stage_seconds{stage}`` and journals a ``profile``
    event so one-off probe runs leave a durable record."""
    dt = dev_time(fn, *args, n=n)
    _PROFILE_SECONDS.observe(dt, stage=stage)
    _journal.emit("profile", stage=stage, dev_s=round(dt, 9), n=n)
    return dt


# --- per-backup pipeline report ---------------------------------------------

def _device_values(fam) -> Dict[tuple, float]:
    """{(device, stage): value} for one (stage, device)-labeled family."""
    return {(s["labels"]["device"], s["labels"]["stage"]): s["value"]
            for s in fam._snapshot_series()}


def baseline() -> Dict[str, Dict[str, float]]:
    """Snapshot the profiler families so :func:`report` can attribute a
    delta to one backup (the engine's ``_registry_stage_sums`` idiom)."""
    out = {"dispatch": {}, "bytes": {}, "padded": {}, "span_s": {},
           "dispatch_dev": _device_values(_DISPATCH_DEV),
           "bytes_dev": _device_values(_STAGE_BYTES_DEV),
           "padded_dev": _device_values(_STAGE_PADDED_DEV)}
    for stage in STAGES:
        out["dispatch"][stage] = _DISPATCH.value(stage=stage)
        out["bytes"][stage] = _STAGE_BYTES.value(stage=stage)
        out["padded"][stage] = _STAGE_PADDED.value(stage=stage)
    tier: Dict[str, float] = {"promotions": _TIER_PROMOTIONS.value(),
                              "demotions": _TIER_DEMOTIONS.value()}
    for path in TIER_PATHS:
        tier[f"probes_{path}"] = _TIER_PROBES.value(path=path)
        tier[f"hits_{path}"] = _TIER_HITS.value(path=path)
    out["tier"] = tier
    spans = _metrics.registry().get("bkw_span_seconds")
    if spans is not None:
        for name in REPORT_SPANS:
            out["span_s"][name] = spans.sum_value(name=name)
    return out


def report(base: Optional[dict] = None) -> dict:
    """Dispatch counts, bytes, padding efficiency, and stage seconds
    since ``base`` (or process start when ``base`` is None)."""
    now = baseline()
    base = base or {}

    def _delta(section: str) -> Dict[str, float]:
        prior = base.get(section, {})
        return {k: v - prior.get(k, 0.0) for k, v in now[section].items()}

    dispatches = {k: int(v) for k, v in _delta("dispatch").items()}
    actual = {k: int(v) for k, v in _delta("bytes").items()}
    padded = {k: int(v) for k, v in _delta("padded").items()}
    efficiency = {
        stage: (round(actual[stage] / padded[stage], 6)
                if padded[stage] > 0 else None)
        for stage in STAGES}
    stage_seconds = {name: round(dt, 6)
                     for name, dt in _delta("span_s").items() if dt > 0}
    # per-device split of the mesh-pipeline launches: {device: {stage: n}}
    # plus per-device pad efficiency, so the report shows whether work
    # divided evenly across the shards (the bench even-split gate)
    by_device: Dict[str, Dict[str, int]] = {}
    eff_device: Dict[str, Dict[str, Optional[float]]] = {}
    prior_d = base.get("dispatch_dev", {})
    now_d = now["dispatch_dev"]
    for (dev, stage), v in now_d.items():
        n = int(v - prior_d.get((dev, stage), 0.0))
        if n:
            by_device.setdefault(dev, {})[stage] = n
    prior_b, prior_p = base.get("bytes_dev", {}), base.get("padded_dev", {})
    for (dev, stage), v in now["padded_dev"].items():
        dp = v - prior_p.get((dev, stage), 0.0)
        if dp > 0:
            db = now["bytes_dev"].get((dev, stage), 0.0) \
                - prior_b.get((dev, stage), 0.0)
            eff_device.setdefault(dev, {})[stage] = round(db / dp, 6)
    out = {
        "dispatches": dispatches,
        "bytes": actual,
        "padded_bytes": padded,
        "pad_efficiency": efficiency,
        "stage_seconds": stage_seconds,
    }
    # tiered-dedup rows: probe/hit split per answering path plus the
    # promotion/demotion clock movement, only when the tier moved at all
    tier_delta = {k: int(v) for k, v in _delta("tier").items()}
    if any(tier_delta.values()):
        probes = {p: tier_delta[f"probes_{p}"] for p in TIER_PATHS}
        hits = {p: tier_delta[f"hits_{p}"] for p in TIER_PATHS}
        out["tier"] = {
            "probes": probes,
            "hits": hits,
            "promotions": tier_delta["promotions"],
            "demotions": tier_delta["demotions"],
            "device_hit_rate": (round(hits["device"] / probes["device"], 6)
                                if probes["device"] > 0 else None),
            "hbm_highwater_bytes": int(_TIER_HBM_HIGH.value()),
        }
    if by_device:
        out["device_dispatches"] = {
            d: by_device[d] for d in sorted(by_device, key=int)}
        out["device_pad_efficiency"] = {
            d: eff_device.get(d, {}) for d in sorted(by_device, key=int)}
    return out


def emit_report(rep: dict, **fields) -> None:
    """Journal one ``pipeline_report`` event (no-op without a journal,
    like every obs emission)."""
    _journal.emit("pipeline_report", report=rep, **fields)


def overlap_report(stage_busy: Dict[str, float], wall_s: float,
                   mode: str = "stream") -> dict:
    """Fold one backup's per-stage busy seconds into the overlap
    families and return the summary row the engine stores + journals.

    ``stage_busy`` must hold BUSY stages only — the caller excludes
    idle/wait accumulators (pack stall, transfer admission wait), which
    would otherwise reward a stalled pipeline.  Efficiency is
    max(stage)/wall: 1.0 means the end-to-end wall clock collapsed onto
    the slowest stage (perfect overlap); a phased run trends toward
    max/sum.  Concurrent fan-out can legitimately push a stage's summed
    busy seconds past the wall, so values above 1.0 are kept as-is."""
    busy = {k: max(float(v), 0.0) for k, v in stage_busy.items()}
    for stage, dt in busy.items():
        if dt > 0:
            _BACKUP_STAGE_BUSY.inc(dt, stage=stage)
    max_stage = max(busy.values(), default=0.0)
    eff = (max_stage / wall_s) if wall_s > 0 else 0.0
    _BACKUP_OVERLAP.set(eff)
    rep = {
        "mode": mode,
        "wall_s": round(wall_s, 6),
        "stage_busy_s": {k: round(v, 6) for k, v in busy.items()},
        "max_stage_s": round(max_stage, 6),
        "overlap_efficiency": round(eff, 6),
    }
    _journal.emit("overlap_report", **rep)
    return rep
