"""Hierarchical spans with Dapper-style trace/span ids.

Subsumes :mod:`backuwup_tpu.utils.tracing` (which remains as thin
wrappers over this module): the flat ``{name: (calls, total_s)}``
aggregate table and its ``BKW_TRACE`` gate keep their exact semantics,
while every span now additionally

* carries a **trace id** (64-bit hex) inherited from the enclosing span
  via a contextvar — ``asyncio.create_task`` copies the context, so the
  send tasks a backup spawns share the backup's trace id for free;
* observes its duration into the ``bkw_span_seconds{name}`` histogram
  (always on — the registry is how /metrics sees per-stage times);
* journals a ``span`` line (trace id, span id, parent id, duration)
  when a journal is installed (obs/journal.py).

Cross-process propagation (the Dapper model, PAPERS.md): the current
trace id rides as an *optional, unauthenticated* ``trace_id`` field on
p2p ``EncapsulatedMsg`` envelopes and client<->server JSON posts; the
receiving side re-enters it with :func:`bind`, so one backup's
pack -> seal -> transfer -> ack -> audit chain is joinable across peers
by grepping journals for one id.  Ids are observability metadata only:
they are outside the signed body and MUST never drive control flow.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import os
import random
import re
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Tuple

from . import journal as _journal
from . import metrics as _metrics

_SPAN_SECONDS = _metrics.histogram(
    "bkw_span_seconds", "Wall-clock duration of named trace spans",
    labelnames=("name",))

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{1,32}$")


@dataclass(frozen=True)
class SpanContext:
    """What the current task carries: the trace it belongs to and the
    innermost open span (None right after a cross-process bind)."""

    trace_id: str
    span_id: Optional[str] = None


_ctx: "contextvars.ContextVar[Optional[SpanContext]]" = \
    contextvars.ContextVar("bkw_trace_ctx", default=None)

# Span/trace ids come from one process-local PRNG (an os.urandom syscall
# per pipeline-segment span would be measurable); the lock keeps draws
# unique under the packer/seal/loop thread mix.
_id_lock = threading.Lock()
_id_rng = random.Random(int.from_bytes(os.urandom(8), "little"))


def _gen_hex(bits: int) -> str:
    with _id_lock:
        return f"{_id_rng.getrandbits(bits):0{bits // 4}x}"


def new_trace_id() -> str:
    return _gen_hex(64)


def new_span_id() -> str:
    return _gen_hex(32)


def clean_trace_id(value) -> Optional[str]:
    """Validate a wire-carried trace id (unauthenticated input): lowercase
    hex up to 32 chars, else None."""
    if not isinstance(value, str) or not _TRACE_ID_RE.match(value):
        return None
    return value


def current() -> Optional[SpanContext]:
    return _ctx.get()


def current_trace_id() -> Optional[str]:
    ctx = _ctx.get()
    return ctx.trace_id if ctx is not None else None


def current_span_id() -> Optional[str]:
    ctx = _ctx.get()
    return ctx.span_id if ctx is not None else None


@contextlib.contextmanager
def bind(trace_id: Optional[str]) -> Iterator[None]:
    """Adopt an incoming trace id (wire propagation); no-op on None, so
    receivers can bind unconditionally."""
    tid = clean_trace_id(trace_id)
    if tid is None:
        yield
        return
    token = _ctx.set(SpanContext(trace_id=tid))
    try:
        yield
    finally:
        _reset(token)


def _reset(token) -> None:
    # A coroutine closed by GC (e.g. an aborted aiohttp handler) runs its
    # finally blocks in whatever context the collector happened to be in;
    # ContextVar.reset then raises "created in a different Context".  The
    # binding dies with the coroutine either way, so swallow it.
    try:
        _ctx.reset(token)
    except ValueError:
        pass


# --- the flat aggregate table (exact utils/tracing.py semantics) ------------

_lock = threading.Lock()
_spans: Dict[str, Tuple[int, float]] = {}
_enabled = os.environ.get("BKW_TRACE", "0") == "1"


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def span(name: str) -> Iterator[SpanContext]:
    """One named span: times the block, propagates the trace id to
    everything started inside it, feeds the ``bkw_span_seconds``
    histogram, journals the close, and (only when ``BKW_TRACE``/
    :func:`enable` is on) accumulates into the flat report table."""
    parent = _ctx.get()
    trace_id = parent.trace_id if parent is not None else new_trace_id()
    ctx = SpanContext(trace_id=trace_id, span_id=new_span_id())
    token = _ctx.set(ctx)
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        dt = time.perf_counter() - t0
        _reset(token)
        if _enabled:
            with _lock:
                calls, total = _spans.get(name, (0, 0.0))
                _spans[name] = (calls + 1, total + dt)
        _SPAN_SECONDS.observe(dt, name=name)
        _journal.emit(
            "span", name=name, trace_id=trace_id, span_id=ctx.span_id,
            parent_id=(parent.span_id if parent is not None else None),
            dur_s=round(dt, 6))


def traced(name: str = None):
    """Decorator form of :func:`span`."""

    def deco(fn):
        label = name or f"{fn.__module__}.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kw):
            with span(label):
                return fn(*args, **kw)

        return wrapper

    return deco


def report() -> Dict[str, Tuple[int, float]]:
    with _lock:
        return dict(_spans)


def reset() -> None:
    with _lock:
        _spans.clear()


def format_report() -> str:
    rows = sorted(report().items(), key=lambda kv: -kv[1][1])
    if not rows:
        return "no spans recorded (BKW_TRACE=1 to enable)"
    width = max(len(k) for k, _ in rows)
    out = []
    for name, (calls, total) in rows:
        out.append(f"{name:<{width}}  {calls:>6}x  {total * 1e3:>10.1f} ms")
    return "\n".join(out)


@contextlib.contextmanager
def jax_profiler(section: str = "trace") -> Iterator[None]:
    """Capture a device profile into ``$BKW_TRACE_DIR/<section>`` when the
    env var is set; no-op (zero overhead) otherwise."""
    trace_dir = os.environ.get("BKW_TRACE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, section)):
        yield
