"""Metrics exposition: ``GET /metrics`` + ``GET /healthz`` plumbing.

Two consumers share these helpers:

* the coordination server (net/server.py) mounts the handlers directly
  on its existing aiohttp application;
* :class:`StatusServer` is a tiny standalone site for the opt-in client
  status port (``ClientApp(status_port=...)`` / ``BKW_STATUS_PORT``),
  so a headless client can be scraped without running the dashboard.

Deliberately NOT imported by ``obs/__init__`` — the obs core stays
stdlib-only, and aiohttp loads only where something actually serves.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from aiohttp import web

from . import metrics as _metrics

#: Prometheus text exposition content type (version 0.0.4).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def metrics_response() -> web.Response:
    """The registry rendered as Prometheus text exposition."""
    body = _metrics.registry().render_prometheus()
    resp = web.Response(text=body)
    resp.headers["Content-Type"] = CONTENT_TYPE
    return resp


def health_response(**fields) -> web.Response:
    """``{"status": "ok", ...fields}`` as JSON (liveness plus whatever
    cheap facts the mounting process wants to advertise).  A caller
    that passes ``status="violated"`` — a broken durability invariant
    (obs/invariants.py) — gets HTTP 503 so dumb probes flip without
    parsing the body; ``degraded`` stays 200 (data still restorable,
    margin shrinking)."""
    doc = {"status": "ok", **fields}
    code = 503 if doc.get("status") == "violated" else 200
    return web.json_response(doc, status=code)


class StatusServer:
    """Opt-in client status port: ``/metrics`` + ``/healthz`` only.

    ``health_fn`` (optional, zero-arg) returns extra fields merged into
    the /healthz document; ``before_metrics`` (optional, zero-arg) runs
    before each render so the owner can refresh point-in-time gauges.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 health_fn: Optional[Callable[[], dict]] = None,
                 before_metrics: Optional[Callable[[], None]] = None):
        self.host = host
        self.port = port
        self.health_fn = health_fn
        self.before_metrics = before_metrics
        self._runner: Optional[web.AppRunner] = None
        self._started = time.time()

    async def _metrics(self, _request) -> web.Response:
        if self.before_metrics is not None:
            self.before_metrics()
        return metrics_response()

    async def _healthz(self, _request) -> web.Response:
        fields = {"uptime_s": round(time.time() - self._started, 3)}
        if self.health_fn is not None:
            fields.update(self.health_fn())
        return health_response(**fields)

    async def start(self) -> int:
        self._started = time.time()
        app = web.Application()
        app.add_routes([web.get("/metrics", self._metrics),
                        web.get("/healthz", self._healthz)])
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
