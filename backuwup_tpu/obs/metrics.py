"""Process-wide metrics registry: labeled counters, gauges, histograms.

A deliberately small re-implementation of the Prometheus client data
model (the container bakes no ``prometheus_client`` wheel, and the
framework needs only a fraction of it):

* **Families are get-or-create.**  ``counter("bkw_x", ...)`` returns the
  existing family when one is already registered under that name, so
  every module can declare the metrics it touches at import time without
  coordinating import order; a name collision with a *different* type or
  label set is a programming error and raises :class:`MetricError`.
* **Thread-safe by construction.**  Every family guards its series map
  with one lock; producers on the packer thread, the seal workers, and
  the event loop can all increment concurrently and the totals are
  exact (covered by the threaded test in tests/test_obs.py).
* **Two read paths.**  :meth:`Registry.render_prometheus` emits the
  text exposition format (``# HELP``/``# TYPE`` + samples, histograms
  as cumulative ``_bucket``/``_sum``/``_count``) for ``GET /metrics``;
  :meth:`Registry.snapshot` returns a plain-JSON dict for bench records,
  panic dumps, and ``scripts/obs_dump.py``.

Histograms are log-bucketed (:func:`log_buckets`): stage times in this
system span ~1 ms device dispatches to ~30 s transfer stalls, a range a
linear bucket layout cannot cover with a fixed bucket count.

The metric name catalog lives in docs/observability.md.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class MetricError(ValueError):
    """Metric misuse: bad name, label mismatch, or type collision."""


def log_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """``count`` geometrically spaced upper bounds from ``start``
    (values rounded to 9 significant digits so renderings are stable)."""
    if start <= 0 or factor <= 1.0 or count < 1:
        raise MetricError("log_buckets needs start>0, factor>1, count>=1")
    return tuple(float(f"{start * factor ** i:.9g}") for i in range(count))


#: Default histogram layout for stage times: 1 ms .. ~32.8 s, doubling.
DEFAULT_SECONDS_BUCKETS = log_buckets(0.001, 2.0, 16)


def quantile_from_buckets(bounds: Sequence[float],
                          counts: Sequence[int], q: float) -> float:
    """Estimate the ``q``-quantile of a bucketed histogram.

    ``counts`` are *per-bucket* observation counts aligned with
    ``bounds`` plus one trailing overflow bucket (the internal
    :class:`Histogram` layout, NOT the cumulative exposition view).
    Within the located bucket the estimate interpolates geometrically
    (log-linear), matching the :func:`log_buckets` layout; the first
    bucket (lower edge 0) interpolates linearly.  Observations past the
    last bound clamp to it — the honest answer a bounded layout can
    give.  An empty histogram or an out-of-range ``q`` returns NaN.
    """
    total = sum(counts)
    if total <= 0 or not 0.0 <= q <= 1.0:
        return math.nan
    rank = q * total
    cum = 0
    for i, c in enumerate(counts[:-1]):
        prev = cum
        cum += c
        if cum >= rank and c > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = float(bounds[i])
            frac = (rank - prev) / c
            if lo > 0.0:
                return lo * (hi / lo) ** frac
            return hi * frac
    return float(bounds[-1])


def _fmt(v: float) -> str:
    """Prometheus sample-value formatting (integers without the .0)."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


class _Family:
    """One named metric family: fixed label names, many labeled series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise MetricError(f"bad metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise MetricError(f"bad label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        # hot path (every inc/observe): every declared label present and
        # no extras — checked without building throwaway sets
        try:
            key = tuple(str(labels[ln]) for ln in self.labelnames)
        except KeyError:
            key = None
        if key is None or len(labels) != len(self.labelnames):
            raise MetricError(
                f"{self.name} expects labels {self.labelnames},"
                f" got {tuple(sorted(labels))}")
        return key

    def _label_str(self, key: Tuple[str, ...],
                   extra: Optional[Tuple[str, str]] = None) -> str:
        pairs = [f'{ln}="{_escape_label(lv)}"'
                 for ln, lv in zip(self.labelnames, key)]
        if extra is not None:
            pairs.append(f'{extra[0]}="{_escape_label(extra[1])}"')
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def clear(self) -> None:
        with self._lock:
            self._series.clear()

    # subclasses implement:
    def _render_samples(self, out: List[str]) -> None:
        raise NotImplementedError

    def _snapshot_series(self) -> List[dict]:
        raise NotImplementedError


class Counter(_Family):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise MetricError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def _render_samples(self, out: List[str]) -> None:
        with self._lock:
            items = sorted(self._series.items())
        for key, v in items:
            out.append(f"{self.name}{self._label_str(key)} {_fmt(v)}")

    def _snapshot_series(self) -> List[dict]:
        with self._lock:
            items = sorted(self._series.items())
        return [{"labels": dict(zip(self.labelnames, key)), "value": float(v)}
                for key, v in items]


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labelnames)
        bounds = tuple(sorted(float(b) for b in
                              (DEFAULT_SECONDS_BUCKETS if buckets is None
                               else buckets)))
        if not bounds or len(set(bounds)) != len(bounds):
            raise MetricError(f"histogram {name}: bad bucket bounds")
        if bounds and bounds[-1] == math.inf:
            bounds = bounds[:-1]  # +Inf is implicit
        self.bounds = bounds

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)  # first bound with v <= le
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = self._series[key] = \
                    [[0] * (len(self.bounds) + 1), 0.0]
            state[0][i] += 1
            state[1] += v

    def sum_value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            return float(state[1]) if state else 0.0

    def count_value(self, **labels) -> int:
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            return sum(state[0]) if state else 0

    def quantile(self, q: float, **labels) -> float:
        """Estimated ``q``-quantile for one labeled series (NaN when the
        series has no observations) — see :func:`quantile_from_buckets`."""
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            counts = list(state[0]) if state else None
        if counts is None:
            return math.nan
        return quantile_from_buckets(self.bounds, counts, q)

    def bucket_counts(self, **labels) -> Dict[str, int]:
        """Cumulative per-``le`` counts (the exposition view)."""
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            counts = list(state[0]) if state else [0] * (len(self.bounds) + 1)
        out, running = {}, 0
        for bound, c in zip(self.bounds, counts):
            running += c
            out[_fmt(bound)] = running
        out["+Inf"] = running + counts[-1]
        return out

    def _render_samples(self, out: List[str]) -> None:
        with self._lock:
            items = sorted((k, (list(s[0]), s[1]))
                           for k, s in self._series.items())
        for key, (counts, total) in items:
            running = 0
            for bound, c in zip(self.bounds, counts):
                running += c
                out.append(f"{self.name}_bucket"
                           f"{self._label_str(key, ('le', _fmt(bound)))}"
                           f" {running}")
            running += counts[-1]
            out.append(f"{self.name}_bucket"
                       f"{self._label_str(key, ('le', '+Inf'))} {running}")
            out.append(f"{self.name}_sum{self._label_str(key)} {_fmt(total)}")
            out.append(f"{self.name}_count{self._label_str(key)} {running}")

    def _snapshot_series(self) -> List[dict]:
        with self._lock:
            items = sorted((k, (list(s[0]), s[1]))
                           for k, s in self._series.items())
        out = []
        for key, (counts, total) in items:
            buckets, running = {}, 0
            for bound, c in zip(self.bounds, counts):
                running += c
                buckets[_fmt(bound)] = running
            buckets["+Inf"] = running + counts[-1]
            out.append({"labels": dict(zip(self.labelnames, key)),
                        "sum": float(total), "count": buckets["+Inf"],
                        "buckets": buckets})
        return out


class Registry:
    """Get-or-create store of metric families; the process global lives
    in :data:`_REGISTRY` (:func:`registry`)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}

    def _get_or_make(self, cls, name: str, help: str,
                     labelnames: Sequence[str], **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not cls \
                        or fam.labelnames != tuple(labelnames):
                    raise MetricError(
                        f"metric {name} already registered as"
                        f" {fam.kind}{fam.labelnames}")
                return fam
            fam = cls(name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_make(Histogram, name, help, labelnames,
                                 buckets=buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def render_prometheus(self) -> str:
        """The text exposition format, families sorted by name."""
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        out: List[str] = []
        for fam in fams:
            if fam.help:
                out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            fam._render_samples(out)
        return "\n".join(out) + "\n" if out else ""

    def snapshot(self) -> dict:
        """Plain-JSON view: {name: {type, help, labels, series}}."""
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        return {fam.name: {"type": fam.kind, "help": fam.help,
                           "labels": list(fam.labelnames),
                           "series": fam._snapshot_series()}
                for fam in fams}

    def reset(self) -> None:
        """Zero every series but keep families registered (module-level
        handles stay valid) — the test-isolation hook."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            fam.clear()


#: The process-wide registry every subsystem instruments into.
_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _REGISTRY.histogram(name, help, labelnames, buckets)
