"""Declarative SLOs evaluated as multi-window burn rates.

The catalog (``defaults.SLO_CATALOG``) states the system's service-level
objectives as data: which ``bkw_*`` family is the bad-event signal, what
fraction of bad events the error budget tolerates, and how the bad
fraction is derived (counter rate, event ratio, histogram quantile,
gauge floor).  :class:`SLOMonitor` evaluates every objective over the
Google-SRE multi-window scheme: ``burn = bad_fraction / budget`` is
computed for a fast window pair (5 m / 1 h) and a slow pair (6 h / 3 d);
the objective is **violated** when both fast windows burn at/above
``SLO_FAST_BURN`` (an active incident — at 14.4x the month's budget
dies in ~2 days) and **degraded** when both slow windows burn at/above
``SLO_SLOW_BURN`` (a smoldering leak).  Requiring both windows of a
pair keeps one spike from paging and keeps a long-cleared incident from
re-paging — the standard reset/derail trade the SRE workbook describes.

Everything reads from a :class:`~backuwup_tpu.obs.series.SeriesRecorder`
— never the wall clock and never the raw registry — so the same monitor
runs on virtual time under the sim driver (a simulated week of burn
history in tier-1 seconds) and on wall time in ``ClientApp``.  While
the recorder's history is still shorter than a window, burn math uses
the actually-covered span (an honest partial answer beats a silent
zero), and an objective whose signal has no observations at all scores
burn 0 — absence of traffic is not an incident.

Results are exported as ``bkw_slo_*`` gauges, joined into the client and
server ``/healthz`` tri-state via :func:`summary_from_registry` (the
``obs/invariants.py`` pattern), journaled as ``slo_breach`` events on
every status transition, and handed to the diagnosis hook so a breach
arrives with its evidence attached (obs/diagnose.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .. import defaults
from ..utils import clock as clockmod
from . import journal as obs_journal
from . import metrics as obs_metrics
from .invariants import (_LEVEL_STATUS, _STATUS_LEVEL, STATUS_DEGRADED,
                         STATUS_OK, STATUS_VIOLATED)

_KINDS = ("counter_rate", "ratio", "quantile", "gauge_below")

_G_BURN = obs_metrics.gauge(
    "bkw_slo_burn_rate",
    "Error-budget burn rate per objective and window (1.0 = exactly"
    " on budget)", ("objective", "window"))
_G_STATUS = obs_metrics.gauge(
    "bkw_slo_status",
    "Objective health: 0 ok, 1 degraded (slow burn), 2 violated (fast"
    " burn)", ("objective",))
_C_BREACHES = obs_metrics.counter(
    "bkw_slo_breaches_total",
    "Objective transitions into a worse status", ("objective",))
_C_EVALS = obs_metrics.counter(
    "bkw_slo_evaluations_total", "SLO evaluation sweeps completed")


class SLOError(ValueError):
    """Malformed catalog entry (bkwlint BKW007 catches these statically;
    this is the runtime backstop)."""


@dataclass(frozen=True)
class Objective:
    """One parsed catalog entry."""

    id: str
    kind: str
    family: str
    labels: Tuple[Tuple[str, str], ...] = ()
    budget: float = 0.01
    target: float = 0.0
    total_family: str = ""
    description: str = ""

    @staticmethod
    def from_entry(entry: dict) -> "Objective":
        oid = str(entry.get("id", ""))
        kind = str(entry.get("kind", ""))
        if not oid or kind not in _KINDS:
            raise SLOError(f"bad SLO entry {entry!r}")
        if kind == "ratio" and not entry.get("total_family"):
            raise SLOError(f"SLO {oid!r}: ratio needs total_family")
        budget = float(entry.get("budget", 0.01))
        if budget <= 0:
            raise SLOError(f"SLO {oid!r}: budget must be > 0")
        return Objective(
            id=oid, kind=kind, family=str(entry.get("family", "")),
            labels=tuple(sorted((str(k), str(v)) for k, v in
                                dict(entry.get("labels") or {}).items())),
            budget=budget, target=float(entry.get("target", 0.0)),
            total_family=str(entry.get("total_family", "")),
            description=str(entry.get("description", "")))


def parse_catalog(entries=None) -> List[Objective]:
    entries = defaults.SLO_CATALOG if entries is None else entries
    out = [Objective.from_entry(e) for e in entries]
    seen = set()
    for obj in out:
        if obj.id in seen:
            raise SLOError(f"duplicate SLO id {obj.id!r}")
        seen.add(obj.id)
    return out


@dataclass
class Breach:
    """One objective's transition into a worse status."""

    objective: str
    t: float
    status: str
    prev_status: str
    burns: Dict[str, float] = field(default_factory=dict)
    window_s: float = 0.0

    def to_dict(self) -> dict:
        return {"objective": self.objective, "t": round(self.t, 6),
                "status": self.status, "prev_status": self.prev_status,
                "burns": {k: round(v, 4) for k, v in self.burns.items()},
                "window_s": round(self.window_s, 3)}


def _win_tag(w: float) -> str:
    return f"{w:g}s"


class SLOMonitor:
    """Evaluates the objective catalog against a SeriesRecorder.

    ``windows`` is the pair-of-pairs ((fast_short, fast_long),
    (slow_short, slow_long)); the scenario harness shrinks it onto
    loopback seconds, the sim keeps the real spans on virtual time.
    ``on_breach`` (optional) receives each :class:`Breach` — the
    diagnosis hook.  ``client`` only tags journal lines so colocated
    test processes stay attributable.
    """

    def __init__(self, recorder, catalog=None, clock=None,
                 windows=None, fast_burn: Optional[float] = None,
                 slow_burn: Optional[float] = None,
                 on_breach: Optional[Callable] = None,
                 client: str = "main"):
        self.recorder = recorder
        self.catalog: List[Objective] = (
            catalog if catalog and isinstance(catalog[0], Objective)
            else parse_catalog(catalog))
        self.clock = clockmod.resolve(clock)
        self.windows = tuple(tuple(float(w) for w in pair) for pair in
                             (defaults.SLO_WINDOWS if windows is None
                              else windows))
        self.fast_burn = float(defaults.SLO_FAST_BURN
                               if fast_burn is None else fast_burn)
        self.slow_burn = float(defaults.SLO_SLOW_BURN
                               if slow_burn is None else slow_burn)
        self.on_breach = on_breach
        self.client = client
        self.status: Dict[str, str] = {o.id: STATUS_OK
                                       for o in self.catalog}
        self.breaches: List[Breach] = []
        self.last_burns: Dict[str, Dict[str, float]] = {}

    # --- bad-fraction derivation -------------------------------------------

    def _bad_fraction(self, obj: Objective,
                      window_s: float) -> Optional[float]:
        """The window's bad-event fraction, or None when the signal has
        nothing to judge (no traffic != an incident)."""
        rec = self.recorder
        labels = dict(obj.labels)
        keys = rec.family_keys(obj.family, labels)
        if obj.kind == "counter_rate":
            span = max((rec.span(k, window_s) for k in keys),
                       default=0.0)
            if span <= 0:
                return None
            bad = sum(rec.delta(k, window_s) for k in keys)
            return min(1.0, bad / span)
        if obj.kind == "ratio":
            total = sum(rec.delta(k, window_s) for k in
                        rec.family_keys(obj.total_family, {}))
            if total <= 0:
                return None
            bad = sum(rec.delta(k, window_s) for k in keys)
            return min(1.0, bad / total)
        if obj.kind == "quantile":
            over = cnt = 0.0
            for k in keys:
                win = rec.hist_window(k, window_s)
                if win is None:
                    continue
                bounds, per, n, _s = win
                if n <= 0:
                    continue
                cnt += n
                over += sum(c for b, c in zip(bounds, per[:-1])
                            if b > obj.target) + per[-1]
            if cnt <= 0:
                return None
            return over / cnt
        # gauge_below: fraction of window samples under the floor
        below = total = 0
        for k in keys:
            for _t, v in rec.points(k, window_s):
                total += 1
                if v < obj.target:
                    below += 1
        if total <= 0:
            return None
        return below / total

    # --- evaluation --------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> Dict[str, str]:
        """One sweep: burn per window per objective, status transitions,
        gauges, journal, breach hook.  Returns {objective: status}.

        ``now`` stamps breaches on ``clock.now()`` — the journal's time
        axis — so the explainer can window events against a breach."""
        now = self.clock.now() if now is None else float(now)
        _C_EVALS.inc()
        (fast_a, fast_b), (slow_a, slow_b) = self.windows
        for obj in self.catalog:
            burns: Dict[str, float] = {}
            for w in (fast_a, fast_b, slow_a, slow_b):
                frac = self._bad_fraction(obj, w)
                burns[_win_tag(w)] = 0.0 if frac is None \
                    else frac / obj.budget
                _G_BURN.set(burns[_win_tag(w)], objective=obj.id,
                            window=_win_tag(w))
            self.last_burns[obj.id] = burns
            fast_fired = (burns[_win_tag(fast_a)] >= self.fast_burn
                          and burns[_win_tag(fast_b)] >= self.fast_burn)
            slow_fired = (burns[_win_tag(slow_a)] >= self.slow_burn
                          and burns[_win_tag(slow_b)] >= self.slow_burn)
            status = (STATUS_VIOLATED if fast_fired
                      else STATUS_DEGRADED if slow_fired else STATUS_OK)
            prev = self.status[obj.id]
            self.status[obj.id] = status
            _G_STATUS.set(_STATUS_LEVEL[status], objective=obj.id)
            if _STATUS_LEVEL[status] > _STATUS_LEVEL[prev]:
                breach = Breach(objective=obj.id, t=now, status=status,
                                prev_status=prev, burns=dict(burns),
                                window_s=fast_b)
                self.breaches.append(breach)
                _C_BREACHES.inc(objective=obj.id)
                obs_journal.emit("slo_breach", client=self.client,
                                 **breach.to_dict())
                if self.on_breach is not None:
                    try:
                        self.on_breach(breach)
                    except Exception as e:  # diagnosis must not kill eval
                        obs_journal.emit("slo_diagnose_error",
                                         objective=obj.id,
                                         error=repr(e)[:200])
            elif _STATUS_LEVEL[status] < _STATUS_LEVEL[prev]:
                obs_journal.emit("slo_recovered", client=self.client,
                                 objective=obj.id, status=status,
                                 t=round(now, 6))
        return dict(self.status)

    # --- summaries ---------------------------------------------------------

    def summary(self) -> dict:
        level = max([_STATUS_LEVEL[s] for s in self.status.values()],
                    default=0)
        return {
            "status": _LEVEL_STATUS[level],
            "objectives": dict(sorted(self.status.items())),
            "breaches": len(self.breaches),
        }


def summary_from_registry() -> dict:
    """Cross-process SLO summary from the registry gauges — what
    ``/healthz`` reports without holding a monitor reference (the
    ``obs/invariants.py`` pattern).  All-ok / empty in a process where
    no monitor has evaluated yet."""
    objectives: Dict[str, str] = {}
    level = 0
    for series in _G_STATUS._snapshot_series():
        lv = int(series["value"])
        objectives[series["labels"].get("objective", "?")] = \
            _LEVEL_STATUS.get(lv, STATUS_VIOLATED)
        level = max(level, lv)
    breaches = 0
    fam = obs_metrics.registry().get("bkw_slo_breaches_total")
    if fam is not None:
        breaches = int(sum(s["value"] for s in fam._snapshot_series()))
    return {"status": _LEVEL_STATUS.get(level, STATUS_VIOLATED),
            "objectives": dict(sorted(objectives.items())),
            "breaches": breaches}


def join_status(*statuses: str) -> str:
    """Worst-of tri-state join (durability x SLO for /healthz)."""
    level = max((_STATUS_LEVEL.get(s, 2) for s in statuses), default=0)
    return _LEVEL_STATUS[level]
