"""Live durability invariants: does the system's core promise hold NOW?

The paper's promise is that randomly matched, mutually untrusting peers
keep each other's encrypted data restorable.  Every prior layer enforces
a piece of that promise (audits demote droppers, erasure survives k-of-n
loss, repair re-homes), but nothing could *state* whether it currently
holds.  :class:`InvariantMonitor` closes that gap: it sweeps the
verifier-side source of truth — the placements table, the blob index,
the audit ledger, and the demotion set in :mod:`backuwup_tpu.store` —
and computes point-in-time durability facts:

* per-stripe clean-survivor count vs RS_K (degraded when shards are on
  lost peers but >= k clean survive; LOST when fewer than k survive and
  no whole replica is alive — the data is unrestorable right now);
* packfiles whose every holder is demoted or dark;
* repair debt: bytes sitting on lost peers that a repair round would
  re-home;
* orphaned placements (rows for packfiles the blob index no longer
  references — leaked storage on peers);
* audit-coverage age: how stale the oldest attestation over any
  placement-holding peer is.

Facts are published as ``bkw_durability_*`` gauges (labeled by client so
multi-client test processes don't fight over one series), summarized in
the server ``/healthz`` and the client status port, and accrued into
``bkw_durability_violation_seconds_total`` — the scorecard's headline
"how long was data actually at risk" number (scenario/scorecard.py).

A *lost* peer here is exactly the repair plane's definition
(:func:`lost_peers`, shared with ``engine._lost_peers``): audit-demoted,
or dark past ``defaults.PEER_DARK_DEADLINE_S``.  Health flips to
``degraded`` while every byte is still restorable — the operator (or the
scenario gate) hears about shrinking margin *before* it hits zero.

Stdlib-only, like the rest of the obs core.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .. import defaults
from . import journal as obs_journal
from . import metrics as obs_metrics
from ..utils import clock as clockmod

#: Health taxonomy, worst-first when comparing: every fact is either
#: fine, a shrinking safety margin, or a broken promise.
STATUS_OK = "ok"
STATUS_DEGRADED = "degraded"
STATUS_VIOLATED = "violated"
_STATUS_LEVEL = {STATUS_OK: 0, STATUS_DEGRADED: 1, STATUS_VIOLATED: 2}
_LEVEL_STATUS = {v: k for k, v in _STATUS_LEVEL.items()}

_LABELS = ("client",)
_G_STRIPES = obs_metrics.gauge(
    "bkw_durability_stripes_total",
    "Packfiles currently placed as erasure stripes", _LABELS)
_G_DEGRADED = obs_metrics.gauge(
    "bkw_durability_stripes_degraded",
    "Stripes with lost shards but >= RS_K clean survivors", _LABELS)
_G_LOST = obs_metrics.gauge(
    "bkw_durability_stripes_lost",
    "Stripes with < RS_K clean survivors and no live whole copy", _LABELS)
_G_UNRESTORABLE = obs_metrics.gauge(
    "bkw_durability_packfiles_unrestorable",
    "Packfiles (striped or whole) with no restorable copy", _LABELS)
_G_REPAIR_DEBT = obs_metrics.gauge(
    "bkw_durability_repair_debt_bytes",
    "Bytes placed on lost peers awaiting repair re-home", _LABELS)
_G_ORPHANED = obs_metrics.gauge(
    "bkw_durability_orphaned_placements",
    "Placement rows for packfiles the blob index no longer references",
    _LABELS)
_G_AUDIT_AGE = obs_metrics.gauge(
    "bkw_durability_audit_coverage_age_seconds",
    "Age of the stalest attestation over placement-holding peers", _LABELS)
_G_STATUS = obs_metrics.gauge(
    "bkw_durability_status",
    "Durability health: 0 ok, 1 degraded, 2 violated", _LABELS)
_C_VIOLATION_S = obs_metrics.counter(
    "bkw_durability_violation_seconds_total",
    "Monotonic-clock seconds spent with a durability invariant violated",
    _LABELS)
_C_SWEEPS = obs_metrics.counter(
    "bkw_durability_sweeps_total", "Invariant monitor sweeps", _LABELS)

#: Gauge handles by summary key, for :func:`summary_from_registry`.
_FACT_GAUGES = {
    "stripes_total": _G_STRIPES,
    "stripes_degraded": _G_DEGRADED,
    "stripes_lost": _G_LOST,
    "packfiles_unrestorable": _G_UNRESTORABLE,
    "repair_debt_bytes": _G_REPAIR_DEBT,
    "orphaned_placements": _G_ORPHANED,
}


def lost_peers(store, now: float) -> Set[bytes]:
    """Placement-holding peers considered LOST: audit-demoted, or dark
    (unseen) past ``defaults.PEER_DARK_DEADLINE_S``.  The single shared
    definition — the repair plane (``engine._lost_peers``) and the
    invariant monitor must never disagree about which peers count."""
    lost: Set[bytes] = set()
    for peer in store.peers_with_placements():
        peer = bytes(peer)
        if store.get_audit_state(peer).demoted:
            lost.add(peer)
            continue
        info = store.get_peer(peer)
        if info is not None and info.last_seen is not None and \
                now - info.last_seen > defaults.PEER_DARK_DEADLINE_S:
            lost.add(peer)
    return lost


@dataclass
class InvariantReport:
    """One sweep's durability facts (see module docstring for meaning)."""

    now: float
    stripes_total: int = 0
    stripes_degraded: int = 0
    stripes_lost: int = 0
    packfiles_total: int = 0
    packfiles_unrestorable: int = 0
    placements_total: int = 0
    lost_peer_count: int = 0
    repair_debt_bytes: int = 0
    orphaned_placements: int = 0
    audit_coverage_age_s: float = 0.0
    violations: List[str] = field(default_factory=list)
    degradations: List[str] = field(default_factory=list)

    @property
    def status(self) -> str:
        if self.violations:
            return STATUS_VIOLATED
        if self.degradations:
            return STATUS_DEGRADED
        return STATUS_OK

    @property
    def summary(self) -> dict:
        """The /healthz- and scorecard-facing view."""
        return {
            "status": self.status,
            "stripes_total": self.stripes_total,
            "stripes_degraded": self.stripes_degraded,
            "stripes_lost": self.stripes_lost,
            "packfiles_unrestorable": self.packfiles_unrestorable,
            "repair_debt_bytes": self.repair_debt_bytes,
            "orphaned_placements": self.orphaned_placements,
            "audit_coverage_age_s": round(self.audit_coverage_age_s, 3),
            "violations": list(self.violations),
            "degradations": list(self.degradations),
        }


class InvariantMonitor:
    """Sweeps one client's verifier-side state into durability facts.

    ``index`` (a :class:`~backuwup_tpu.snapshot.blob_index.BlobIndex`,
    optional) enables the orphaned-placement check; without it that fact
    stays 0.  ``client`` labels the published series.  :meth:`sweep` is
    synchronous and cheap (one placements query + one ledger read per
    holder); :meth:`run` wraps it in a background cadence for
    ``ClientApp``.
    """

    def __init__(self, store, index=None, client: str = "main",
                 clock=None):
        self.store = store
        self.index = index
        self.client = client
        self.clock = clockmod.resolve(clock)
        self.last_report: Optional[InvariantReport] = None
        self._last_mono: Optional[float] = None

    # --- the sweep ---------------------------------------------------------

    def sweep(self, now: Optional[float] = None) -> InvariantReport:
        # ``now`` is wall-compatible (judged against persisted last_seen/
        # sent_at timestamps); the violation-seconds accrual interval is
        # measured on the monotonic clock so an NTP step can neither
        # inflate nor hide time-at-risk.  Callers that pin ``now`` (tests,
        # the sim) get it used for both — explicit virtual time IS the
        # monotonic axis there.
        mono = self.clock.monotonic() if now is None else now
        now = self.clock.now() if now is None else now
        rep = InvariantReport(now=now)
        rows = self.store.all_placements()
        lost = lost_peers(self.store, now)
        rep.placements_total = len(rows)
        rep.lost_peer_count = len(lost)

        by_pid: Dict[bytes, List[Tuple[bytes, int, int]]] = {}
        for pid, peer, size, shard_index, _sent_at in rows:
            by_pid.setdefault(pid, []).append((peer, size, shard_index))
        rep.packfiles_total = len(by_pid)

        k = defaults.RS_K
        n = defaults.RS_K + defaults.RS_M
        for pid, prows in sorted(by_pid.items()):
            tag = pid.hex()[:12]
            whole_alive = any(idx < 0 and peer not in lost
                              for peer, _s, idx in prows)
            lost_rows = sum(1 for peer, _s, _i in prows if peer in lost)
            rep.repair_debt_bytes += sum(
                size for peer, size, _i in prows if peer in lost)
            stripe_rows = [(peer, idx) for peer, _s, idx in prows
                           if idx >= 0]
            if stripe_rows:
                rep.stripes_total += 1
                # a re-striped packfile may have more than n rows while a
                # repair is mid-flight; judge against the wider of the two
                expected = max(n, max(idx for _p, idx in stripe_rows) + 1)
                clean = len({idx for peer, idx in stripe_rows
                             if peer not in lost})
                if whole_alive:
                    continue  # a live full replica trumps stripe math
                if clean < k and lost_rows:
                    rep.stripes_lost += 1
                    rep.packfiles_unrestorable += 1
                    rep.violations.append(
                        f"stripe {tag}: {clean}/{k} clean survivors"
                        " — unrestorable")
                elif clean < expected:
                    # either shards sit on lost peers (> k still clean)
                    # or the stripe is mid-upload: placements land
                    # per-ack, so a backup in flight is visibly short of
                    # coverage without any peer having been lost
                    rep.stripes_degraded += 1
                    why = "lost shard(s)" if lost_rows else "incomplete"
                    rep.degradations.append(
                        f"stripe {tag}: {clean}/{expected} clean shards"
                        f" ({why}; safe at >= {k})")
            elif not whole_alive and lost_rows:
                rep.packfiles_unrestorable += 1
                rep.violations.append(
                    f"packfile {tag}: every replica on a lost peer")

        if rep.repair_debt_bytes and not rep.violations:
            rep.degradations.append(
                f"{rep.repair_debt_bytes} bytes on lost peers await repair")

        # orphaned placements: rows whose packfile the blob index no
        # longer references (leaked peer storage, e.g. a forgotten repair)
        if self.index is not None and by_pid:
            try:
                live_pids = self.index.packfile_ids()
            except RuntimeError:  # index mutating concurrently; next sweep
                live_pids = None
            if live_pids:
                rep.orphaned_placements = sum(
                    len(prows) for pid, prows in by_pid.items()
                    if pid not in live_pids)
                if rep.orphaned_placements:
                    rep.degradations.append(
                        f"{rep.orphaned_placements} placement rows orphaned"
                        " by the blob index")

        # audit-coverage age: the stalest attestation across holders; a
        # never-audited holder counts from its first placement
        holders: Dict[bytes, float] = {}
        for _pid, peer, _size, _idx, sent_at in rows:
            holders[peer] = min(holders.get(peer, sent_at), sent_at)
        worst = 0.0
        for peer, first_sent in holders.items():
            st = self.store.get_audit_state(peer)
            basis = st.last_audit if st.last_audit else first_sent
            worst = max(worst, now - basis)
        rep.audit_coverage_age_s = max(0.0, worst)
        if rep.audit_coverage_age_s > defaults.DURABILITY_AUDIT_MAX_AGE_S:
            rep.degradations.append(
                f"stalest audit {rep.audit_coverage_age_s:.0f}s old"
                f" (> {defaults.DURABILITY_AUDIT_MAX_AGE_S:.0f}s)")

        self._publish(rep, mono)
        return rep

    def _publish(self, rep: InvariantReport, mono: float) -> None:
        c = self.client
        _G_STRIPES.set(rep.stripes_total, client=c)
        _G_DEGRADED.set(rep.stripes_degraded, client=c)
        _G_LOST.set(rep.stripes_lost, client=c)
        _G_UNRESTORABLE.set(rep.packfiles_unrestorable, client=c)
        _G_REPAIR_DEBT.set(rep.repair_debt_bytes, client=c)
        _G_ORPHANED.set(rep.orphaned_placements, client=c)
        _G_AUDIT_AGE.set(rep.audit_coverage_age_s, client=c)
        _G_STATUS.set(_STATUS_LEVEL[rep.status], client=c)
        _C_SWEEPS.inc(client=c)
        # violation time accrues over the interval the PREVIOUS sweep
        # proved violated — the first bad sweep starts the clock
        prev = self.last_report
        if prev is not None and self._last_mono is not None \
                and prev.status == STATUS_VIOLATED \
                and mono > self._last_mono:
            _C_VIOLATION_S.inc(mono - self._last_mono, client=c)
        if prev is None or prev.status != rep.status:
            obs_journal.emit("durability", client=c, status=rep.status,
                             stripes_degraded=rep.stripes_degraded,
                             stripes_lost=rep.stripes_lost,
                             unrestorable=rep.packfiles_unrestorable,
                             repair_debt_bytes=rep.repair_debt_bytes)
        self.last_report = rep
        self._last_mono = mono

    # --- background cadence ------------------------------------------------

    async def run(self, interval_s: Optional[float] = None,
                  janitor=None) -> None:
        """Sweep-then-sleep forever (cancel to stop); the ClientApp
        background task.  Sweeping FIRST makes health current within one
        interval of any state change.  ``janitor`` (a blocking callable,
        e.g. ``Engine.expire_partials``) piggybacks on the same cadence
        so receiver-side TTL hygiene runs on live processes too, not
        only inside startup recovery — it runs on the executor and its
        failures are contained like a sweep bug's."""
        interval = defaults.DURABILITY_SWEEP_INTERVAL_S \
            if interval_s is None else interval_s
        loop = asyncio.get_running_loop()
        while True:
            try:
                self.sweep()
            except Exception as e:  # a sweep bug must not kill the app
                obs_journal.emit("durability_sweep_error", client=self.client,
                                 error=repr(e)[:200])
            if janitor is not None:
                try:
                    await loop.run_in_executor(None, janitor)
                except Exception as e:
                    obs_journal.emit("durability_sweep_error",
                                     client=self.client,
                                     error=repr(e)[:200])
            await self.clock.sleep(interval)


def summary_from_registry() -> dict:
    """Cross-client durability summary from the process registry — what
    the coordination server's ``/healthz`` reports when clients are
    colocated (the scenario harness, tests, bench), and all zeros /
    ``ok`` in a standalone server process.  Counts sum across client
    labels; status and audit age take the worst."""
    out = {key: 0 for key in _FACT_GAUGES}
    level = 0
    age = 0.0
    for key, gauge in _FACT_GAUGES.items():
        for series in gauge._snapshot_series():
            out[key] += int(series["value"])
    for series in _G_STATUS._snapshot_series():
        level = max(level, int(series["value"]))
    for series in _G_AUDIT_AGE._snapshot_series():
        age = max(age, float(series["value"]))
    out["audit_coverage_age_s"] = round(age, 3)
    out["status"] = _LEVEL_STATUS.get(level, STATUS_VIOLATED)
    return out
