"""Bounded in-process time series over the metrics registry.

GWP's argument (PAPERS.md) is that regressions are findable only when
telemetry is *continuously* collected — a cumulative counter snapshot
says where the system is, never how it got there.  This module closes
that gap without an external TSDB: a :class:`SeriesRecorder` samples a
selected set of ``bkw_*`` registry families on a cadence into bounded
per-series ring buffers, and derives the windowed views the SLO plane
(obs/slo.py) and the breach explainer (obs/diagnose.py) need:

* ``delta``/``rate`` over a trailing window for counters (reset-safe:
  a shrinking cumulative value clamps to the post-reset tail instead of
  going negative);
* windowed per-bucket histogram deltas, so a p99 objective judges the
  window's OWN observations, not the process lifetime;
* robust-zscore anomaly flags (median/MAD — one outlier cannot drag the
  baseline the way a mean/stddev score lets it).

All time flows through the ``utils/clock.py`` seam: under ``SimDriver``
the recorder runs on virtual time and a simulated week of history costs
tier-1 seconds; in ``ClientApp``/server it runs on the wall clock.
bkwlint BKW006 enforces the seam statically.

Series are keyed ``family{label=value,...}`` — the same flat spelling
the scenario scorecard uses — so a key is printable evidence as-is.
Beyond registry sampling, :meth:`SeriesRecorder.record` appends
synthetic points directly; the sim plane uses it to chart world-truth
numbers (``sim:*`` keys) that never transit the registry.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from .. import defaults
from ..utils import clock as clockmod
from . import journal as obs_journal
from . import metrics as obs_metrics

_C_SAMPLES = obs_metrics.counter(
    "bkw_series_samples_total", "Recorder sampling sweeps completed")
_G_POINTS = obs_metrics.gauge(
    "bkw_series_points", "Retained time-series points per family",
    ("family",))

#: MAD == 0 means the baseline is perfectly flat; any deviation is then
#: "infinitely" surprising — capped so rankings stay comparable/sortable.
_Z_CAP = 99.0


def flat_key(family: str, labels: Dict[str, str]) -> str:
    """``family{label=value,...}`` with labels sorted — the one spelling
    shared with the scorecard's counter_deltas keys."""
    if not labels:
        return family
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{family}{{{inner}}}"


def robust_zscore(values: Sequence[float]) -> float:
    """Robust z of the LAST value against the median/MAD of the rest.

    z = 0.6745 * (x - median) / MAD, the standard consistency-scaled
    form; a flat baseline (MAD 0) maps any deviation to ±``_Z_CAP`` so
    deterministic ranking survives the degenerate case."""
    if len(values) < 2:
        return 0.0
    base = sorted(values[:-1])
    n = len(base)
    med = (base[n // 2] if n % 2 else
           (base[n // 2 - 1] + base[n // 2]) / 2.0)
    devs = sorted(abs(v - med) for v in base)
    mad = (devs[n // 2] if n % 2 else
           (devs[n // 2 - 1] + devs[n // 2]) / 2.0)
    x = values[-1]
    if mad <= 0.0:
        if x == med:
            return 0.0
        return _Z_CAP if x > med else -_Z_CAP
    z = 0.6745 * (x - med) / mad
    return max(-_Z_CAP, min(_Z_CAP, z))


class SeriesRecorder:
    """Ring-buffered history for selected registry families.

    ``families`` maps family name -> retention override (None keeps
    ``defaults.SERIES_RETENTION_POINTS``).  A plain sequence of names is
    accepted too.  Counters/gauges store ``(t, float)`` points;
    histograms store ``(t, (cum_counts, sum, count))`` where
    ``cum_counts`` is the cumulative per-bucket tuple in bound order
    plus +Inf — exactly what a windowed quantile needs to difference.
    """

    def __init__(self, families, registry=None, clock=None,
                 retention: Optional[int] = None,
                 journal_samples: bool = False):
        if not isinstance(families, dict):
            families = {name: None for name in families}
        self.registry = registry or obs_metrics.registry()
        self.clock = clockmod.resolve(clock)
        self.retention = int(defaults.SERIES_RETENTION_POINTS
                             if retention is None else retention)
        self.journal_samples = bool(journal_samples)
        self._retention: Dict[str, int] = {
            name: int(cap) if cap else self.retention
            for name, cap in families.items()}
        #: key -> deque[(t, value)]
        self._points: Dict[str, deque] = {}
        #: key -> "counter" | "gauge" | "histogram" (manual keys: caller-set)
        self.kinds: Dict[str, str] = {}
        #: key -> owning family (manual keys: the key itself)
        self._family_of: Dict[str, str] = {}
        self.samples_taken = 0

    # --- writing -----------------------------------------------------------

    def _append(self, key: str, family: str, kind: str, t: float,
                value) -> None:
        dq = self._points.get(key)
        if dq is None:
            cap = self._retention.get(family, self.retention)
            dq = self._points[key] = deque(maxlen=cap)
            self.kinds[key] = kind
            self._family_of[key] = family
        dq.append((t, value))

    def record(self, key: str, value: float, t: Optional[float] = None,
               kind: str = "gauge") -> None:
        """Manual point append for synthetic series (the sim plane's
        world-truth numbers).  ``key`` doubles as the family."""
        t = self.clock.monotonic() if t is None else float(t)
        self._append(key, key, kind, t, float(value))

    def sample(self) -> int:
        """One sweep over the selected families; returns points added."""
        t = self.clock.monotonic()
        snap_points = 0
        per_family: Dict[str, int] = {}
        for family in self._retention:
            fam = self.registry.get(family)
            if fam is None:
                continue
            kind = fam.kind
            for series in fam._snapshot_series():
                key = flat_key(family, series.get("labels", {}))
                if kind == "histogram":
                    buckets = series["buckets"]
                    cum = tuple(buckets[b] for b in
                                sorted((k for k in buckets if k != "+Inf"),
                                       key=float)) + (buckets["+Inf"],)
                    value = (cum, float(series.get("sum", 0.0)),
                             int(series.get("count", 0)))
                else:
                    value = float(series.get("value", 0.0))
                self._append(key, family, kind, t, value)
                snap_points += 1
                per_family[family] = per_family.get(family, 0) + 1
        self.samples_taken += 1
        _C_SAMPLES.inc()
        for family in per_family:
            retained = sum(len(dq) for k, dq in self._points.items()
                           if self._family_of[k] == family)
            _G_POINTS.set(retained, family=family)
        if self.journal_samples and snap_points:
            obs_journal.emit("series_sample", t=round(t, 6),
                            points=snap_points,
                            families=len(per_family))
        return snap_points

    # --- reading -----------------------------------------------------------

    def keys(self) -> List[str]:
        return sorted(self._points)

    def family_keys(self, family: str,
                    labels: Optional[Dict[str, str]] = None) -> List[str]:
        """Keys of one family whose labels contain ``labels`` (subset
        match on the flat spelling; {} matches every series)."""
        need = [f"{k}={v}" for k, v in (labels or {}).items()]
        out = []
        for key in sorted(self._points):
            if self._family_of[key] != family:
                continue
            inner = key[len(family):].strip("{}")
            parts = inner.split(",") if inner else []
            if all(n in parts for n in need):
                out.append(key)
        return out

    def points(self, key: str,
               window_s: Optional[float] = None) -> List[tuple]:
        dq = self._points.get(key)
        if not dq:
            return []
        if window_s is None:
            return list(dq)
        cutoff = dq[-1][0] - float(window_s)
        return [p for p in dq if p[0] >= cutoff]

    def latest(self, key: str):
        dq = self._points.get(key)
        return dq[-1] if dq else None

    def _window_pair(self, key: str, window_s: float):
        pts = self.points(key, window_s)
        if len(pts) < 2:
            return None
        return pts[0], pts[-1]

    def delta(self, key: str, window_s: float) -> float:
        """Counter increase over the window, reset-safe: a decrease
        (process restart / registry reset) restarts the accrual from the
        post-reset floor instead of reporting a negative burn."""
        pts = self.points(key, window_s)
        if len(pts) < 2:
            return 0.0
        total, prev = 0.0, pts[0][1]
        for _t, v in pts[1:]:
            step = v - prev
            if step > 0:
                total += step
            elif step < 0:  # reset: accrue from the post-reset floor
                total += v
            prev = v
        return total

    def rate(self, key: str, window_s: float) -> float:
        pair = self._window_pair(key, window_s)
        if pair is None:
            return 0.0
        span = pair[1][0] - pair[0][0]
        if span <= 0:
            return 0.0
        return self.delta(key, window_s) / span

    def span(self, key: str, window_s: float) -> float:
        """Clock seconds the window's points actually cover (<= window_s
        while history is still filling)."""
        pair = self._window_pair(key, window_s)
        return 0.0 if pair is None else pair[1][0] - pair[0][0]

    def hist_window(self, key: str, window_s: float):
        """(bounds, per-bucket counts, count, sum) of the histogram's
        observations inside the window — the delta of the cumulative
        views at the window's edges.  None without two points."""
        pair = self._window_pair(key, window_s)
        if pair is None or self.kinds.get(key) != "histogram":
            return None
        (_t0, (cum0, sum0, n0)), (_t1, (cum1, sum1, n1)) = pair
        if n1 < n0 or len(cum0) != len(cum1):
            cum0, sum0, n0 = (0,) * len(cum1), 0.0, 0  # reset mid-window
        per = []
        prev = 0
        for a, b in zip(cum0, cum1):
            d = b - a
            per.append(max(0, d - prev))
            prev = d
        fam = self.registry.get(self._family_of[key])
        bounds = tuple(getattr(fam, "bounds", ()))
        return bounds, per, n1 - n0, sum1 - sum0

    def fraction_over(self, key: str, window_s: float,
                      threshold: float) -> Optional[float]:
        """Fraction of the window's histogram observations in buckets
        whose upper bound exceeds ``threshold`` — the bad-event fraction
        of a latency objective.  None when the window holds nothing."""
        win = self.hist_window(key, window_s)
        if win is None:
            return None
        bounds, per, count, _sum = win
        if count <= 0:
            return None
        over = sum(c for bound, c in zip(bounds, per[:-1])
                   if bound > threshold) + per[-1]
        return over / count

    # --- anomaly flags -----------------------------------------------------

    def anomalies(self, window_s: float,
                  min_points: Optional[int] = None,
                  z_threshold: Optional[float] = None) -> List[dict]:
        """Robust-zscore flags over every series' window.

        Counters score consecutive increments (a level shift in the
        *rate* is the anomaly, not the ever-growing total); gauges score
        raw values; histograms score per-interval observation counts.
        Deterministic: scores rounded, sorted by (-|z|, key).
        """
        min_points = int(defaults.SERIES_ANOMALY_MIN_POINTS
                         if min_points is None else min_points)
        z_threshold = float(defaults.SERIES_ANOMALY_Z
                            if z_threshold is None else z_threshold)
        out = []
        for key in sorted(self._points):
            pts = self.points(key, window_s)
            kind = self.kinds.get(key, "gauge")
            if kind == "histogram":
                values = [p[1][2] for p in pts]
            else:
                values = [p[1] for p in pts]
            if kind in ("counter", "histogram"):
                values = [max(0.0, b - a)
                          for a, b in zip(values, values[1:])]
            if len(values) < min_points:
                continue
            z = robust_zscore(values)
            if abs(z) < z_threshold:
                continue
            out.append({"key": key, "kind": kind,
                        "z": round(z, 4),
                        "last": round(float(values[-1]), 6)})
        out.sort(key=lambda a: (-abs(a["z"]), a["key"]))
        return out

    # --- background cadence ------------------------------------------------

    async def run(self, interval_s: Optional[float] = None,
                  on_sample=None) -> None:
        """Sample-then-sleep forever (cancel to stop).  ``on_sample``
        (optional, zero-arg — the SLO monitor's evaluate) rides the same
        cadence; its failures are contained like a sweep bug's."""
        interval = (defaults.SERIES_SAMPLE_INTERVAL_S
                    if interval_s is None else interval_s)
        while True:
            try:
                self.sample()
                if on_sample is not None:
                    on_sample()
            except Exception as e:  # a recorder bug must not kill the app
                obs_journal.emit("series_sample_error",
                                error=repr(e)[:200])
            await self.clock.sleep(interval)
