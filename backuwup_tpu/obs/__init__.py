"""Unified observability plane: metrics, correlated traces, event journal.

The single source of truth for runtime signals (SURVEY §5.5; the
reference prints ad-hoc lines and keeps no machine-readable telemetry):

* :mod:`~backuwup_tpu.obs.metrics` — a thread-safe process-wide registry
  of labeled Counters, Gauges, and log-bucketed Histograms with
  Prometheus text exposition and a JSON snapshot API;
* :mod:`~backuwup_tpu.obs.trace` — hierarchical spans with Dapper-style
  trace/span ids, propagated across the wire (p2p ``EncapsulatedMsg``
  and client<->server JSON messages) so one backup's
  pack -> seal -> transfer -> ack -> audit chain is joinable across
  processes; subsumes :mod:`backuwup_tpu.utils.tracing` (kept as thin
  wrappers);
* :mod:`~backuwup_tpu.obs.journal` — a size-rotated append-only JSONL
  journal of status events, span closes, retry firings, and fault-plane
  injections, with a panic handler that dumps the metrics snapshot plus
  the last N journal lines;
* :mod:`~backuwup_tpu.obs.invariants` — the durability invariant
  monitor: sweeps the verifier-side placement/audit state into live
  ``bkw_durability_*`` facts (clean survivors per stripe, repair debt,
  unrestorable packfiles) that /healthz and the scenario scorecard
  consume;
* :mod:`~backuwup_tpu.obs.expo` — ``GET /metrics`` + ``GET /healthz``
  exposition shared by the coordination server and the opt-in client
  status port;
* :mod:`~backuwup_tpu.obs.profile` — the performance half (GWP,
  PAPERS.md): per-stage device dispatch accounting
  (``bkw_device_dispatch_total``), padded-vs-actual byte efficiency,
  the honest chained-execution stage timer, and the per-backup
  pipeline report;
* :mod:`~backuwup_tpu.obs.timeline` — journals + spans exported as
  Chrome trace-event JSON (Perfetto), merging multiple clients'
  journals into one cross-process timeline keyed by trace id.

Import-light by design: this package depends only on the stdlib and
:mod:`backuwup_tpu.defaults` (``expo`` additionally on aiohttp), never
on jax or any accelerator runtime, so every layer can instrument itself
without import cycles or device initialization.
"""

from . import invariants, journal, metrics, profile, timeline, trace

__all__ = ["invariants", "journal", "metrics", "profile", "timeline",
           "trace"]
