"""Local client state store: identity, backup config, peer ledger, event log.

Re-designs the reference's SQLite config layer (``client/src/config/mod.rs``,
``identity.rs``, ``backup.rs``, ``peers.rs``, ``log.rs``) on the stdlib
``sqlite3`` module.  Same responsibilities:

* ``config`` table — typed KV: root secret, auth token, obfuscation key,
  initialized flag, backup path, highest-sent-index watermark
  (``config/identity.rs:85-180``, ``config/backup.rs:32-98``).
* ``peers`` table — storage-accounting ledger per peer:
  transmitted/received/negotiated byte counters, first/last seen
  (``config/peers.rs:12-19``); ``find_peers_with_storage`` orders by free
  space like ``peers.rs:176-193``.
* ``log`` table — append-only event log doubling as restore rate-limiter and
  backup size-estimator source (``config/log.rs:83-160``).

Directory resolution honors ``CONFIG_DIR`` / ``DATA_DIR`` env vars — the
test seam the reference uses to run N clients on one machine
(``config/mod.rs:90-103``, SURVEY.md §4).
"""

from __future__ import annotations

import json
import math
import os
import sqlite3
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from . import defaults

_SCHEMA = """
CREATE TABLE IF NOT EXISTS config (
    key TEXT PRIMARY KEY,
    value BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS peers (
    pubkey BLOB PRIMARY KEY,
    bytes_transmitted INTEGER NOT NULL DEFAULT 0,
    bytes_received INTEGER NOT NULL DEFAULT 0,
    bytes_negotiated INTEGER NOT NULL DEFAULT 0,
    first_seen REAL NOT NULL,
    last_seen REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS log (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    timestamp REAL NOT NULL,
    event TEXT NOT NULL,
    data TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS placements (
    packfile_id BLOB NOT NULL,
    peer BLOB NOT NULL,
    size INTEGER NOT NULL,
    sent_at REAL NOT NULL,
    shard_index INTEGER NOT NULL DEFAULT -1,
    PRIMARY KEY (packfile_id, peer)
);
CREATE TABLE IF NOT EXISTS audit_ledger (
    peer BLOB PRIMARY KEY,
    passes INTEGER NOT NULL DEFAULT 0,
    failures INTEGER NOT NULL DEFAULT 0,
    misses INTEGER NOT NULL DEFAULT 0,
    consecutive_failures INTEGER NOT NULL DEFAULT 0,
    consecutive_misses INTEGER NOT NULL DEFAULT 0,
    demoted INTEGER NOT NULL DEFAULT 0,
    last_result TEXT NOT NULL DEFAULT '',
    last_audit REAL NOT NULL DEFAULT 0,
    next_due REAL NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS peer_stats (
    peer BLOB PRIMARY KEY,
    throughput_bps REAL NOT NULL DEFAULT 0,
    latency_s REAL NOT NULL DEFAULT 0,
    success REAL NOT NULL DEFAULT 1,
    samples INTEGER NOT NULL DEFAULT 0,
    updated REAL NOT NULL DEFAULT 0,
    placement_demoted INTEGER NOT NULL DEFAULT 0,
    placement_demoted_at REAL NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS snapshots (
    hash BLOB PRIMARY KEY,
    parent BLOB,
    created_at REAL NOT NULL,
    size INTEGER NOT NULL DEFAULT 0,
    pruned_at REAL NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS snapshot_blobs (
    snapshot_hash BLOB NOT NULL,
    blob_hash BLOB NOT NULL,
    size INTEGER NOT NULL,
    PRIMARY KEY (snapshot_hash, blob_hash)
);
CREATE TABLE IF NOT EXISTS reclaim_backlog (
    file_id BLOB NOT NULL,
    peer BLOB NOT NULL,
    kind INTEGER NOT NULL,
    size INTEGER NOT NULL DEFAULT 0,
    queued_at REAL NOT NULL,
    PRIMARY KEY (file_id, peer)
);
"""

EVENT_BACKUP = "backup"
EVENT_RESTORE_REQUEST = "restore_request"
EVENT_REPAIR = "repair"
EVENT_GC = "gc"


def config_dir() -> Path:
    d = os.environ.get("CONFIG_DIR")
    return Path(d) if d else Path.home() / ".backuwup" / "config"


def data_dir() -> Path:
    d = os.environ.get("DATA_DIR")
    return Path(d) if d else Path.home() / ".backuwup" / "data"


@dataclass(frozen=True)
class AuditState:
    """One peer's row in the audit ledger (no reference equivalent)."""

    peer: bytes
    passes: int = 0
    failures: int = 0
    misses: int = 0
    consecutive_failures: int = 0
    consecutive_misses: int = 0
    demoted: bool = False
    last_result: str = ""
    last_audit: float = 0.0
    next_due: float = 0.0


@dataclass(frozen=True)
class PeerStatsRow:
    """One peer's persisted transfer estimators (net/peer_stats.py; no
    reference equivalent).  EWMA state, not raw telemetry — the
    histograms live in the metrics registry and reset with the process;
    this row is what survives a client restart."""

    peer: bytes
    throughput_bps: float = 0.0
    latency_s: float = 0.0
    success: float = 1.0
    samples: int = 0
    updated: float = 0.0
    #: placement demotion — distinct from audit demotion (audit_ledger):
    #: the peer is measured too slow/flaky to receive NEW placements, but
    #: its held data still counts and it recovers after probation or a
    #: run of successes.
    placement_demoted: bool = False
    placement_demoted_at: float = 0.0


@dataclass(frozen=True)
class SnapshotRow:
    """One snapshot's lineage row (docs/lifecycle.md; no reference
    equivalent).  ``pruned_at`` > 0 means retention marked it dead —
    pruning never touches data, only this flag; reclaiming the bytes is
    GC's job."""

    hash: bytes
    parent: Optional[bytes]
    created_at: float
    size: int = 0
    pruned_at: float = 0.0

    @property
    def retained(self) -> bool:
        return self.pruned_at == 0.0


@dataclass(frozen=True)
class PeerInfo:
    """config/peers.rs:12-19."""

    pubkey: bytes
    bytes_transmitted: int
    bytes_received: int
    bytes_negotiated: int
    first_seen: float
    last_seen: float

    @property
    def free_storage(self) -> int:
        return max(0, self.bytes_negotiated - self.bytes_transmitted)


class Store:
    """One client's persistent local state."""

    def __init__(self, directory: Optional[Path] = None,
                 data_base: Optional[Path] = None):
        self.dir = Path(directory) if directory else config_dir()
        self.dir.mkdir(parents=True, exist_ok=True)
        # data dir is per-store so N clients can share a process (the
        # reference separates clients per-process via DATA_DIR; this is the
        # in-process generalization of that seam)
        self.data_base = Path(data_base) if data_base else data_dir()
        self._lock = threading.RLock()
        self._db = sqlite3.connect(self.dir / "config.db",
                                   check_same_thread=False)
        # same crash discipline as the server DB (net/server.py): WAL keeps
        # a mid-transaction process death from corrupting placements/peer
        # state; NORMAL syncs the WAL at checkpoint, plenty for a client
        # whose DB can be re-derived from the server plus its own disk
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.executescript(_SCHEMA)
        # erasure-era column on pre-existing databases; -1 = whole packfile
        # (the CREATE above already carries it for fresh stores)
        try:
            self._db.execute(
                "ALTER TABLE placements ADD COLUMN"
                " shard_index INTEGER NOT NULL DEFAULT -1")
        except sqlite3.OperationalError:
            pass  # already present
        # WAN-era placement-demotion columns on pre-existing databases
        for clause in ("placement_demoted INTEGER NOT NULL DEFAULT 0",
                       "placement_demoted_at REAL NOT NULL DEFAULT 0"):
            try:
                self._db.execute(
                    f"ALTER TABLE peer_stats ADD COLUMN {clause}")
            except sqlite3.OperationalError:
                pass  # already present
        self._db.commit()

    def close(self) -> None:
        self._db.close()

    # --- generic KV -------------------------------------------------------

    def _get(self, key: str) -> Optional[bytes]:
        with self._lock:
            row = self._db.execute(
                "SELECT value FROM config WHERE key = ?", (key,)).fetchone()
        return None if row is None else bytes(row[0])

    def _set(self, key: str, value: Optional[bytes]) -> None:
        with self._lock:
            if value is None:
                self._db.execute("DELETE FROM config WHERE key = ?", (key,))
            else:
                self._db.execute(
                    "INSERT INTO config (key, value) VALUES (?, ?) "
                    "ON CONFLICT(key) DO UPDATE SET value = excluded.value",
                    (key, bytes(value)))
            self._db.commit()

    # --- identity (config/identity.rs:85-180) -----------------------------

    def get_root_secret(self) -> Optional[bytes]:
        return self._get("root_secret")

    def set_root_secret(self, secret: bytes) -> None:
        self._set("root_secret", secret)

    def get_auth_token(self) -> Optional[bytes]:
        return self._get("auth_token")

    def set_auth_token(self, token: Optional[bytes]) -> None:
        self._set("auth_token", token)

    def get_obfuscation_key(self) -> Optional[bytes]:
        return self._get("obfuscation_key")

    def set_obfuscation_key(self, key: bytes) -> None:
        if len(key) != 4:
            raise ValueError("obfuscation key must be 4 bytes")
        self._set("obfuscation_key", key)

    def is_initialized(self) -> bool:
        return self._get("initialized") == b"1"

    def set_initialized(self) -> None:
        self._set("initialized", b"1")

    # --- backup config (config/backup.rs) ---------------------------------

    def get_backup_path(self) -> Optional[str]:
        v = self._get("backup_path")
        return None if v is None else v.decode()

    def set_backup_path(self, path: str) -> None:
        self._set("backup_path", path.encode())

    def get_highest_sent_index(self) -> int:
        """Resume-safe index watermark (config/backup.rs:80-98)."""
        v = self._get("highest_sent_index")
        return -1 if v is None else int(v)

    def set_highest_sent_index(self, idx: int) -> None:
        # Monotonic: the watermark means "every index file <= idx was acked",
        # so it must never move backwards (a regression would re-send files
        # the peer's writer refuses to overwrite).
        self._set("highest_sent_index",
                  str(max(int(idx), self.get_highest_sent_index())).encode())

    def packfile_dir(self) -> Path:
        d = self.data_base / "packfiles"
        d.mkdir(parents=True, exist_ok=True)
        return d

    def index_dir(self) -> Path:
        d = self.data_base / "index"
        d.mkdir(parents=True, exist_ok=True)
        return d

    def dedup_cold_dir(self) -> Path:
        """Cold-tier fingerprint runs (dedupstore.ColdFingerprintStore)."""
        d = self.data_base / "dedup_cold"
        d.mkdir(parents=True, exist_ok=True)
        return d

    def received_dir(self, peer_id: bytes) -> Path:
        d = self.data_base / "received_packfiles" / bytes(peer_id).hex()
        d.mkdir(parents=True, exist_ok=True)
        return d

    def restore_dir(self) -> Path:
        d = self.data_base / "restore_packfiles"
        d.mkdir(parents=True, exist_ok=True)
        return d

    def challenge_dir(self) -> Path:
        """Encrypted per-packfile audit challenge tables (docs/audit.md)."""
        d = self.data_base / "challenges"
        d.mkdir(parents=True, exist_ok=True)
        return d

    # --- peers ledger (config/peers.rs) ------------------------------------

    def add_peer_negotiated(self, pubkey: bytes, amount: int,
                            now: Optional[float] = None) -> None:
        """Upsert-increment negotiated storage (peers.rs:110-123)."""
        self._bump_peer(pubkey, "bytes_negotiated", amount, now)

    def add_peer_transmitted(self, pubkey: bytes, amount: int) -> None:
        self._bump_peer(pubkey, "bytes_transmitted", amount)

    def add_peer_received(self, pubkey: bytes, amount: int) -> None:
        self._bump_peer(pubkey, "bytes_received", amount)

    def credit_peer_transmitted(self, pubkey: bytes, amount: int) -> None:
        """Clamped decrement after a holder acks a RECLAIM: the freed
        bytes count against ``bytes_transmitted`` again as free storage.
        Clamped at zero — a double-delivered ack must not mint quota."""
        self._credit_peer(pubkey, "bytes_transmitted", amount)

    def credit_peer_received(self, pubkey: bytes, amount: int) -> None:
        """Holder-side quota credit when serving a RECLAIM: the deleted
        packfiles stop counting against the requester's received quota."""
        self._credit_peer(pubkey, "bytes_received", amount)

    def _credit_peer(self, pubkey: bytes, column: str, amount: int) -> None:
        with self._lock:
            self._db.execute(
                f"UPDATE peers SET {column} = MAX(0, {column} - ?),"
                " last_seen = ? WHERE pubkey = ?",
                (int(amount), time.time(), bytes(pubkey)))
            self._db.commit()

    def _bump_peer(self, pubkey: bytes, column: str, amount: int,
                   now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            cur = self._db.execute(
                f"UPDATE peers SET {column} = {column} + ?, last_seen = ?"
                " WHERE pubkey = ?", (int(amount), now, bytes(pubkey)))
            if cur.rowcount == 0:
                self._db.execute(
                    f"INSERT INTO peers (pubkey, {column}, first_seen, last_seen)"
                    " VALUES (?, ?, ?, ?)",
                    (bytes(pubkey), int(amount), now, now))
            self._db.commit()

    def get_peer(self, pubkey: bytes) -> Optional[PeerInfo]:
        with self._lock:
            row = self._db.execute(
                "SELECT pubkey, bytes_transmitted, bytes_received,"
                " bytes_negotiated, first_seen, last_seen FROM peers"
                " WHERE pubkey = ?", (bytes(pubkey),)).fetchone()
        return None if row is None else PeerInfo(bytes(row[0]), *row[1:])

    def list_peers(self) -> list:
        with self._lock:
            rows = self._db.execute(
                "SELECT pubkey, bytes_transmitted, bytes_received,"
                " bytes_negotiated, first_seen, last_seen FROM peers").fetchall()
        return [PeerInfo(bytes(r[0]), *r[1:]) for r in rows]

    def find_peers_with_storage(self, exclude=()) -> list:
        """Peers ordered by measured capacity (throughput × success from
        the persisted EWMA estimators), free storage as tiebreak — bytes
        go where they are most likely to land fast (peers.rs:176-193
        ordered by free space alone).  Two exclusion sets apply: peers the
        audit ledger demoted (proven to drop data — never again) and
        placement-demoted peers (measured too slow/flaky — sit out until
        probation expires or successes recover them).  ``exclude`` adds
        caller-side exclusions (the repair round must not re-place data on
        the very peers it is repairing away from).
        """
        avoid = (self.demoted_peers() | self.placement_demoted_peers()
                 | {bytes(p) for p in exclude})
        peers = [p for p in self.list_peers()
                 if p.free_storage > 0 and p.pubkey not in avoid]
        stats = {s.peer: s for s in self.all_peer_stats()}

        def bucket(p: "PeerInfo") -> int:
            # log2 buckets keep the ordering deterministic under EWMA
            # jitter: a 2x capacity gap reorders, a 3% one does not.
            # Unmeasured peers score a neutral floor so newcomers are
            # neither starved nor preferred over proven-fast peers.
            st = stats.get(p.pubkey)
            if st is None or st.samples < defaults.PLACEMENT_MIN_SAMPLES:
                score = float(defaults.PLACEMENT_NEUTRAL_SCORE_BPS)
            else:
                score = st.throughput_bps * max(st.success, 0.0)
            return int(math.log2(max(score, 1.0)))

        # deterministic: capacity bucket desc, free space desc, pubkey —
        # shard placement must be reproducible under the seeded fault plane
        peers.sort(key=lambda p: (-bucket(p), -p.free_storage, p.pubkey))
        return peers

    # --- packfile placements (verifier's who-holds-what map) ----------------

    def record_placement(self, packfile_id: bytes, peer: bytes, size: int,
                         now: Optional[float] = None,
                         shard_index: int = -1) -> None:
        """``shard_index`` -1 = the peer holds the whole packfile; >= 0 =
        it holds that one erasure shard of the stripe.  The (packfile_id,
        peer) key enforces one shard per peer per stripe."""
        now = time.time() if now is None else now
        with self._lock:
            self._db.execute(
                "INSERT INTO placements"
                " (packfile_id, peer, size, sent_at, shard_index)"
                " VALUES (?, ?, ?, ?, ?)"
                " ON CONFLICT(packfile_id, peer) DO NOTHING",
                (bytes(packfile_id), bytes(peer), int(size), now,
                 int(shard_index)))
            self._db.commit()

    def placements_for_peer(self, peer: bytes) -> list:
        """[(packfile_id, size)] held by ``peer``, oldest placement first."""
        with self._lock:
            rows = self._db.execute(
                "SELECT packfile_id, size FROM placements WHERE peer = ?"
                " ORDER BY sent_at", (bytes(peer),)).fetchall()
        return [(bytes(r[0]), int(r[1])) for r in rows]

    def shard_placements_for_peer(self, peer: bytes) -> list:
        """[(packfile_id, size, shard_index)] held by ``peer``, oldest
        first; shard_index -1 means the whole packfile."""
        with self._lock:
            rows = self._db.execute(
                "SELECT packfile_id, size, shard_index FROM placements"
                " WHERE peer = ? ORDER BY sent_at",
                (bytes(peer),)).fetchall()
        return [(bytes(r[0]), int(r[1]), int(r[2])) for r in rows]

    def shards_for_packfile(self, packfile_id: bytes) -> list:
        """[(peer, shard_index)] across the stripe (or [(peer, -1)] rows
        for whole-packfile replication)."""
        with self._lock:
            rows = self._db.execute(
                "SELECT peer, shard_index FROM placements"
                " WHERE packfile_id = ?", (bytes(packfile_id),)).fetchall()
        return [(bytes(r[0]), int(r[1])) for r in rows]

    def placements_for_packfile(self, packfile_id: bytes) -> list:
        """[(peer, size, shard_index)] — GC's retire/reclaim walk needs
        the per-row byte sizes alongside the stripe geometry."""
        with self._lock:
            rows = self._db.execute(
                "SELECT peer, size, shard_index FROM placements"
                " WHERE packfile_id = ?", (bytes(packfile_id),)).fetchall()
        return [(bytes(r[0]), int(r[1]), int(r[2])) for r in rows]

    def retire_placement(self, packfile_id: bytes, peer: bytes) -> int:
        """Drop one (packfile, peer) placement row — sourceless shard
        repair retires exactly the lost shard rows it re-homed."""
        with self._lock:
            cur = self._db.execute(
                "DELETE FROM placements WHERE packfile_id = ? AND peer = ?",
                (bytes(packfile_id), bytes(peer)))
            self._db.commit()
        return cur.rowcount

    def all_placements(self) -> list:
        """Every placement row as ``(packfile_id, peer, size,
        shard_index, sent_at)`` — the invariant monitor's one-query
        sweep over the who-holds-what map (obs/invariants.py)."""
        with self._lock:
            rows = self._db.execute(
                "SELECT packfile_id, peer, size, shard_index, sent_at"
                " FROM placements").fetchall()
        return [(bytes(r[0]), bytes(r[1]), int(r[2]), int(r[3]),
                 float(r[4])) for r in rows]

    def peers_with_placements(self) -> list:
        with self._lock:
            rows = self._db.execute(
                "SELECT DISTINCT peer FROM placements").fetchall()
        return [bytes(r[0]) for r in rows]

    def peers_for_packfile(self, packfile_id: bytes) -> list:
        """Every peer recorded as holding ``packfile_id`` — a packfile is
        orphaned only when ALL of its placements are on lost peers."""
        with self._lock:
            rows = self._db.execute(
                "SELECT peer FROM placements WHERE packfile_id = ?",
                (bytes(packfile_id),)).fetchall()
        return [bytes(r[0]) for r in rows]

    def retire_placements(self, peer: bytes) -> int:
        """Drop every placement row for a lost peer once repair has
        re-homed (or written off) its packfiles; returns rows removed."""
        with self._lock:
            cur = self._db.execute(
                "DELETE FROM placements WHERE peer = ?", (bytes(peer),))
            self._db.commit()
        return cur.rowcount

    # --- snapshot lineage + retention (docs/lifecycle.md) -------------------

    def record_snapshot(self, snapshot_hash: bytes, parent: Optional[bytes],
                        size: int, blobs, now: Optional[float] = None) -> None:
        """One transaction commits the lineage row AND its blob manifest
        (``blobs`` iterates (blob_hash, size) for every blob the snapshot
        references, duplicates included) — GC's mark phase is a local
        join against these manifests, so a snapshot must never exist
        without one (that is the legacy-store guard's trigger)."""
        now = time.time() if now is None else now
        with self._lock:
            self._db.execute(
                "INSERT INTO snapshots (hash, parent, created_at, size)"
                " VALUES (?, ?, ?, ?) ON CONFLICT(hash) DO UPDATE SET"
                " pruned_at = 0",
                (bytes(snapshot_hash),
                 None if parent is None else bytes(parent),
                 now, int(size)))
            self._db.executemany(
                "INSERT INTO snapshot_blobs (snapshot_hash, blob_hash, size)"
                " VALUES (?, ?, ?)"
                " ON CONFLICT(snapshot_hash, blob_hash) DO NOTHING",
                [(bytes(snapshot_hash), bytes(h), int(s))
                 for h, s in blobs])
            self._db.commit()

    def get_snapshot(self, snapshot_hash: bytes) -> Optional["SnapshotRow"]:
        with self._lock:
            row = self._db.execute(
                "SELECT hash, parent, created_at, size, pruned_at"
                " FROM snapshots WHERE hash = ?",
                (bytes(snapshot_hash),)).fetchone()
        if row is None:
            return None
        return SnapshotRow(bytes(row[0]),
                           None if row[1] is None else bytes(row[1]),
                           float(row[2]), int(row[3]), float(row[4]))

    def list_snapshots(self) -> list:
        """Every lineage row (pruned included), oldest first."""
        with self._lock:
            rows = self._db.execute(
                "SELECT hash, parent, created_at, size, pruned_at"
                " FROM snapshots ORDER BY created_at, hash").fetchall()
        return [SnapshotRow(bytes(r[0]),
                            None if r[1] is None else bytes(r[1]),
                            float(r[2]), int(r[3]), float(r[4]))
                for r in rows]

    def retained_snapshots(self) -> list:
        return [s for s in self.list_snapshots() if s.retained]

    def latest_snapshot(self) -> Optional["SnapshotRow"]:
        """Most recent RETAINED snapshot — the parent link for the next
        backup and the one snapshot retention may never prune."""
        retained = self.retained_snapshots()
        return retained[-1] if retained else None

    def prune_snapshots(self, hashes, now: Optional[float] = None) -> int:
        """Mark snapshots dead.  Never touches data — the blobs stay
        until GC proves nothing retained references them."""
        now = time.time() if now is None else now
        with self._lock:
            cur = self._db.executemany(
                "UPDATE snapshots SET pruned_at = ?"
                " WHERE hash = ? AND pruned_at = 0",
                [(now, bytes(h)) for h in hashes])
            self._db.commit()
        return cur.rowcount

    def get_retention_policy(self) -> Optional[str]:
        v = self._get("retention_policy")
        return None if v is None else v.decode()

    def set_retention_policy(self, policy: Optional[str]) -> None:
        self._set("retention_policy",
                  None if policy is None else policy.encode())

    def apply_retention(self, policy: Optional[str] = None,
                        now: Optional[float] = None) -> list:
        """Compute and mark the prune set under the named policy
        (comma-separated ``keep-last:N`` / ``keep-daily:N`` rules; a
        snapshot kept by ANY rule is retained).  The newest retained
        snapshot is always kept regardless of policy — retention must
        never walk the store back past the latest restorable state.
        Returns the pruned hashes."""
        policy = self.get_retention_policy() if policy is None else policy
        if not policy or policy.strip() == "keep-all":
            return []
        snaps = self.retained_snapshots()
        snaps.reverse()  # newest first
        if not snaps:
            return []
        keep = {snaps[0].hash}
        for rule in policy.split(","):
            rule = rule.strip()
            if not rule:
                continue
            name, _, arg = rule.partition(":")
            try:
                n = int(arg)
            except ValueError:
                raise ValueError(f"bad retention rule {rule!r}")
            if name == "keep-last":
                keep.update(s.hash for s in snaps[:n])
            elif name == "keep-daily":
                # newest snapshot per UTC day, for the N newest days
                days: dict = {}
                for s in snaps:
                    days.setdefault(int(s.created_at // 86400), s.hash)
                for day in sorted(days, reverse=True)[:n]:
                    keep.add(days[day])
            else:
                raise ValueError(f"unknown retention rule {rule!r}")
        prune = [s.hash for s in snaps if s.hash not in keep]
        if prune:
            self.prune_snapshots(prune, now=now)
        return prune

    def live_blobs(self) -> dict:
        """blob_hash -> size over every blob some RETAINED snapshot's
        manifest references — GC's mark phase in one query."""
        with self._lock:
            rows = self._db.execute(
                "SELECT sb.blob_hash, MAX(sb.size) FROM snapshot_blobs sb"
                " JOIN snapshots s ON s.hash = sb.snapshot_hash"
                " WHERE s.pruned_at = 0 GROUP BY sb.blob_hash").fetchall()
        return {bytes(r[0]): int(r[1]) for r in rows}

    def manifest_blobs(self) -> dict:
        """blob_hash -> size over EVERY manifest row, pruned snapshots
        included — GC's occupancy denominator (a packfile's total known
        payload, live or dead)."""
        with self._lock:
            rows = self._db.execute(
                "SELECT blob_hash, MAX(size) FROM snapshot_blobs"
                " GROUP BY blob_hash").fetchall()
        return {bytes(r[0]): int(r[1]) for r in rows}

    def snapshots_without_manifest(self) -> list:
        """Retained snapshots with NO manifest rows — pre-lifecycle
        backups GC cannot reason about, so it must refuse to collect."""
        with self._lock:
            rows = self._db.execute(
                "SELECT s.hash FROM snapshots s WHERE s.pruned_at = 0"
                " AND NOT EXISTS (SELECT 1 FROM snapshot_blobs sb"
                " WHERE sb.snapshot_hash = s.hash)").fetchall()
        return [bytes(r[0]) for r in rows]

    def drop_pruned_manifests(self) -> int:
        """Delete manifest rows belonging to pruned snapshots (the
        lineage tombstone row itself stays); returns rows dropped."""
        with self._lock:
            cur = self._db.execute(
                "DELETE FROM snapshot_blobs WHERE snapshot_hash IN"
                " (SELECT hash FROM snapshots WHERE pruned_at > 0)")
            self._db.commit()
        return cur.rowcount

    # --- GC run state (crash roll-forward; docs/lifecycle.md) ---------------

    def get_gc_state(self) -> Optional[dict]:
        v = self._get("gc_state")
        return None if v is None else json.loads(v)

    def set_gc_state(self, state: Optional[dict]) -> None:
        self._set("gc_state",
                  None if state is None
                  else json.dumps(state, sort_keys=True).encode())

    # --- reclaim backlog (make-before-break's best-effort tail) -------------

    def queue_reclaim(self, file_id: bytes, peer: bytes, kind: int,
                      size: int, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            self._db.execute(
                "INSERT INTO reclaim_backlog"
                " (file_id, peer, kind, size, queued_at)"
                " VALUES (?, ?, ?, ?, ?)"
                " ON CONFLICT(file_id, peer) DO NOTHING",
                (bytes(file_id), bytes(peer), int(kind), int(size), now))
            self._db.commit()

    def reclaim_backlog(self) -> list:
        """[(file_id, peer, kind, size)], oldest queued first."""
        with self._lock:
            rows = self._db.execute(
                "SELECT file_id, peer, kind, size FROM reclaim_backlog"
                " ORDER BY queued_at, file_id").fetchall()
        return [(bytes(r[0]), bytes(r[1]), int(r[2]), int(r[3]))
                for r in rows]

    def clear_reclaim(self, file_id: bytes, peer: bytes) -> int:
        with self._lock:
            cur = self._db.execute(
                "DELETE FROM reclaim_backlog"
                " WHERE file_id = ? AND peer = ?",
                (bytes(file_id), bytes(peer)))
            self._db.commit()
        return cur.rowcount

    # --- audit ledger (docs/audit.md; no reference equivalent) --------------

    def get_audit_state(self, peer: bytes) -> "AuditState":
        with self._lock:
            row = self._db.execute(
                "SELECT peer, passes, failures, misses, consecutive_failures,"
                " consecutive_misses, demoted, last_result, last_audit,"
                " next_due FROM audit_ledger WHERE peer = ?",
                (bytes(peer),)).fetchone()
        if row is None:
            return AuditState(peer=bytes(peer))
        return AuditState(bytes(row[0]), *row[1:6], bool(row[6]), *row[7:])

    def put_audit_state(self, state: "AuditState") -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO audit_ledger (peer, passes, failures, misses,"
                " consecutive_failures, consecutive_misses, demoted,"
                " last_result, last_audit, next_due)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)"
                " ON CONFLICT(peer) DO UPDATE SET"
                " passes = excluded.passes, failures = excluded.failures,"
                " misses = excluded.misses,"
                " consecutive_failures = excluded.consecutive_failures,"
                " consecutive_misses = excluded.consecutive_misses,"
                " demoted = excluded.demoted,"
                " last_result = excluded.last_result,"
                " last_audit = excluded.last_audit,"
                " next_due = excluded.next_due",
                (state.peer, state.passes, state.failures, state.misses,
                 state.consecutive_failures, state.consecutive_misses,
                 int(state.demoted), state.last_result, state.last_audit,
                 state.next_due))
            self._db.commit()

    def demoted_peers(self) -> set:
        with self._lock:
            rows = self._db.execute(
                "SELECT peer FROM audit_ledger WHERE demoted = 1").fetchall()
        return {bytes(r[0]) for r in rows}

    def audit_due_peers(self, now: Optional[float] = None) -> list:
        """Peers holding placements whose next audit is due (next_due <=
        now), never-audited peers (no ledger row) first."""
        now = time.time() if now is None else now
        due = []
        for peer in self.peers_with_placements():
            st = self.get_audit_state(peer)
            if st.next_due <= now:
                due.append((st.next_due, peer))
        due.sort(key=lambda t: t[0])
        return [p for _, p in due]

    def mark_audit_due(self, peer: bytes,
                       now: Optional[float] = None) -> None:
        """Pull a peer's next audit forward to *now* (AuditDue push)."""
        now = time.time() if now is None else now
        st = self.get_audit_state(peer)
        if st.next_due > now:
            self.put_audit_state(
                AuditState(st.peer, st.passes, st.failures, st.misses,
                           st.consecutive_failures, st.consecutive_misses,
                           st.demoted, st.last_result, st.last_audit, now))

    # --- per-peer transfer estimators (net/peer_stats.py) -------------------

    def get_peer_stats(self, peer: bytes) -> Optional["PeerStatsRow"]:
        with self._lock:
            row = self._db.execute(
                "SELECT peer, throughput_bps, latency_s, success, samples,"
                " updated, placement_demoted, placement_demoted_at"
                " FROM peer_stats WHERE peer = ?",
                (bytes(peer),)).fetchone()
        if row is None:
            return None
        return PeerStatsRow(bytes(row[0]), *row[1:6], bool(row[6]), row[7])

    def put_peer_stats(self, row: "PeerStatsRow") -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO peer_stats (peer, throughput_bps, latency_s,"
                " success, samples, updated) VALUES (?, ?, ?, ?, ?, ?)"
                " ON CONFLICT(peer) DO UPDATE SET"
                " throughput_bps = excluded.throughput_bps,"
                " latency_s = excluded.latency_s,"
                " success = excluded.success,"
                " samples = excluded.samples,"
                " updated = excluded.updated",
                (bytes(row.peer), float(row.throughput_bps),
                 float(row.latency_s), float(row.success),
                 int(row.samples), float(row.updated)))
            self._db.commit()

    def all_peer_stats(self) -> list:
        with self._lock:
            rows = self._db.execute(
                "SELECT peer, throughput_bps, latency_s, success, samples,"
                " updated, placement_demoted, placement_demoted_at"
                " FROM peer_stats").fetchall()
        return [PeerStatsRow(bytes(r[0]), *r[1:6], bool(r[6]), r[7])
                for r in rows]

    def set_placement_demoted(self, peer: bytes, demoted: bool,
                              now: Optional[float] = None) -> None:
        """Flip a peer's placement-demotion flag (distinct from the audit
        ledger's demotion: this one is about measured capacity, not proven
        data loss, and is recoverable)."""
        now = time.time() if now is None else now
        with self._lock:
            cur = self._db.execute(
                "UPDATE peer_stats SET placement_demoted = ?,"
                " placement_demoted_at = ? WHERE peer = ?",
                (int(demoted), now if demoted else 0.0, bytes(peer)))
            if cur.rowcount == 0:
                self._db.execute(
                    "INSERT INTO peer_stats (peer, placement_demoted,"
                    " placement_demoted_at, updated) VALUES (?, ?, ?, ?)",
                    (bytes(peer), int(demoted),
                     now if demoted else 0.0, now))
            self._db.commit()

    def placement_demoted_peers(self, now: Optional[float] = None) -> set:
        """Peers currently placement-demoted.  Probation is lazy: a row
        demoted longer than ``PLACEMENT_PROBATION_S`` ago is cleared here
        — the peer gets another chance to prove itself."""
        now = time.time() if now is None else now
        cutoff = now - defaults.PLACEMENT_PROBATION_S
        with self._lock:
            self._db.execute(
                "UPDATE peer_stats SET placement_demoted = 0,"
                " placement_demoted_at = 0 WHERE placement_demoted = 1"
                " AND placement_demoted_at <= ?", (cutoff,))
            self._db.commit()
            rows = self._db.execute(
                "SELECT peer FROM peer_stats"
                " WHERE placement_demoted = 1").fetchall()
        return {bytes(r[0]) for r in rows}

    # --- audit challenge cursor (single-use table entries) ------------------

    def get_audit_cursor(self, packfile_id: bytes) -> int:
        v = self._get(f"audit_cursor:{bytes(packfile_id).hex()}")
        return 0 if v is None else int(v)

    def set_audit_cursor(self, packfile_id: bytes, value: int) -> None:
        self._set(f"audit_cursor:{bytes(packfile_id).hex()}",
                  str(int(value)).encode())

    # --- event log (config/log.rs) -----------------------------------------

    def add_event(self, event: str, data: dict,
                  now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        with self._lock:
            self._db.execute(
                "INSERT INTO log (timestamp, event, data) VALUES (?, ?, ?)",
                (now, event, json.dumps(data, sort_keys=True)))
            self._db.commit()

    def last_event_time(self, event: str) -> Optional[float]:
        """Rate-limiter query (log.rs:98-114)."""
        with self._lock:
            row = self._db.execute(
                "SELECT MAX(timestamp) FROM log WHERE event = ?",
                (event,)).fetchone()
        return row[0]

    def last_backup_size(self) -> Optional[int]:
        """Size-estimate source (log.rs:132-160)."""
        with self._lock:
            row = self._db.execute(
                "SELECT data FROM log WHERE event = ? ORDER BY id DESC LIMIT 1",
                (EVENT_BACKUP,)).fetchone()
        if row is None:
            return None
        return json.loads(row[0]).get("size")
