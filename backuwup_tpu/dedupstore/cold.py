"""Cold tier: a bucketed LSM-style host/disk fingerprint store.

Fingerprints that do not fit the hot HBM table (dedupstore/tiered.py)
live here as sorted immutable runs plus an in-memory memtable, in the
classic LSM arrangement (the reference keeps its whole index host-side;
this store keeps only the cold overflow).  One record is the 16-byte
truncated fingerprint — the same 4-word key the device table probes
(``ops/dedup_index.py``) — plus a u32 value, 20 bytes total.

Layout of one run file (little-endian):

=============================  ==============================================
region                         contents
=============================  ==============================================
header (24 bytes)              ``b"BKWCRUN1"`` magic, u32 bucket count,
                               u32 input count, u64 record count
input seqs                     u64 per input: the runs this run replaced
                               (compaction provenance — recovery rolls the
                               make-before-break cleanup forward)
skip words                     u64 per bucket: bloom-style filter, one bit
                               per key's second word (``w1 & 63``) — a
                               query whose bit is unset skips the run
                               without touching a record
bucket directory               u64 per bucket: record count per prefix
                               bucket (top bits of the first key word)
records                        count x 20 bytes, sorted ascending by the
                               big-endian serialized key
=============================  ==============================================

Keys serialize big-endian per word so plain byte order sorts like the
``(w0, w1, w2, w3)`` tuple and the first key word is the literal byte
prefix — runs are therefore prefix-bucketed by construction, and
:meth:`ColdFingerprintStore.classify` answers a whole query batch with
one vectorized binary search per run after the skip words drop the
definite absents.

Durability follows ALICE discipline (PAPERS.md): a run becomes visible
only via ``durable.commit_replace`` (fsync tmp, rename, fsync dir) with
``faults.crashpoint`` seams on both sides, and compaction is
make-before-break — the merged run records its inputs' seqs, so a crash
between commit and input cleanup is rolled forward on the next open.
The memtable is volatile by design: the tiered front only drops a key
from the hot table after :meth:`flush` made it durable here, and every
other memtable entry is reconstructible from the BlobIndex authority.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import defaults
from ..obs import profile as obs_profile
from ..utils import durable, faults

_MAGIC = b"BKWCRUN1"
_HEADER = struct.Struct("<8sIIQ")
RECORD_DTYPE = np.dtype([("key", "S16"), ("value", "<u4")])

# Crash seams on the two durable commits (bkwlint BKW003: registered at
# import, one crashpoint call on each side of each commit_replace).
_CP_RUN_PRE = faults.register_crash_site("tier.run.commit.pre")
_CP_RUN_POST = faults.register_crash_site("tier.run.commit.post")
_CP_COMPACT_PRE = faults.register_crash_site("tier.compact.commit.pre")
_CP_COMPACT_POST = faults.register_crash_site("tier.compact.commit.post")


def pack_keys(queries: np.ndarray) -> np.ndarray:
    """``(N, 4)`` u32 query words -> ``(N,)`` S16 sortable keys.

    Big-endian per word, so lexicographic byte order equals numeric
    ``(w0, w1, w2, w3)`` order (numpy's trailing-NUL-stripping bytes
    semantics preserve both order and distinctness for fixed-width
    originals padded with the minimal byte).
    """
    q = np.ascontiguousarray(np.asarray(queries, dtype=np.uint32))
    if q.size == 0:
        return np.empty(0, dtype="S16")
    return q.reshape(-1, 4).astype(">u4").reshape(-1).view("S16")


def unpack_keys(keys: np.ndarray) -> np.ndarray:
    """``(N,)`` S16 keys -> ``(N, 4)`` u32 query words (inverse of
    :func:`pack_keys`)."""
    if len(keys) == 0:
        return np.zeros((0, 4), dtype=np.uint32)
    # field views of structured arrays are strided: repack first
    fixed = np.ascontiguousarray(np.asarray(keys, dtype="S16"))
    raw = fixed.view(">u4").reshape(-1, 4)
    return raw.astype(np.uint32)


class _Run:
    """One sorted immutable run, records memory-mapped read-only."""

    def __init__(self, path: Path):
        self.path = path
        self.seq = int(path.stem[1:])
        with path.open("rb") as f:
            head = f.read(_HEADER.size)
            if len(head) != _HEADER.size:
                raise ValueError(f"truncated run header: {path}")
            magic, n_buckets, n_inputs, count = _HEADER.unpack(head)
            if magic != _MAGIC:
                raise ValueError(f"bad run magic in {path}: {magic!r}")
            self.count = count
            self.inputs: Tuple[int, ...] = tuple(
                np.frombuffer(f.read(8 * n_inputs), dtype="<u8").tolist())
            self.skip = np.frombuffer(
                f.read(8 * n_buckets), dtype="<u8").copy()
            self.bucket_counts = np.frombuffer(
                f.read(8 * n_buckets), dtype="<u8").copy()
            offset = f.tell()
        self.records = np.memmap(path, dtype=RECORD_DTYPE, mode="r",
                                 offset=offset, shape=(count,))

    @property
    def n_buckets(self) -> int:
        return len(self.skip)


def _encode_run(records: np.ndarray, n_buckets: int,
                inputs: Sequence[int]) -> bytes:
    """Serialize sorted records into one run blob (header + filters +
    bucket directory + records)."""
    shift = 32 - (n_buckets.bit_length() - 1)
    keys_w = unpack_keys(records["key"])
    if len(keys_w):
        buckets = (keys_w[:, 0] >> np.uint32(shift)).astype(np.int64)
        bits = (keys_w[:, 1] & np.uint32(63)).astype(np.uint64)
        skip = np.zeros(n_buckets, dtype="<u8")
        np.bitwise_or.at(skip, buckets, np.uint64(1) << bits)
        counts = np.bincount(buckets, minlength=n_buckets).astype("<u8")
    else:
        skip = np.zeros(n_buckets, dtype="<u8")
        counts = np.zeros(n_buckets, dtype="<u8")
    head = _HEADER.pack(_MAGIC, n_buckets, len(inputs), len(records))
    return b"".join([
        head,
        np.asarray(list(inputs), dtype="<u8").tobytes(),
        skip.tobytes(), counts.tobytes(),
        np.ascontiguousarray(records).tobytes(),
    ])


class ColdFingerprintStore:
    """Batched membership over memtable + sorted runs, newest wins.

    ``classify(queries)`` takes the same ``(N, 4)`` u32 query rows the
    device table probes and returns a ``(N,)`` u32 vector — ``value + 1``
    for present keys, 0 for absent keys and all-zero padding rows (the
    device table's found-vector convention, so the tiered front can
    merge the two answers without translation).
    """

    def __init__(self, root: Path, *,
                 memtable_limit: Optional[int] = None,
                 n_buckets: Optional[int] = None,
                 compact_fanin: Optional[int] = None):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.memtable_limit = memtable_limit \
            or defaults.DEDUP_COLD_MEMTABLE_LIMIT
        self.n_buckets = n_buckets or defaults.DEDUP_COLD_BUCKETS
        if self.n_buckets & (self.n_buckets - 1):
            raise ValueError("n_buckets must be a power of two")
        self.compact_fanin = compact_fanin \
            or defaults.DEDUP_COLD_COMPACT_FANIN
        self._memtable: Dict[bytes, int] = {}
        self._runs: List[_Run] = []
        self._recover()

    # --- recovery replay -----------------------------------------------------

    def _recover(self) -> None:
        """Scan the run directory into a consistent run set.

        Uncommitted ``.tmp`` leftovers are dropped; a committed merged
        run whose inputs still exist (crash between compaction commit
        and cleanup) rolls forward by deleting the inputs — the merged
        run holds every surviving record, so replay is idempotent.
        """
        for tmp in self.root.glob("*.tmp"):
            tmp.unlink()
        runs = sorted((_Run(p) for p in self.root.glob("r*.run")),
                      key=lambda r: r.seq)
        by_seq = {r.seq: r for r in runs}
        stale: set = set()
        for r in runs:
            for seq in r.inputs:
                if seq in by_seq:
                    stale.add(seq)
        for seq in stale:
            by_seq[seq].path.unlink(missing_ok=True)
        self._runs = [r for r in runs if r.seq not in stale]
        self._next_seq = max((r.seq for r in runs), default=-1) + 1
        self._note_state()

    # --- ingest --------------------------------------------------------------

    def insert(self, queries: np.ndarray,
               values: Optional[np.ndarray] = None) -> None:
        """Buffer ``(N, 4)`` query rows into the memtable (all-zero
        padding rows skipped); flushes a run when the memtable fills."""
        q = np.asarray(queries, dtype=np.uint32).reshape(-1, 4)
        if values is None:
            vals = np.ones(len(q), dtype=np.uint32)
        else:
            vals = np.asarray(values, dtype=np.uint32).reshape(-1)
        live = q.any(axis=1)
        packed = pack_keys(q[live])
        for k, v in zip(packed.tolist(), vals[live].tolist()):
            self._memtable[k] = v
        if len(self._memtable) >= self.memtable_limit:
            self.flush()
        else:
            self._note_state()

    def flush(self) -> None:
        """Commit the memtable as a new sorted run (crash-safe), then
        fold same-size runs per the size-tiered policy."""
        if self._memtable:
            records = np.empty(len(self._memtable), dtype=RECORD_DTYPE)
            records["key"] = np.array(list(self._memtable.keys()),
                                      dtype="S16")
            records["value"] = np.fromiter(
                self._memtable.values(), dtype=np.uint32,
                count=len(self._memtable))
            records.sort(order="key")
            self._commit_run(records, kind="flush", inputs=())
            self._memtable.clear()
        self._maybe_compact()
        self._note_state()

    def _commit_run(self, records: np.ndarray, kind: str,
                    inputs: Sequence[int]) -> _Run:
        seq = self._next_seq
        self._next_seq += 1
        path = self.root / f"r{seq:012d}.run"
        tmp = path.with_suffix(".tmp")
        tmp.write_bytes(_encode_run(records, self.n_buckets, inputs))
        if kind == "flush":
            faults.crashpoint(_CP_RUN_PRE)
            durable.commit_replace(tmp, path)
            faults.crashpoint(_CP_RUN_POST)
        else:
            faults.crashpoint(_CP_COMPACT_PRE)
            durable.commit_replace(tmp, path)
            faults.crashpoint(_CP_COMPACT_POST)
        run = _Run(path)
        self._runs.append(run)
        obs_profile.tier_cold_commit(kind)
        return run

    # --- size-tiered compaction ----------------------------------------------

    @staticmethod
    def _tier_of(count: int) -> int:
        # log4 of record count: one merged run of fanin=4 same-size
        # inputs lands one tier up, so tiers stay geometrically spaced
        return max(count, 1).bit_length() // 2

    def _maybe_compact(self) -> None:
        while True:
            tiers: Dict[int, List[_Run]] = {}
            for run in self._runs:
                tiers.setdefault(self._tier_of(run.count), []).append(run)
            victims = next((rs for rs in tiers.values()
                            if len(rs) >= self.compact_fanin), None)
            if victims is None:
                return
            self._compact(victims)

    def _compact(self, victims: List[_Run]) -> None:
        """Merge ``victims`` into one run, newest value winning, then
        drop the inputs (make-before-break: the merged run commits with
        the input seqs in its header before anything is deleted)."""
        newest_first = sorted(victims, key=lambda r: -r.seq)
        merged = np.concatenate(
            [np.asarray(r.records) for r in newest_first])
        order = np.argsort(merged["key"], kind="stable")
        merged = merged[order]
        keep = np.ones(len(merged), dtype=bool)
        keep[1:] = merged["key"][1:] != merged["key"][:-1]
        self._commit_run(merged[keep], kind="compact",
                         inputs=tuple(r.seq for r in victims))
        for r in victims:
            r.path.unlink(missing_ok=True)
        gone = {r.seq for r in victims}
        self._runs = [r for r in self._runs if r.seq not in gone]

    # --- batched classify ----------------------------------------------------

    def classify(self, queries: np.ndarray) -> np.ndarray:
        """``(N, 4)`` u32 query rows -> ``(N,)`` u32: ``value + 1`` for
        present keys, 0 for absent keys and all-zero padding rows."""
        q = np.asarray(queries, dtype=np.uint32).reshape(-1, 4)
        out = np.zeros(len(q), dtype=np.uint32)
        open_idx = np.flatnonzero(q.any(axis=1))
        if open_idx.size == 0:
            return out
        packed = pack_keys(q[open_idx])
        # memtable first (newest layer)
        mem = self._memtable
        if mem:
            misses = []
            for i, key in enumerate(packed.tolist()):
                v = mem.get(key)
                if v is None:
                    misses.append(i)
                else:
                    out[open_idx[i]] = v + 1
            if not misses:
                return out
            sel = np.asarray(misses, dtype=np.int64)
            open_idx, packed = open_idx[sel], packed[sel]
        shift = 32 - (self.n_buckets.bit_length() - 1)
        w = q[open_idx]
        buckets = (w[:, 0] >> np.uint32(shift)).astype(np.int64)
        bits = (w[:, 1] & np.uint32(63)).astype(np.uint64)
        for run in sorted(self._runs, key=lambda r: -r.seq):
            if run.count == 0 or open_idx.size == 0:
                continue
            # bloom-style skip words: definite absents never touch a
            # record page
            cand = np.flatnonzero(
                (run.skip[buckets] >> bits) & np.uint64(1))
            if cand.size == 0:
                continue
            pos = np.searchsorted(run.records["key"], packed[cand])
            inb = pos < run.count
            hitm = np.zeros(cand.size, dtype=bool)
            if inb.any():
                sub = cand[inb]
                hitm[inb] = run.records["key"][pos[inb]] == packed[sub]
            if hitm.any():
                hits = cand[hitm]
                out[open_idx[hits]] = \
                    run.records["value"][pos[hitm]] + 1
                keepm = np.ones(open_idx.size, dtype=bool)
                keepm[hits] = False
                open_idx, packed = open_idx[keepm], packed[keepm]
                buckets, bits = buckets[keepm], bits[keepm]
        return out

    # --- lifecycle -----------------------------------------------------------

    def reset(self) -> None:
        """Drop every run and the memtable (tiered front reconcile: the
        cold tier is a cache of the BlobIndex authority, and a detected
        stale key invalidates the whole store rather than risking a
        pruned fingerprint classifying as duplicate).  Seqs stay
        monotonic so no later run can alias a deleted one."""
        for r in self._runs:
            r.path.unlink(missing_ok=True)
        self._runs = []
        self._memtable.clear()
        self._note_state()

    # --- introspection -------------------------------------------------------

    @property
    def run_count(self) -> int:
        return len(self._runs)

    def __len__(self) -> int:
        """Records across runs + memtable (cross-run duplicates counted
        until compaction merges them — an upper bound on unique keys)."""
        return len(self._memtable) + sum(r.count for r in self._runs)

    def known_queries(self) -> np.ndarray:
        """All distinct keys as ``(N, 4)`` u32 rows, newest-wins
        deduplicated (seeding helper for the tiered front)."""
        layers = [np.array(list(self._memtable.keys()), dtype="S16")]
        layers += [np.asarray(r.records["key"])
                   for r in sorted(self._runs, key=lambda r: -r.seq)]
        keys = np.concatenate(layers) if layers else \
            np.empty(0, dtype="S16")
        return unpack_keys(np.unique(keys))

    def _note_state(self) -> None:
        obs_profile.tier_cold_state(len(self._runs), len(self))
