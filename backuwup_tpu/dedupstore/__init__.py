"""Tiered dedup index: HBM-hot probe over a host LSM cold tier.

``cold.py`` is the bucketed LSM-style host/disk fingerprint store
(sorted immutable runs + memtable, crash-disciplined run commits);
``tiered.py`` is the :class:`TieredDedupIndex` front that keeps the hot
:class:`~backuwup_tpu.ops.dedup_index.ShardedDedupIndex` under the
``DEDUP_HBM_BUDGET_BYTES`` cap by demoting cold fingerprints instead of
growing 4x forever.  Architecture notes: docs/dedup_tiering.md.
"""

from .cold import ColdFingerprintStore
from .tiered import TieredDedupIndex

__all__ = ["ColdFingerprintStore", "TieredDedupIndex"]
