"""TieredDedupIndex: hot HBM probe over the cold LSM store.

Drop-in for :class:`~backuwup_tpu.snapshot.device_dedup.MeshDedupIndex`
(same ``classify_dispatch`` / ``resolve_hints`` / ``classify_insert``
interface, same ``mesh``/``axis``/``host``/``capacity``/``sharded``
attributes) with one semantic shift: the hot
:class:`~backuwup_tpu.ops.dedup_index.ShardedDedupIndex` is a *partial*
cache.  A device hit is still authoritative ("resident before this
batch"), but a device miss only means "not in HBM" — the per-shard
overflow/found-flag machinery the mesh pipeline already downloads per
batch doubles as the miss filter, and only those flagged lanes fall
through to :class:`~backuwup_tpu.dedupstore.cold.ColdFingerprintStore`
in one vectorized batch.  The hot path stays free of per-batch host
round trips (FastCDC's system argument, PAPERS.md: never stall the
pipeline around the chunker).

Budget discipline: the hot table's HBM bytes (``slots x 20 x devices``)
never exceed ``DEDUP_HBM_BUDGET_BYTES``.  When insert pressure would
force a 4x growth past the cap, :meth:`_demote` spills the
least-recently-probed residents to the cold store — durably
(run commit) *before* the hot table drops them — and rebuilds through
the same migration path a growth would use.  Promotion is the inverse:
a probe-frequency clock over dispatch windows re-pins cold keys that
keep getting hit back into HBM.

Correctness invariant (the bit-identity gate): ``hot ∪ cold`` always
covers every fingerprint the :class:`BlobIndex` authority knows, so
device-miss + cold-miss ⇒ genuinely new, and device hits only ever name
keys the authority knows (junk fallback keys aside, at the same 2^-128
odds the 128-bit truncation already accepts).
"""

from __future__ import annotations

from collections import OrderedDict
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np
from jax.sharding import Mesh

from .. import defaults
from ..obs import profile as obs_profile
from ..ops.dedup_index import (
    DedupIndexFull,
    ShardedDedupIndex,
    hashes_to_queries,
)
from ..snapshot.blob_index import BlobIndex
from ..snapshot.device_dedup import (
    _SEED_BATCH,
    MeshDedupIndex,
    _next_pow2,
)
from .cold import ColdFingerprintStore

# 16-byte truncated key + u32 value per hot slot
SLOT_BYTES = 20


class TieredDedupIndex(MeshDedupIndex):
    """Budget-capped MeshDedupIndex with a cold LSM fall-through."""

    def __init__(self, mesh: Mesh, host_index: BlobIndex,
                 axis: str = "data", capacity: Optional[int] = None, *,
                 cold_dir: Path,
                 hbm_budget_bytes: Optional[int] = None,
                 clock_windows: Optional[int] = None,
                 promote_min_hits: Optional[int] = None,
                 memtable_limit: Optional[int] = None,
                 compact_fanin: Optional[int] = None):
        self.hbm_budget_bytes = int(
            hbm_budget_bytes or defaults.DEDUP_HBM_BUDGET_BYTES)
        self.clock_windows = int(
            clock_windows or defaults.DEDUP_TIER_CLOCK_WINDOWS)
        self.promote_min_hits = int(
            promote_min_hits or defaults.DEDUP_TIER_PROMOTE_MIN_HITS)
        self.cold = ColdFingerprintStore(
            cold_dir, memtable_limit=memtable_limit,
            compact_fanin=compact_fanin)
        self._windows = 0
        self._saw_dispatch = False
        self._cold_hits: Dict[bytes, int] = {}
        self._promote_queue: Dict[bytes, int] = {}
        # probe-recency clock: fingerprint -> None, most recent last;
        # demotion keeps the newest entries, so its size cap doubles as
        # the hot working-set estimate
        self._recent: "OrderedDict[bytes, None]" = OrderedDict()
        n_dev = mesh.shape[axis]
        known = len(host_index) + host_index.queued_count
        need = max(defaults.DEDUP_SHARD_CAPACITY,
                   _next_pow2(4 * max(known, 1) // max(n_dev, 1)))
        cap = min(capacity or need, self._max_capacity(n_dev))
        super().__init__(mesh, host_index, axis, capacity=cap)

    # --- capacity / budget ---------------------------------------------------

    def _max_capacity(self, n_dev: int) -> int:
        """Largest pow2 per-shard capacity under the HBM budget (floor
        of 8 slots/shard so a tiny budget still yields a working table)."""
        per = self.hbm_budget_bytes // (SLOT_BYTES * max(n_dev, 1))
        cap = 1
        while cap * 2 <= per:
            cap *= 2
        return max(cap, 8)

    @property
    def hbm_table_bytes(self) -> int:
        """HBM bytes the hot fingerprint table occupies across the mesh."""
        return self.mesh.shape[self.axis] * self.capacity * SLOT_BYTES

    @property
    def _pressure(self) -> bool:
        """True once the tier split is live: the cold store holds keys,
        or the next 4x growth would cross the budget (so the next Full
        demotes).  Until then the index behaves exactly like the parent
        and the per-batch clock/cold bookkeeping — recency touches, cold
        lookups, heat counters — is skipped wholesale: recency only
        matters for picking demotion victims, and the first demotion's
        arbitrary pick is corrected by the very next touched batches."""
        return (len(self.cold) > 0 or
                self.mesh.shape[self.axis] * self.capacity * 4 * SLOT_BYTES
                > self.hbm_budget_bytes)

    def _note_hbm(self) -> None:
        obs_profile.tier_hbm_bytes(self.hbm_table_bytes)

    # --- seeding -------------------------------------------------------------

    def _rebuild(self) -> None:
        """Seed hot up to a 50% fill ceiling; everything else — and
        everything the persisted cold runs already answer — stays cold.

        Checking the runs first means a restart does not re-spill the
        whole population through fresh run commits: the cold tier's own
        durable state seeds itself.  But first the persisted runs are
        reconciled against the authority: a cold key the BlobIndex no
        longer knows (GC / peer-loss prune since the runs committed)
        would misclassify a re-packed blob as duplicate, so any stale
        key invalidates the cold store wholesale — it is a cache, and
        the seeding below rebuilds it from the authority.
        """
        self.sharded = ShardedDedupIndex.create(
            self.mesh, self.axis, capacity=self.capacity)
        self._note_hbm()
        fill_cap = (self.mesh.shape[self.axis] * self.capacity) // 2
        seeded = 0
        hashes = self.host.known_hashes()
        if len(self.cold):
            known16 = {bytes(h[:16]) for h in hashes}
            cq = self.cold.known_queries()
            le = np.ascontiguousarray(cq.astype("<u4")).tobytes()
            if any(le[i * 16:(i + 1) * 16] not in known16
                   for i in range(len(cq))):
                self.cold.reset()
        for s in range(0, len(hashes), _SEED_BATCH):
            batch = hashes[s:s + _SEED_BATCH]
            q = hashes_to_queries(batch)
            if len(self.cold):
                fresh = np.flatnonzero(self.cold.classify(q) == 0)
                if fresh.size == 0:
                    continue
                q = q[fresh]
            take = min(len(q), max(0, fill_cap - seeded))
            if take:
                try:
                    self.sharded.insert(
                        q[:take], np.ones(take, dtype=np.uint32))
                    seeded += take
                except DedupIndexFull:
                    # probe clustering filled the table early: the whole
                    # segment goes cold (a key in both tiers is harmless)
                    self.cold.insert(q[:take])
                    fill_cap = seeded
            if take < len(q):
                self.cold.insert(q[take:])

    # --- growth / demotion ---------------------------------------------------

    def _grow(self) -> None:
        """Grow 4x while that fits the budget; at the cap, demote the
        cold half of the table instead of growing forever."""
        n_dev = self.mesh.shape[self.axis]
        cap = self.capacity * 4
        while n_dev * cap * SLOT_BYTES <= self.hbm_budget_bytes:
            try:
                self.sharded = self.sharded.grown(cap)
                self.capacity = cap
                self._note_hbm()
                return
            except DedupIndexFull:
                cap *= 4
        self._demote()

    def _demote(self) -> None:
        """Spill the least-recently-probed keys to the cold store and
        rebuild the hot table with only the recent quarter.

        Ordering is make-before-break: the spill set is durable in the
        cold tier (run commit + fsync) before the old table is replaced,
        so a crash anywhere leaves every key classifiable — from the old
        hot table before, from the committed run after.
        """
        keys_q, vals = self.sharded.dump()
        n_dev = self.mesh.shape[self.axis]
        # keep the recent quarter of the table (or half the residents
        # when pressure hit at low fill — pathological probe clustering):
        # post-demotion headroom must absorb a whole dispatch batch, and
        # a keep target of half the slots left zero room the moment a
        # demotion had just run.  The budget is a HARD cap: when even a
        # demoted table cannot take the batch, the bounded-retry parking
        # paths hand the keys to the cold tier instead of growing.
        keep_cap = min((n_dev * self.capacity) // 4, len(keys_q) // 2)
        rank = {k: i for i, k in enumerate(self._recent)}
        # clock keys are the raw little-endian first-16-bytes (h[:16]),
        # exactly the u32 query words' LE serialization
        le = np.ascontiguousarray(keys_q.astype("<u4")).tobytes()
        order = np.fromiter(
            (rank.get(le[i * 16:(i + 1) * 16], -1)
             for i in range(len(keys_q))),
            dtype=np.int64, count=len(keys_q))
        keep_mask = np.zeros(len(keys_q), dtype=bool)
        if keep_cap:
            keep_mask[np.argsort(order, kind="stable")[-keep_cap:]] = True
        spill = ~keep_mask
        self.cold.insert(keys_q[spill], vals[spill])
        self.cold.flush()
        obs_profile.tier_demotions(int(spill.sum()))
        self.sharded = ShardedDedupIndex.create(
            self.mesh, self.axis, capacity=self.capacity)
        kq, kv = keys_q[keep_mask], vals[keep_mask]
        for s in range(0, len(kq), _SEED_BATCH):
            try:
                self.sharded.insert(kq[s:s + _SEED_BATCH],
                                    kv[s:s + _SEED_BATCH])
            except DedupIndexFull:  # pragma: no cover - keep set <= 1/4
                self.cold.insert(kq[s:], kv[s:])
                self.cold.flush()
                break
        self._note_hbm()

    # --- promotion clock -----------------------------------------------------

    def note_window(self, lanes: int, lost: int = 0) -> None:
        """Dispatch-site hook (ops/pipeline.py): one mesh classify
        dispatch = one clock window.  ``lanes``/``lost`` describe the
        batch's real query lanes and exhausted-probe fallout."""
        self._saw_dispatch = True
        if lanes:
            self._tick_window()

    def _tick_window(self) -> None:
        self._windows += 1
        if self._windows % self.clock_windows == 0:
            self._run_clock()

    def _touch(self, key16: bytes) -> None:
        r = self._recent
        if key16 in r:
            r.move_to_end(key16)
        else:
            r[key16] = None
            cap = max(64, (self.mesh.shape[self.axis] * self.capacity) // 2)
            while len(r) > cap:
                r.popitem(last=False)

    def _note_cold_hit(self, key16: bytes) -> None:
        n = self._cold_hits.get(key16, 0) + 1
        self._cold_hits[key16] = n
        if n >= self.promote_min_hits:
            self._promote_queue[key16] = 1

    def _run_clock(self) -> None:
        """One promotion/demotion period: cold keys that crossed the hit
        threshold this period get re-pinned into HBM, then the counters
        reset so stale heat decays."""
        if self._promote_queue:
            keys = list(self._promote_queue)
            q = np.frombuffer(b"".join(keys), dtype="<u4").reshape(-1, 4)
            vals = np.ones(len(keys), dtype=np.uint32)
            for _ in range(2):
                try:
                    self.sharded.insert(q, vals)
                    for k in keys:
                        self._touch(k)
                    obs_profile.tier_promotions(len(keys))
                    break
                except DedupIndexFull:
                    self._grow()
            # still full after a demotion: skip this period's promotions
            # — the keys stay cold-classifiable, heat re-accrues
            self._promote_queue.clear()
        self._cold_hits.clear()

    # --- classify interface --------------------------------------------------

    def resolve_hints(self, hashes: List[bytes],
                      raw: List[Optional[bool]]) -> List[bool]:
        """Parent semantics plus the cold fall-through: concrete-False
        occurrences (device miss, the repurposed overflow-flag filter)
        consult the cold tier in one batch before being called new;
        ``None`` occurrences still go to the host authority."""
        hashes = [bytes(h) for h in hashes]
        if not hashes:
            return []
        _unset = object()
        facts: dict = {}
        for h, f in zip(hashes, raw):
            prev = facts.get(h, _unset)
            if prev is None:
                continue
            if f is None:
                facts[h] = None
            elif prev is _unset:
                facts[h] = bool(f)
            else:
                facts[h] = prev and bool(f)
        dev_probes = sum(1 for f in facts.values() if f is not None)
        dev_hits = sum(1 for f in facts.values() if f)
        obs_profile.tier_probes("device", dev_probes, dev_hits)
        miss = [h for h, f in facts.items() if f is False]
        if miss and self._pressure:
            ans = self.cold.classify(hashes_to_queries(miss))
            cold_hits = 0
            for h, a in zip(miss, ans):
                if a:
                    facts[h] = True
                    cold_hits += 1
                    self._note_cold_hit(h[:16])
            obs_profile.tier_probes("cold", len(miss), cold_hits)
        pend = [h for h, f in facts.items() if f is None]
        host_facts = {}
        if pend:
            for h in pend:
                host_facts[h] = self.host.is_duplicate(h)
            obs_profile.tier_probes("host", len(pend),
                                    sum(host_facts.values()))
            q = hashes_to_queries(pend)
            vals = np.ones(len(pend), dtype=np.uint32)
            attempts = 0
            while True:
                try:
                    self.sharded.insert(q, vals)
                    break
                except DedupIndexFull:
                    attempts += 1
                    if attempts >= 3:
                        # batch ~ table size at the budget cap: park the
                        # keys in the cold tier instead of thrashing the
                        # demotion path — still classifiable everywhere
                        self.cold.insert(q)
                        break
                    self._grow()
        if self._pressure:
            for h in facts:
                self._touch(h[:16])
        if not self._saw_dispatch:
            self._tick_window()
        flags: List[bool] = []
        seen: set = set()
        for h in hashes:
            if h in seen:
                flags.append(True)
            else:
                seen.add(h)
                f = facts[h]
                flags.append(host_facts[h] if f is None else f)
        return flags

    def classify_insert(self, hashes: List[bytes]) -> List[bool]:
        """Parent semantics plus the cold fall-through for device-new
        verdicts (and budget-capped growth via the overridden _grow)."""
        hashes = [bytes(h) for h in hashes]
        if not hashes:
            return []
        first: dict = {}
        uniq: List[bytes] = []
        for h in hashes:
            if h not in first:
                first[h] = len(uniq)
                uniq.append(h)
        q = hashes_to_queries(uniq)
        vals = np.ones(len(uniq), dtype=np.uint32)
        interrupted = False
        attempts = 0
        found = None
        while True:
            try:
                found = self.sharded.insert(q, vals)
                break
            except DedupIndexFull:
                # a demotion/growth mid-batch may have scattered part of
                # the batch; verdicts resolve against the host authority
                interrupted = True
                attempts += 1
                if attempts >= 3:
                    # batch ~ table size at the budget cap: park the keys
                    # cold and let the authority answer this batch
                    self.cold.insert(q)
                    break
                self._grow()
        cold_dup: set = set()
        if interrupted:
            obs_profile.tier_probes("host", len(uniq))
        else:
            miss_idx = np.flatnonzero(found == 0)
            obs_profile.tier_probes("device", len(uniq),
                                    len(uniq) - miss_idx.size)
            if miss_idx.size and self._pressure:
                ans = self.cold.classify(q[miss_idx])
                cold_hits = 0
                for i, a in zip(miss_idx.tolist(), ans.tolist()):
                    if a:
                        cold_dup.add(uniq[i])
                        cold_hits += 1
                        self._note_cold_hit(uniq[i][:16])
                obs_profile.tier_probes("cold", int(miss_idx.size),
                                        cold_hits)
        if self._pressure:
            for h in uniq:
                self._touch(h[:16])
        self._tick_window()
        flags: List[bool] = []
        seen: set = set()
        for h in hashes:
            if h in seen:
                flags.append(True)
            elif interrupted:
                seen.add(h)
                flags.append(self.host.is_duplicate(h))
            else:
                seen.add(h)
                flags.append(bool(found[first[h]] > 0) or h in cold_dup)
        return flags
