"""Compile-time tunables for the whole framework.

Mirrors the constant surface of the reference (``client/src/defaults.rs:1-68``,
``shared/src/constants.rs:4-7``, ``client/src/backup/filesystem/packfile/mod.rs:25-31``,
``shared/src/p2p_message.rs:8``, ``client/src/backup/filesystem/dir_packer.rs:35``,
``client/src/backup/filesystem/packfile/blob_index.rs:16``), plus the
TPU-kernel tunables that have no reference equivalent.
"""

KiB = 1024
MiB = 1024 * KiB
GiB = 1024 * MiB

# --- content-defined chunking (reference client/src/defaults.rs:62-68) ------
CDC_MIN_CHUNK = 256 * KiB
CDC_DESIRED_CHUNK = 1 * MiB
CDC_MAX_CHUNK = 3 * MiB

# Normalized-chunking mask widths (FastCDC 2020, normalization level 2):
# below the desired size a stricter mask applies, above it a looser one.
CDC_MASK_S_BITS = 22  # desired 2**20 => 20 + 2
CDC_MASK_L_BITS = 18  # 20 - 2

# --- packfiles (reference packfile/mod.rs:25-31) -----------------------------
PACKFILE_TARGET_SIZE = 3 * MiB
PACKFILE_MAX_SIZE = 16 * MiB
PACKFILE_MAX_BLOBS = 100_000
ZSTD_COMPRESSION_LEVEL = 3

# --- blob index (reference blob_index.rs:16) --------------------------------
INDEX_FILE_MAX_ENTRIES = 50_000

# --- tree blobs (reference dir_packer.rs:35) --------------------------------
TREE_MAX_CHILDREN = 10_000

# --- send pipeline / backpressure (reference defaults.rs:38-59) -------------
PACKFILE_LOCAL_BUFFER_LIMIT = 100 * MiB
PACKFILE_RESUME_THRESHOLD = 50 * MiB  # resume packing when this much is free
PACKFILE_SEND_TIMEOUT_S = 20.0
ACK_TIMEOUT_S = 5.0
STORAGE_REQUEST_RETRY_S = 10.0
RESTORE_REQUEST_THROTTLE_S = 60.0
STORAGE_REQUEST_STEP = 50 * 1000 * 1000  # 50 MB (decimal, like the reference)
STORAGE_REQUEST_CAP = 150 * 1000 * 1000  # 150 MB
PEER_OVERUSE_GRACE = 16 * MiB  # tolerated overshoot per peer (defaults.rs:34)

# --- unified retry policies (utils/retry.py; no reference equivalent — the
# reference hardcodes each of these inline) ----------------------------------
RETRY_JITTER = 0.1  # default +/- fraction applied to every delay
DIAL_RETRY_BASE_S = 0.5  # p2p dial (handle_connections.rs:145-165 used 0.5)
DIAL_RETRY_CAP_S = 2.0
DIAL_RETRY_ATTEMPTS = 2  # retries after the first dial (3 dials total)
WS_RECONNECT_BASE_S = 0.2  # server push channel (net_server/mod.rs:26-55)
WS_RECONNECT_CAP_S = 30.0
# Grace given to in-flight handlers when a coordination node stops.
# aiohttp's 60s default lets one live WebSocket push channel stall a
# node's shutdown for a minute; clients reconnect elsewhere anyway, so
# a stopping (or dying) node cuts stragglers fast.
SERVER_SHUTDOWN_GRACE_S = 2.0
STORAGE_REQUEST_RETRY_CAP_S = 60.0  # re-request backoff ceiling
SEND_IDLE_BASE_S = 0.05  # send loop waiting on the packer
SEND_IDLE_CAP_S = 0.25
PEER_WAIT_BASE_S = 0.2  # send loop waiting for a usable peer
PEER_WAIT_CAP_S = 1.0

# --- peer-loss repair (utils/faults.py, engine.repair_round) -----------------
# A peer unseen for this long is treated as lost even without an audit
# demotion: its placements are orphaned and repair re-replicates them.
PEER_DARK_DEADLINE_S = 3 * 24 * 3600.0

# --- erasure-coded shard placement (erasure/, docs/erasure.md; no reference
# equivalent — the reference is replication-only) -----------------------------
# Each sealed packfile is split into RS_K data shards plus RS_M parity
# shards (systematic GF(2^8) Reed-Solomon); any RS_K of the RS_K+RS_M
# shards reconstruct the packfile.  Sharding activates per packfile only
# when a full stripe of distinct peers is available at send time;
# otherwise the legacy whole-packfile single-peer path runs.
RS_K = 4
RS_M = 2

# --- concurrent transfer plane (net/transfer.py, docs/transfer.md; no
# reference equivalent — send.rs transmits strictly one file at a time) -------
# Uploads admitted concurrently across all peers; per-peer ordering is
# still serialized (the signed transport sequence demands it).
TRANSFER_MAX_INFLIGHT = 8
# Distinct peers the whole-packfile path fans out to per send tick (the
# stripe path always uses one peer per missing shard).
TRANSFER_MAX_PEERS = 4
# In-flight payload RAM cap; a single transfer larger than the cap is
# still admitted when the plane is empty (no deadlock on oversize files).
TRANSFER_INFLIGHT_BYTE_CAP = 64 * MiB
# Packfile seal pipeline (snapshot/packfile.py): worker threads running
# zstd + AES-GCM (both release the GIL) and the bound on
# assembled-but-unwritten packfile batches (double buffering).  0 workers
# = the original synchronous seal-in-add_blob behavior.
PACK_SEAL_WORKERS = 2
PACK_SEAL_QUEUE_PACKFILES = 2
# Streaming dataflow (docs/dataflow.md): blobs buffered below the
# packfile target size are force-emitted into the seal pipeline once
# they have waited this long, so the wire never starves behind the
# end-of-tree flush while the packer walks small directories.
PACK_EMIT_MAX_LAG_S = 2.0
# Missed-wakeup backstop for the event-driven send loop: the seal
# callback wakes the loop the moment a packfile commits; this timeout
# only bounds how long a (theoretical) lost wakeup could park it.
SEND_WAKEUP_BACKSTOP_S = 0.5
# Host->device staging ring depth for manifest_segments_stream
# (ops/pipeline.py): batch N+1's bytes upload asynchronously while
# batch N runs scan->digest on device.
PIPELINE_STAGE_DEPTH = 2

# --- resumable WAN transfer plane (net/p2p.py send_file, docs/transfer.md) ---
# Payloads larger than this go out as FILE_PART frames with per-part acks
# and receiver-side partial persistence, preceded by a RESUME_QUERY so a
# reconnect continues from the verified offset.  0 disables chunking
# entirely (every file rides the legacy whole-FILE frame).
TRANSFER_CHUNK_BYTES = 1 * MiB
# Reconnect-and-resume attempts after a mid-transfer failure of a chunked
# send, before the failure surfaces to the scheduler as a failed transfer.
TRANSFER_RESUME_ATTEMPTS = 2
# False = reconnect attempts restart from byte zero (no RESUME_QUERY);
# the bench's restart-from-zero baseline leg, never what production wants.
TRANSFER_RESUME_ENABLED = True
# Adaptive per-transfer deadline (replaces the fixed send/ack timeout pair
# for sized payloads): budget = ACK_TIMEOUT_S + size / floor, where floor
# is the larger of the assumed minimum link rate and the peer's measured
# EWMA throughput derated by the safety fraction.  The minimum keeps a
# never-measured peer from being declared stalled on its first large
# send; the safety fraction tolerates throughput regressions before the
# stall detector calls abort-and-resume.
TRANSFER_MIN_THROUGHPUT_BPS = 256 * KiB
TRANSFER_DEADLINE_SAFETY = 0.25
TRANSFER_DEADLINE_CAP_S = 600.0

# --- restore data plane (engine.run_restore planner, net/transfer.py
# download lanes; docs/transfer.md restore data plane) ------------------------
# Per-stripe source fan-out: each stripe's shards are pulled from its k
# currently-fastest live holders (k = the stripe's data-shard count); the
# remaining m holders are held back as hedge spares.  When a pull has been
# running for this fraction of its adaptive deadline without finishing, a
# redundant pull of a spare shard is launched and the first completion
# wins — the stall is raced, not waited out.
RESTORE_HEDGE_DEADLINE_FRACTION = 0.5
# Re-queue budget for a stalled/failed shard download before the stripe
# falls back to whole-copy RESTORE_ALL sources (each retry prefers a
# holder that has not failed this shard yet).
RESTORE_FETCH_RETRIES = 2
# Serve-side throttle for RESTORE_FETCH sessions.  Deliberately decoupled
# from RESTORE_REQUEST_THROTTLE_S and off by default: one multi-source
# restore legitimately opens several fetch connections to the same holder
# in quick succession (per-stripe pulls, hedges, the index sweep), and a
# fetch serves only the named items, so the abuse surface is bounded.
# Operators worried about hostile pullers can raise it.
RESTORE_FETCH_MIN_INTERVAL_S = 0.0
# Upper bound on items one FETCH_REQUEST may name (mirrors the audit
# batch bound: reject absurd batches before doing any disk work).
RESTORE_FETCH_MAX_WANTS = 4096

# --- capacity-aware placement (store.find_peers_with_storage,
# net/peer_stats.py; docs/transfer.md) ----------------------------------------
# Peers are ranked by log2-bucketed (EWMA throughput x success ratio) with
# free space as the tiebreak; a peer needs this many samples before its
# measurement outranks the neutral prior, so fresh peers stay schedulable.
PLACEMENT_MIN_SAMPLES = 3
# Score assumed for unmeasured peers: they sort above measured-slow peers
# and below measured-fast ones.
PLACEMENT_NEUTRAL_SCORE_BPS = TRANSFER_MIN_THROUGHPUT_BPS
# Placement demotion (recoverable; distinct from audit demotion): a peer
# whose success EWMA sinks below the demote threshold over at least
# min-samples transfers stops receiving placements until either its
# success EWMA climbs back over the recovery threshold or the probation
# window expires.
PLACEMENT_DEMOTE_SUCCESS = 0.25
PLACEMENT_RECOVER_SUCCESS = 0.6
PLACEMENT_DEMOTE_MIN_SAMPLES = 4
PLACEMENT_PROBATION_S = 600.0

# --- protocol limits (reference shared/src/constants.rs:4-7) ----------------
MAX_BACKUP_STORAGE_REQUEST_SIZE = 16 * GiB
BACKUP_REQUEST_EXPIRY_S = 300.0

# --- p2p transport (reference shared/src/p2p_message.rs:8) ------------------
MAX_P2P_MESSAGE_SIZE = 8 * MiB
# Signed-envelope framing budget (P2PBody FILE encoding + Ed25519
# signature is ~150 bytes; 4 KiB leaves generous slack).  Every file the
# send pipeline ships must fit one transport message, so the packfile
# writer's effective cap is the wire max minus this — the analog of the
# reference's validate_size_constraints proof (pack.rs:257-288), which
# only had to prove 16 MiB because its transport cap was not smaller.
P2P_ENVELOPE_OVERHEAD = 4 * KiB
PACKFILE_WIRE_MAX = MAX_P2P_MESSAGE_SIZE - P2P_ENVELOPE_OVERHEAD

# --- storage attestation (no reference equivalent; docs/audit.md) -----------
AUDIT_CHALLENGES_PER_PACKFILE = 16  # precomputed table entries per packfile
AUDIT_WINDOW_BYTES = 64 * KiB  # sampled window length (clamped to file size)
AUDIT_SAMPLES_PER_ROUND = 8  # challenges issued per peer per audit round
AUDIT_MAX_CHALLENGES_PER_MSG = 256  # prover-side cap on one CHALLENGE body
AUDIT_INTERVAL_S = 6 * 3600.0  # healthy-peer re-audit cadence
AUDIT_RETRY_BASE_S = 60.0  # first retry delay after a miss/failure
AUDIT_BACKOFF_CAP_S = 24 * 3600.0  # exponential-backoff ceiling
AUDIT_DEMOTE_MISSES = 3  # consecutive offline windows before demotion
AUDIT_DEMOTE_FAILURES = 1  # confirmed bad/missing proofs before demotion
AUDIT_PROOF_TIMEOUT_S = 15.0  # verifier wait for the PROOF body
AUDIT_SERVE_MIN_INTERVAL_S = 5.0  # prover-side per-peer rate limit
AUDIT_SERVER_BLOCK_FAILURES = 2  # distinct failing verifiers to block matches
AUDIT_REPORT_WINDOW_S = 24 * 3600.0  # server aggregation window

# --- observability plane (obs/, docs/observability.md; no reference
# equivalent — the reference prints ad-hoc lines) ------------------------------
OBS_JOURNAL_MAX_BYTES = 4 * MiB  # rotate the JSONL journal past this size
OBS_JOURNAL_KEEP = 3  # rotated generations retained (<path>.1 .. .keep)
OBS_PANIC_TAIL_LINES = 200  # journal lines embedded in a panic dump
# EWMA smoothing for the per-peer throughput/latency/success estimators
# (net/peer_stats.py): each new TransferResult carries 20% weight, so
# ~10 transfers dominate the estimate — reactive on WAN shifts without
# one stalled send cratering a peer's score.
PEER_STATS_ALPHA = 0.2

# --- live SLO plane (obs/series.py, obs/slo.py, obs/diagnose.py,
# docs/observability.md §SLOs; no reference equivalent) ------------------------
# Registry sampling cadence of the in-process time-series recorder and
# the ring-buffer depth per series.  At the default 10 s cadence 2048
# points retain ~5.7 h — enough to feed the 1 h fast burn window with
# real headroom; the 6 h/3 d slow windows clamp to available history
# while the buffer fills (burn math uses the actual covered span).
SERIES_SAMPLE_INTERVAL_S = 10.0
SERIES_RETENTION_POINTS = 2048
# Robust-zscore anomaly flagging: |z| at/above this flags a series, and
# a series needs this many points in the window before it is judged at
# all (a two-point baseline flags everything).
SERIES_ANOMALY_Z = 3.5
SERIES_ANOMALY_MIN_POINTS = 6
# Google-SRE multi-window burn alerts: the fast pair catches an active
# incident (page-grade), the slow pair a smoldering budget leak
# (ticket-grade).  Both windows of a pair must burn past the threshold
# before the objective's status moves — one spike in a short window is
# not an incident.  The sim plane reuses these spans verbatim on
# virtual time; the scenario harness shrinks them via the monitor's
# ``windows=`` override.
SLO_WINDOWS = ((300.0, 3600.0), (21600.0, 259200.0))
SLO_FAST_BURN = 14.4
SLO_SLOW_BURN = 6.0
# The declarative objective catalog (bkwlint BKW007 keeps it honest
# against the registered metric families and the docs table).  Entries
# are plain literals — the linter AST-parses this tuple, so no computed
# values.  ``budget`` is the tolerated bad-event fraction (error
# budget); ``burn = bad_fraction / budget``.  Kinds:
#   counter_rate — bad seconds per clock second (delta / covered span)
#   ratio        — bad events / total events (needs total_family)
#   quantile     — histogram observations above target / all in window
#   gauge_below  — window samples below target / all samples
SLO_CATALOG = (
    {"id": "durability", "kind": "counter_rate",
     "family": "bkw_durability_violation_seconds_total", "labels": {},
     "budget": 0.001,
     "description": "fraction of time any durability invariant is"
                    " violated stays ~0"},
    {"id": "transfer_stalls", "kind": "ratio",
     "family": "bkw_transfer_stalls_total", "labels": {},
     "total_family": "bkw_transfers_total", "budget": 0.02,
     "description": "adaptive-deadline stall aborts per completed"
                    " transfer"},
    {"id": "backup_p99", "kind": "quantile",
     "family": "bkw_span_seconds", "labels": {"name": "engine.backup"},
     "target": 120.0, "budget": 0.01,
     "description": "p99 end-to-end backup wall seconds"},
    {"id": "restore_p99", "kind": "quantile",
     "family": "bkw_span_seconds", "labels": {"name": "engine.restore"},
     "target": 120.0, "budget": 0.01,
     "description": "p99 end-to-end restore wall seconds"},
    {"id": "matchmaking_p99", "kind": "quantile",
     "family": "bkw_server_request_seconds",
     "labels": {"route": "/backups/request"},
     "target": 5.0, "budget": 0.01,
     "description": "p99 matchmaking request latency at the"
                    " coordination server"},
    {"id": "backup_overlap", "kind": "gauge_below",
     "family": "bkw_backup_overlap_efficiency", "labels": {},
     "target": 0.5, "budget": 0.25,
     "description": "streaming-dataflow overlap efficiency holds above"
                    " the floor for most of the window"},
    {"id": "repl_promote_p99", "kind": "quantile",
     "family": "bkw_repl_promote_seconds", "labels": {},
     "target": 30.0, "budget": 0.05,
     "description": "p99 successor promotion seconds (epoch commit +"
                    " log-tail replay)"},
)
# Evidence ranking for the breach explainer (obs/diagnose.py): how far
# back from the breach instant evidence is gathered when the caller
# does not pin a window, and how many ranked causes a report keeps.
DIAGNOSE_WINDOW_S = 600.0
DIAGNOSE_TOP_CAUSES = 5

# --- durability invariant monitor (obs/invariants.py, docs/scenarios.md) -----
# Background sweep cadence of the client's InvariantMonitor; health is
# current within one interval of any placement/ledger change.
DURABILITY_SWEEP_INTERVAL_S = 5.0
# Stalest tolerated attestation over a placement-holding peer before the
# monitor reports degraded audit coverage (4x the audit cadence: one
# missed round is routine backoff, four is a stuck verifier).
DURABILITY_AUDIT_MAX_AGE_S = 4 * AUDIT_INTERVAL_S

# --- crash consistency (engine.recover, net/p2p.py PartialStore janitor,
# docs/crash_consistency.md; no reference equivalent) -------------------------
# A receiver-side partial transfer untouched for this long is abandoned:
# the TTL janitor deletes the bin/json pair and frees the quota.  Kept
# shorter than PEER_DARK_DEADLINE_S — a sender that has been gone for a
# day will restart the transfer from its own resume handshake anyway.
PARTIAL_STORE_TTL_S = 24 * 3600.0

# --- snapshot lifecycle / GC (engine.run_gc, docs/lifecycle.md; no
# reference equivalent — the reference is append-only) ------------------------
# Default retention policy recorded into fresh stores.  keep-all keeps
# every snapshot (the pre-lifecycle behavior); operators narrow it to
# comma-separated keep-last:N / keep-daily:N rules.
RETENTION_DEFAULT = "keep-all"
# A packfile whose live-byte fraction (bytes still referenced by some
# retained snapshot / total payload bytes) drops below this is sparse:
# GC pulls it back, extracts the live blobs, and re-packs them into
# fresh packfiles.  At/above the threshold the dead bytes ride along —
# compaction I/O costs more than the space it would free.
GC_COMPACT_OCCUPANCY = 0.5
# Holder-side RECLAIM rate limit, same posture as the restore throttle:
# one reclaim service per peer per interval, so a buggy (or hostile)
# peer cannot grind a holder's disk with delete storms.
RECLAIM_MIN_INTERVAL_S = 5.0
# Max file ids accepted in one RECLAIM body (mirrors the restore-fetch
# wants cap): bounds the per-request unlink loop and the ack's freed-
# bytes accounting.
RECLAIM_MAX_ITEMS = 4096

# --- scale-out coordination plane (net/serverstore.py, net/matchmaking.py,
# docs/server.md; no reference equivalent — the reference is one process
# over one Postgres) ----------------------------------------------------------
# In-memory matchmaking shards, keyed by client pubkey.  Each shard has
# its own lock, FIFO, and deadline heap; fulfill walks shards starting at
# the requester's home shard (cross-shard work stealing), so the count
# bounds lock contention, not matchable peers.
MATCHMAKING_SHARDS = 8
# Write-behind store: max operations drained into one group commit.  The
# batch is whatever queued since the last commit, capped here so a
# firehose cannot defer the commit (and the durability acks) unboundedly.
SERVER_STORE_MAX_BATCH = 256

# --- federated coordination plane (net/ring.py, net/server.py /fed/*,
# docs/server.md §Federation; no reference equivalent) ------------------------
# Virtual nodes per physical coordination node on the consistent-hash
# ring.  More vnodes smooth the key distribution (max node share decays
# ~1/sqrt(vnodes)) at the cost of a larger sorted point list; 64 keeps
# add/remove key movement within ~2/N in practice.
FEDERATION_RING_VNODES = 64
# Store partitions behind PartitionedServerStore when the caller does
# not pin a count.  Partition count is a *file layout* choice, fixed for
# the lifetime of the data directory — nodes route to partitions by
# pubkey, so every node must agree on it.
SERVER_STORE_PARTITIONS = 4
# Inter-node RPC (/fed/steal, /fed/notify) total timeout.  Steal RPCs
# sit on the client's matchmaking request path, so this bounds the tail
# a dead peer can add to a fulfill.
FEDERATION_RPC_TIMEOUT_S = 2.0
# After a failed inter-node RPC the peer is skipped (steal order walks
# past it, wrong-node redirects are not issued toward it) for this long.
FEDERATION_PEER_BACKOFF_S = 3.0
# Client-side: after a refused dial or a failed redirect hop the client
# pins itself to whatever node answers (sends ``fed_pinned`` so servers
# skip redirects) for this long, preventing redirect ping-pong while the
# ring view is stale.
FEDERATION_CLIENT_PIN_S = 10.0
# After a remote-steal walk finds every peer empty, the remote leg sits
# out this long before walking again.  A starved federation otherwise
# pays a full ring of RPCs on EVERY unfulfilled matchmaking request —
# an RPC storm that throttles local throughput (~4x on loopback) while
# producing nothing.
FEDERATION_STEAL_COOLDOWN_S = 0.05

# --- replicated coordination metadata (net/serverstore.py ReplicatedServerStore,
# net/server.py /repl/*, docs/server.md §Replication; no reference equivalent) -
# Ring successors each partition's operation log ships to (the primary/
# backup chain).  A write's future resolves only after the log record is
# durable on the primary AND acked by at least one live successor, so 2
# keeps a replica margin: after one permanent node loss the promoted
# successor still ships to one live peer in a 3-node ring.
REPL_SUCCESSORS = 2
# Synchronous ship RPC (/repl/ship) timeout.  Shipping happens on the
# store's writer thread inside the group commit, so this bounds the
# latency a dead successor can add to a write batch before it is marked
# down and the batch proceeds degraded.
REPL_SHIP_TIMEOUT_S = 2.0
# Extra full-chain retry rounds when a shipped batch collects ZERO
# acks (serverstore.py _ship_tail).  Degraded mode (resolving write
# futures no successor holds) is the last resort, not the first
# response to one slow peer — each retry round ignores the ship-down
# backoff and waits REPL_SHIP_RETRY_BASE_S * 2^round before trying.
REPL_SHIP_RETRIES = 2
REPL_SHIP_RETRY_BASE_S = 0.2
# Forward/tail RPC deadline (net/server.py _repl_post).  Deliberately
# LOOSER than the federation RPC timeout: a forward's owner is the only
# correct target (there is no fallback peer to try), so a slow owner
# should mean a slow request, not a failed one — timeouts here surface
# as client-visible errors.  This bounds livelock, not latency.
REPL_FORWARD_TIMEOUT_S = 10.0
# Successor-side health probing of the primaries it backs: probe
# interval and the consecutive-failure count that (together with every
# more-senior chain member also being dead) triggers promotion.  The
# promote deadline seen by clients is roughly INTERVAL x FAILURES plus
# one replay.
REPL_PROBE_INTERVAL_S = 2.0
REPL_PROBE_FAILURES = 2

# --- server-side TTLs (reference server/src/client_auth_manager.rs:17-20) ---
AUTH_CHALLENGE_TTL_S = 30.0
SESSION_TTL_S = 24 * 3600.0
P2P_REQUEST_TTL_S = 60.0

# --- UI cadence (reference ws_status_message.rs:134-141, backup/mod.rs:112) -
PROGRESS_DEBOUNCE_S = 0.1
PEERS_DEBOUNCE_S = 0.25
PROGRESS_TICKER_S = 0.4

# --- TPU execution tunables (no reference equivalent) -----------------------
# Device block length for the gear-hash scan: streams are cut into blocks of
# this many bytes, sharded across devices with a GEAR_WINDOW-1 byte halo.
TPU_STREAM_BLOCK = 4 * MiB
# Leaf bucket sizes (in 1 KiB blake3 chunks) used when batching variable-size
# CDC chunks for fingerprinting; chunks are padded up to the nearest bucket.
BLAKE3_LEAF_BUCKETS = (16, 64, 256, 1024, 2048, 3072)
# Sharded dedup index: default capacity per device shard (slots) and probe cap.
DEDUP_SHARD_CAPACITY = 1 << 20
DEDUP_MAX_PROBES = 32

# --- tiered dedup index (dedupstore/, docs/dedup_tiering.md) -----------------
# Ceiling on HBM bytes the hot fingerprint table may occupy across the whole
# mesh: slots x 20 bytes (16-byte truncated key + u32 value) x n_devices.
# When an insert would force a 4x growth past this cap, the tiered index
# demotes cold fingerprints to the host LSM store instead of growing.
DEDUP_HBM_BUDGET_BYTES = 256 * MiB
# Cold-tier LSM store: memtable entries before a sorted run is committed,
# prefix-bucket count per run (first key word, top bits), and the size-tiered
# compaction fan-in (merge when a tier accumulates this many runs).
DEDUP_COLD_MEMTABLE_LIMIT = 1 << 16
DEDUP_COLD_BUCKETS = 256
DEDUP_COLD_COMPACT_FANIN = 4
# Promotion/demotion clock: one period every this many classify dispatch
# windows; cold fingerprints hit at least PROMOTE_MIN_HITS times within a
# period are promoted into the hot table.
DEDUP_TIER_CLOCK_WINDOWS = 8
DEDUP_TIER_PROMOTE_MIN_HITS = 2
