/* Native single-thread dedup pipeline: windowed-gear CDC + BLAKE3.
 *
 * This is the honest CPU baseline the device pipeline is measured against
 * (BASELINE.md: ">=10x CPU single-thread chunk+hash throughput"), playing
 * the role the SIMD `fastcdc` + `blake3` crates play in the reference
 * client (dir_packer.rs:246-311).  Semantics are normative per
 * backuwup_tpu/ops/CDC_SPEC.md and bit-identical to ops/cdc_cpu.py /
 * ops/blake3_cpu.py; parity is asserted by tests and by bench.py before
 * any timing is reported.
 *
 * BLAKE3 is implemented from the public specification (IV, message
 * permutation, flag values, tree structure); no third-party code.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

/* ---------------------------------------------------------------- gear -- */

#define GEAR_WINDOW 32
static uint32_t GEAR[256];
static int gear_ready = 0;

/* GEAR[b] = fmix32(GEAR_SEED32 + b), spec v2 (ops/gear.py). */
static void gear_init(void) {
    if (gear_ready) return;
    for (int i = 0; i < 256; i++) {
        uint32_t h = 0x6261636BU + (uint32_t)i;
        h ^= h >> 16;
        h *= 0x85EBCA6BU;
        h ^= h >> 13;
        h *= 0xC2B2AE35U;
        h ^= h >> 16;
        GEAR[i] = h;
    }
    gear_ready = 1;
}

/* Next inclusive cut end for the chunk starting at s (select_cuts rules:
 * window 1 = [s+min-1, s+desired-2] under mask_s, window 2 =
 * [s+desired-1, s+max-2] under mask_l, both capped at n-2; else forced at
 * s+max-1 or EOF).  The rolling hash h[i] depends only on bytes
 * [i-31, i], so the scan warms up over the 31 bytes before the first
 * eligible position instead of hashing the skipped min-size prefix. */
static size_t next_cut(const uint8_t *data, size_t n, size_t s,
                       uint64_t min_size, uint64_t desired, uint64_t max_size,
                       uint32_t mask_s, uint32_t mask_l) {
    if (n - s <= min_size) return n - 1;
    size_t start = s + min_size - 1; /* first eligible end position */
    uint32_t h = 0;
    size_t warm = start >= GEAR_WINDOW - 1 ? start - (GEAR_WINDOW - 1) : 0;
    for (size_t i = warm; i < start; i++)
        h = (h << 1) + GEAR[data[i]];
    size_t hi1 = s + desired - 2;
    if (hi1 > n - 2) hi1 = n - 2;
    size_t hi2 = s + max_size - 2;
    if (hi2 > n - 2) hi2 = n - 2;
    for (size_t i = start; i <= hi2; i++) {
        h = (h << 1) + GEAR[data[i]];
        if (i <= hi1) {
            if ((h & mask_s) == 0) return i;
        } else {
            if ((h & mask_l) == 0) return i;
        }
    }
    size_t forced = s + max_size - 1;
    return forced < n - 1 ? forced : n - 1;
}

/* -------------------------------------------------------------- blake3 -- */

#define CHUNK_LEN 1024
#define BLOCK_LEN 64
#define FLAG_CHUNK_START 1u
#define FLAG_CHUNK_END 2u
#define FLAG_PARENT 4u
#define FLAG_ROOT 8u

static const uint32_t B3_IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u};

static const uint8_t B3_PERM[16] = {2, 6,  3, 10, 7, 0,  4, 13,
                                    1, 11, 12, 5, 9, 14, 15, 8};

static inline uint32_t rotr32(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

#define G(a, b, c, d, mx, my)                \
    do {                                     \
        st[a] = st[a] + st[b] + (mx);        \
        st[d] = rotr32(st[d] ^ st[a], 16);   \
        st[c] = st[c] + st[d];               \
        st[b] = rotr32(st[b] ^ st[c], 12);   \
        st[a] = st[a] + st[b] + (my);        \
        st[d] = rotr32(st[d] ^ st[a], 8);    \
        st[c] = st[c] + st[d];               \
        st[b] = rotr32(st[b] ^ st[c], 7);    \
    } while (0)

static void compress(const uint32_t cv[8], const uint32_t block[16],
                     uint64_t counter, uint32_t block_len, uint32_t flags,
                     uint32_t out[8]) {
    uint32_t st[16];
    uint32_t m[16];
    memcpy(m, block, sizeof(m));
    memcpy(st, cv, 8 * sizeof(uint32_t));
    memcpy(st + 8, B3_IV, 4 * sizeof(uint32_t));
    st[12] = (uint32_t)counter;
    st[13] = (uint32_t)(counter >> 32);
    st[14] = block_len;
    st[15] = flags;
    for (int r = 0;; r++) {
        G(0, 4, 8, 12, m[0], m[1]);
        G(1, 5, 9, 13, m[2], m[3]);
        G(2, 6, 10, 14, m[4], m[5]);
        G(3, 7, 11, 15, m[6], m[7]);
        G(0, 5, 10, 15, m[8], m[9]);
        G(1, 6, 11, 12, m[10], m[11]);
        G(2, 7, 8, 13, m[12], m[13]);
        G(3, 4, 9, 14, m[14], m[15]);
        if (r == 6) break;
        uint32_t p[16];
        for (int i = 0; i < 16; i++) p[i] = m[B3_PERM[i]];
        memcpy(m, p, sizeof(m));
    }
    for (int i = 0; i < 8; i++) out[i] = st[i] ^ st[i + 8];
}

static void load_block(const uint8_t *p, size_t len, uint32_t block[16]) {
    uint8_t buf[BLOCK_LEN];
    const uint8_t *src = p;
    if (len < BLOCK_LEN) {
        memset(buf, 0, sizeof(buf));
        memcpy(buf, p, len);
        src = buf;
    }
    for (int i = 0; i < 16; i++)
        block[i] = (uint32_t)src[4 * i] | ((uint32_t)src[4 * i + 1] << 8) |
                   ((uint32_t)src[4 * i + 2] << 16) |
                   ((uint32_t)src[4 * i + 3] << 24);
}

/* Chaining value of one <=1024-byte leaf chunk. */
static void chunk_cv(const uint8_t *data, size_t len, uint64_t counter,
                     int root, uint32_t cv[8]) {
    size_t nblocks = len ? (len + BLOCK_LEN - 1) / BLOCK_LEN : 1;
    memcpy(cv, B3_IV, 8 * sizeof(uint32_t));
    for (size_t b = 0; b < nblocks; b++) {
        size_t off = b * BLOCK_LEN;
        size_t blen = len - off < BLOCK_LEN ? len - off : BLOCK_LEN;
        if (!len) blen = 0;
        uint32_t block[16];
        load_block(data + off, blen, block);
        uint32_t flags = 0;
        if (b == 0) flags |= FLAG_CHUNK_START;
        if (b == nblocks - 1) {
            flags |= FLAG_CHUNK_END;
            if (root) flags |= FLAG_ROOT;
        }
        uint32_t out[8];
        compress(cv, block, counter, (uint32_t)blen, flags, out);
        memcpy(cv, out, sizeof(out));
    }
}

static void parent_cv(const uint32_t l[8], const uint32_t r[8], int root,
                      uint32_t out[8]) {
    uint32_t block[16];
    memcpy(block, l, 8 * sizeof(uint32_t));
    memcpy(block + 8, r, 8 * sizeof(uint32_t));
    compress(B3_IV, block, 0, BLOCK_LEN,
             FLAG_PARENT | (root ? FLAG_ROOT : 0), out);
}

static uint64_t pow2_below(uint64_t n) { /* largest power of two < n */
    uint64_t p = 1;
    while (p * 2 < n) p *= 2;
    return p;
}

/* Subtree over whole chunks [c0, c0+count); ROOT never set here. */
static void subtree_cv(const uint8_t *data, size_t len, uint64_t c0,
                       uint64_t count, uint32_t cv[8]) {
    if (count == 1) {
        chunk_cv(data, len, c0, 0, cv);
        return;
    }
    uint64_t split = pow2_below(count);
    uint32_t l[8], r[8];
    subtree_cv(data, split * CHUNK_LEN, c0, split, l);
    subtree_cv(data + split * CHUNK_LEN, len - split * CHUNK_LEN, c0 + split,
               count - split, r);
    parent_cv(l, r, 0, cv);
}

void bkw_blake3(const uint8_t *data, size_t len, uint8_t out[32]) {
    uint32_t cv[8];
    uint64_t count = len ? (len + CHUNK_LEN - 1) / CHUNK_LEN : 1;
    if (count == 1) {
        chunk_cv(data, len, 0, 1, cv);
    } else {
        uint64_t split = pow2_below(count);
        uint32_t l[8], r[8];
        subtree_cv(data, split * CHUNK_LEN, 0, split, l);
        subtree_cv(data + split * CHUNK_LEN, len - split * CHUNK_LEN, split,
                   count - split, r);
        parent_cv(l, r, 1, cv);
    }
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)cv[i];
        out[4 * i + 1] = (uint8_t)(cv[i] >> 8);
        out[4 * i + 2] = (uint8_t)(cv[i] >> 16);
        out[4 * i + 3] = (uint8_t)(cv[i] >> 24);
    }
}

/* ------------------------------------------------------------ manifest -- */

/* Chunk only: fills offsets/lengths, returns chunk count (or -1 if cap is
 * too small). */
long bkw_chunk(const uint8_t *data, size_t n, uint64_t min_size,
               uint64_t desired, uint64_t max_size, uint32_t mask_s,
               uint32_t mask_l, uint64_t *offsets, uint64_t *lengths,
               size_t cap) {
    gear_init();
    long k = 0;
    size_t s = 0;
    while (s < n) {
        size_t e = next_cut(data, n, s, min_size, desired, max_size, mask_s,
                            mask_l);
        if ((size_t)k >= cap) return -1;
        offsets[k] = s;
        lengths[k] = e - s + 1;
        k++;
        s = e + 1;
    }
    return k;
}

/* Full single-thread pipeline: chunk + digest every chunk.  digests must
 * hold 32*cap bytes. */
long bkw_manifest(const uint8_t *data, size_t n, uint64_t min_size,
                  uint64_t desired, uint64_t max_size, uint32_t mask_s,
                  uint32_t mask_l, uint64_t *offsets, uint64_t *lengths,
                  uint8_t *digests, size_t cap) {
    long k = bkw_chunk(data, n, min_size, desired, max_size, mask_s, mask_l,
                       offsets, lengths, cap);
    if (k < 0) return k;
    for (long i = 0; i < k; i++)
        bkw_blake3(data + offsets[i], lengths[i], digests + 32 * i);
    return k;
}
