"""ctypes binding for the native single-thread dedup pipeline.

``libbkw_native.so`` (built by the Makefile here) plays the role of the
reference's native `fastcdc` + SIMD `blake3` crates
(``dir_packer.rs:246-311``): the honest single-thread CPU baseline for the
device pipeline's throughput target, and a fast host fallback.  The library
is built on first import when a C compiler is available.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

_DIR = Path(__file__).resolve().parent
_LIB = _DIR / "libbkw_native.so"


class NativeUnavailable(RuntimeError):
    pass


_lib: Optional[ctypes.CDLL] = None


def _build() -> None:
    subprocess.run(["make", "-C", str(_DIR), "-s"], check=True,
                   capture_output=True)


def _stale() -> bool:
    if not _LIB.exists():
        return True
    mtime = _LIB.stat().st_mtime
    return any(src.stat().st_mtime > mtime
               for src in (_DIR / "cdc_blake3.c", _DIR / "Makefile")
               if src.exists())


def load() -> ctypes.CDLL:
    """Load (building if missing or stale) the native library; raises
    :class:`NativeUnavailable` when no compiler/library exists."""
    global _lib
    if _lib is not None:
        return _lib
    if _stale():
        try:
            _build()
        except (OSError, subprocess.CalledProcessError) as e:
            if not _LIB.exists():
                raise NativeUnavailable(f"cannot build native library: {e}")
            logging.getLogger(__name__).warning(
                "native library is stale and rebuild failed (%s); "
                "loading the outdated binary", e)
    lib = ctypes.CDLL(str(_LIB))
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.bkw_blake3.argtypes = [u8p, ctypes.c_size_t, u8p]
    lib.bkw_blake3.restype = None
    common = [u8p, ctypes.c_size_t, ctypes.c_uint64, ctypes.c_uint64,
              ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32, u64p, u64p]
    lib.bkw_chunk.argtypes = common + [ctypes.c_size_t]
    lib.bkw_chunk.restype = ctypes.c_long
    lib.bkw_manifest.argtypes = common + [u8p, ctypes.c_size_t]
    lib.bkw_manifest.restype = ctypes.c_long
    _lib = lib
    return lib


def available() -> bool:
    try:
        load()
        return True
    except NativeUnavailable:
        return False


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def blake3_native(data: bytes) -> bytes:
    lib = load()
    arr = np.frombuffer(bytes(data), dtype=np.uint8)
    out = np.zeros(32, dtype=np.uint8)
    lib.bkw_blake3(_u8(arr) if len(arr) else _u8(out), len(arr), _u8(out))
    return out.tobytes()


def _cap(n: int, min_size: int) -> int:
    return max(4, n // max(min_size, 1) + 2)


def chunk_native(data, params) -> List[Tuple[int, int]]:
    """Chunk one stream; bit-identical to ops.cdc_cpu.chunk_stream."""
    lib = load()
    arr = np.frombuffer(bytes(data), dtype=np.uint8)
    cap = _cap(len(arr), params.min_size)
    offs = np.zeros(cap, dtype=np.uint64)
    lens = np.zeros(cap, dtype=np.uint64)
    k = lib.bkw_chunk(
        _u8(arr) if len(arr) else _u8(offs.view(np.uint8)), len(arr),
        params.min_size, params.desired_size, params.max_size,
        params.mask_s, params.mask_l,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), cap)
    if k < 0:
        raise RuntimeError("native chunk capacity overflow")
    return [(int(offs[i]), int(lens[i])) for i in range(k)]


def manifest_native(data, params):
    """Chunk + digest one stream single-threaded; returns
    (chunks, digests-bytes-list)."""
    lib = load()
    arr = np.frombuffer(bytes(data), dtype=np.uint8)
    cap = _cap(len(arr), params.min_size)
    offs = np.zeros(cap, dtype=np.uint64)
    lens = np.zeros(cap, dtype=np.uint64)
    digs = np.zeros(cap * 32, dtype=np.uint8)
    k = lib.bkw_manifest(
        _u8(arr) if len(arr) else _u8(digs), len(arr),
        params.min_size, params.desired_size, params.max_size,
        params.mask_s, params.mask_l,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        _u8(digs), cap)
    if k < 0:
        raise RuntimeError("native manifest capacity overflow")
    chunks = [(int(offs[i]), int(lens[i])) for i in range(k)]
    digests = [digs[32 * i:32 * (i + 1)].tobytes() for i in range(k)]
    return chunks, digests
